"""Import checkpoints saved by the reference (DeepSpeed) into this framework.

Migration path for users switching from the reference: their training runs
left behind DeepSpeed checkpoint directories, and those weights should load
here without a detour through torch.

Two on-disk formats are supported (both documented in SURVEY.md §5
"Checkpoint / resume"; format details verified against the reference's
writer, runtime/engine.py:3197–3261 and checkpoint/ds_to_universal.py:469):

1. **Engine checkpoints** — ``<dir>/<tag>/mp_rank_00_model_states.pt``
   written by ``engine.save_checkpoint``. The ``module`` entry is the
   wrapped model's own ``state_dict()``; for HF models that means HF tensor
   names, so the mapping into our pytree is exactly the HF-interop mapping
   (`models/hf_loader.params_from_state`). The optional ``latest`` file at
   the directory root names the tag.
2. **Universal checkpoints (UCP)** — ``<dir>/<tag>/zero/<param_name>/fp32.pt``
   per-parameter fp32 fragments produced by ``ds_to_universal.py``. Param
   names are again module state-dict names, so the same mapping applies.

Scope, by design:
- Model-parallel (``mp_rank_01+``) shards are rejected with instructions to
  consolidate first (the reference's own migration guidance); TP resharding
  happens on OUR side via `module_inject/auto_tp.py` partition specs after
  the full-shape weights are loaded — the AutoTP analogue shards pytrees,
  not files.
- ZeRO optimizer shards (``zero_pp_rank_*``/``bf16_zero_*``) hold flat
  1-D partitions whose layout is private to the reference's optimizer; the
  reference itself converts them via ``ds_to_universal`` — import that
  output (format 2) instead. Optimizer state is rebuilt fresh here (the
  moments live in a different, sharding-aware layout).

Requires torch (CPU) to deserialize ``.pt`` files; gated at call time.
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import DecoderConfig
from deepspeed_tpu.models.hf_loader import config_from_hf, params_from_state
from deepspeed_tpu.utils.logging import logger

Params = Any


def _torch():
    try:
        import torch
    except ImportError as exc:                       # pragma: no cover
        raise RuntimeError(
            "importing DeepSpeed .pt checkpoints requires torch "
            "(CPU build is enough)") from exc
    return torch


def resolve_tag(ckpt_dir: str, tag: Optional[str] = None) -> str:
    """Tag resolution mirroring the reference's ``latest`` convention."""
    if tag is not None:
        return tag
    from deepspeed_tpu.checkpoint.store import latest_tag
    latest = latest_tag(ckpt_dir)
    if latest is not None:
        return latest
    # single-subdir checkpoint dirs are unambiguous
    subs = [d for d in sorted(os.listdir(ckpt_dir))
            if os.path.isdir(os.path.join(ckpt_dir, d))]
    if len(subs) == 1:
        return subs[0]
    raise ValueError(
        f"cannot resolve checkpoint tag in {ckpt_dir}: no 'latest' file "
        f"and {len(subs)} candidate subdirectories {subs}")


def _strip_prefixes(sd: Dict[str, Any]) -> Dict[str, Any]:
    """Strip wrapper prefixes ('module.', DDP-style) off state-dict keys."""
    for prefix in ("module.", "model.module."):
        if all(k.startswith(prefix) for k in sd):
            sd = {k[len(prefix):]: v for k, v in sd.items()}
    return sd


def _state_reader(sd: Dict[str, Any]):
    """(get, names) view over a torch state dict, matching _reader()."""
    def get(name: str) -> np.ndarray:
        t = sd[name]
        if hasattr(t, "detach"):
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)
    return get, set(sd.keys())


def load_ds_checkpoint(ckpt_dir: str, hf_config: Dict[str, Any],
                       tag: Optional[str] = None, dtype=np.float32
                       ) -> Tuple[DecoderConfig, Params]:
    """Load a reference engine checkpoint into (DecoderConfig, params).

    ``hf_config`` is the HF ``config.json`` dict of the wrapped model (the
    reference does not checkpoint the model config — users keep it next to
    the weights; same requirement here).
    """
    torch = _torch()
    tag = resolve_tag(ckpt_dir, tag)
    path = os.path.join(ckpt_dir, tag, "mp_rank_00_model_states.pt")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no model states at {path}")
    other = os.path.join(ckpt_dir, tag, "mp_rank_01_model_states.pt")
    if os.path.exists(other):
        raise ValueError(
            f"{ckpt_dir} is a model-parallel checkpoint ({other} "
            "exists). Consolidate it first (reference: "
            "ds_to_universal.py merges TP slices), then import the "
            "universal checkpoint via load_universal_checkpoint().")
    blob = torch.load(path, map_location="cpu", weights_only=False)
    sd = blob.get("module", blob)
    if not isinstance(sd, dict):                     # pragma: no cover
        raise ValueError(f"unexpected model-states payload in {path}")
    sd = _strip_prefixes(sd)
    # ZeRO-3 model states saved without gather_16bit_weights hold 0-size
    # placeholders (params live in the zero_pp_rank_* optimizer shards) —
    # fail fast instead of stacking empty arrays into a garbage pytree
    if any(getattr(t, "numel", lambda: 1)() == 0 for t in sd.values()):
        raise ValueError(
            f"{path} holds ZeRO-3 placeholder (0-size) tensors — the "
            "weights live in the zero_pp_rank_* shards. Re-save with "
            "stage3_gather_16bit_weights_on_model_save, or convert with "
            "the reference's ds_to_universal.py / zero_to_fp32.py and "
            "import via load_universal_checkpoint().")
    cfg = config_from_hf(hf_config)
    get, names = _state_reader(sd)
    params = params_from_state(cfg, hf_config, get, names, dtype)
    logger.info(f"imported DeepSpeed checkpoint {ckpt_dir}@{tag}: "
                f"{cfg.num_params() / 1e6:.1f}M params")
    return cfg, params


def load_universal_checkpoint(ckpt_dir: str, hf_config: Dict[str, Any],
                              tag: Optional[str] = None, dtype=np.float32
                              ) -> Tuple[DecoderConfig, Params]:
    """Load a reference *universal* checkpoint (ds_to_universal output).

    Layout: ``<dir>/<tag>/zero/<param_name>/fp32.pt`` holds the merged
    full-shape fp32 weight per parameter (reference
    checkpoint/ds_to_universal.py: `merge_tp_slices`:232 writes one file
    per param). Optimizer-state fragments (``exp_avg.pt`` …) are ignored —
    moments are rebuilt in this framework's sharding-aware layout.
    """
    torch = _torch()
    tag = resolve_tag(ckpt_dir, tag)
    zero_dir = os.path.join(ckpt_dir, tag, "zero")
    if not os.path.isdir(zero_dir):
        raise FileNotFoundError(f"no universal-checkpoint dir at {zero_dir}")

    def get(name: str) -> np.ndarray:
        # no caching: each param is read exactly once by params_from_state,
        # and holding fp32 copies would double peak host RAM at 70B scale
        t = torch.load(os.path.join(zero_dir, name, "fp32.pt"),
                       map_location="cpu", weights_only=False)
        if isinstance(t, dict):                      # {'param': tensor} form
            t = t.get("param", t)
        return t.detach().float().numpy()

    names = {d for d in os.listdir(zero_dir)
             if os.path.exists(os.path.join(zero_dir, d, "fp32.pt"))}
    # param dirs may carry the 'module.' prefix; normalize both views
    if names and all(n.startswith("module.") for n in names):
        raw_get = get

        def get(name):                               # noqa: F811
            return raw_get("module." + name)
        names = {n[len("module."):] for n in names}
    cfg = config_from_hf(hf_config)
    params = params_from_state(cfg, hf_config, get, names, dtype)
    logger.info(f"imported universal checkpoint {ckpt_dir}@{tag}: "
                f"{cfg.num_params() / 1e6:.1f}M params")
    return cfg, params
