"""NVMe perf tools (reference: deepspeed/nvme/ — ds_io / ds_nvme_tune)."""

from deepspeed_tpu.nvme.perf import run_sweep, sweep_config_space

__all__ = ["run_sweep", "sweep_config_space"]
