"""NVMe I/O benchmark sweep.

Reference: ``deepspeed/nvme/perf_run_sweep.py`` + ``bin/ds_io`` /
``bin/ds_nvme_tune`` — sweep (threads × block size × queue depth) over the
aio engine, report read/write GB/s, recommend the best config for
ZeRO-Infinity's swap path. Here the engine under test is the C++
AsyncIOEngine (csrc/async_io.cpp) that runtime/zero/infinity.py uses, so
the number this reports is exactly the bandwidth the optimizer sweep will
see.
"""

import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.io.async_io import AsyncIOEngine


def _bench_one(path: str, total_mb: int, block_kb: int, threads: int,
               read: bool) -> float:
    """One (threads, block) point → GB/s."""
    eng = AsyncIOEngine(num_threads=threads)
    block = block_kb * 1024 // 4                  # fp32 elements
    total = total_mb * 1024 * 1024 // 4
    buf = np.random.default_rng(0).random(block).astype(np.float32)
    if read:
        # populate the file first
        for off in range(0, total, block):
            eng.pwrite(path, buf, off * 4)
        eng.drain()
    out = np.empty(block, np.float32)
    t0 = time.perf_counter()
    for off in range(0, total, block):
        if read:
            eng.pread(path, out, off * 4)
        else:
            eng.pwrite(path, buf, off * 4)
    eng.drain()
    dt = time.perf_counter() - t0
    return (total * 4 / 1e9) / dt


def sweep_config_space(threads: List[int] = (1, 2, 4, 8),
                       block_kb: List[int] = (256, 1024, 4096)
                       ) -> List[Dict]:
    return [{"threads": t, "block_kb": b} for t in threads
            for b in block_kb]


def run_sweep(nvme_dir: str, total_mb: int = 64,
              configs: Optional[List[Dict]] = None,
              results_path: Optional[str] = None) -> Dict:
    """Sweep read+write bandwidth; returns
    {"results": [...], "best_read": cfg, "best_write": cfg}
    (reference ds_nvme_tune output shape)."""
    os.makedirs(nvme_dir, exist_ok=True)
    path = os.path.join(nvme_dir, "ds_io_bench.bin")
    configs = configs or sweep_config_space()
    results = []
    for cfg in configs:
        wr = _bench_one(path, total_mb, cfg["block_kb"], cfg["threads"],
                        read=False)
        rd = _bench_one(path, total_mb, cfg["block_kb"], cfg["threads"],
                        read=True)
        results.append({**cfg, "write_gbps": wr, "read_gbps": rd})
    out = {
        "results": results,
        "best_read": max(results, key=lambda r: r["read_gbps"]),
        "best_write": max(results, key=lambda r: r["write_gbps"]),
    }
    if results_path:
        with open(results_path, "w") as fh:
            json.dump(out, fh, indent=1)
    try:
        os.remove(path)
    except OSError:
        pass
    return out
