"""Async NVMe/disk I/O — Python wrapper over csrc/async_io.cpp.

Reference: the DeepNVMe stack (``csrc/aio/py_lib/py_ds_aio.cpp``,
``ops/aio``, ``deepspeed/io/fast_file_writer.py``). Serves ZeRO-Infinity
tensor swapping and fast checkpointing: submit non-blocking reads/writes
of numpy buffers against files, overlap with compute, drain at a barrier.
Falls back to a synchronous Python implementation without a toolchain.
"""

import os
import tempfile
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import is_native_available, load_async_io


def atomic_write(path: str, data: bytes, durable: bool = True) -> None:
    """Write ``data`` to ``path`` atomically: tmp file + fsync + rename.

    Readers never observe a torn file — they see either the old contents or
    the complete new contents. With ``durable`` the file (and, best-effort,
    its directory entry) are fsync'd before the rename so a crash cannot
    leave a renamed-but-empty file. Shared by the checkpoint fragment store
    and the KV-tier NVMe spill path.
    """
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory,
                               prefix="." + os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            if durable:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable:
        try:
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # some filesystems refuse directory fsync


def pread_retry(path: str, size: int = -1, offset: int = 0,
                retries: int = 3, backoff_s: float = 0.05,
                _open: Callable = open) -> bytes:
    """Read ``size`` bytes at ``offset`` with bounded retry on transient errors.

    Retries ``OSError`` with exponential backoff up to ``retries`` attempts;
    a missing file is not transient and surfaces immediately so callers can
    map it to their own corruption/miss handling. Shared by the checkpoint
    fragment reader and the KV-tier NVMe load path.
    """
    attempt = 0
    while True:
        try:
            with _open(path, "rb") as fh:
                if offset:
                    fh.seek(offset)
                return fh.read() if size < 0 else fh.read(size)
        except FileNotFoundError:
            raise
        except OSError:
            attempt += 1
            if attempt > retries:
                raise
            time.sleep(backoff_s * (2 ** (attempt - 1)))


class AsyncIOEngine:
    def __init__(self, num_threads: int = 4, o_direct: bool = False,
                 use_native: Optional[bool] = None):
        if use_native is None:
            use_native = is_native_available()
        self._native = None
        self._fallback_jobs = []
        if use_native:
            self._lib = load_async_io()
            self._native = self._lib.ds_aio_create(num_threads,
                                                   1 if o_direct else 0)
        #: keep submitted buffers alive until drain (the C engine reads
        #: from the raw pointers)
        self._pinned: Dict[int, np.ndarray] = {}
        self._next = 0

    def __del__(self):
        try:
            if self._native is not None:
                self._lib.ds_aio_destroy(self._native)
        except Exception:
            pass

    def _track(self, buf: np.ndarray) -> int:
        self._next += 1
        self._pinned[self._next] = buf
        return self._next

    def pwrite(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        buf = np.ascontiguousarray(buf)
        tid = self._track(buf)
        if self._native is not None:
            self._lib.ds_aio_pwrite(self._native, path.encode(),
                                    buf.ctypes.data, buf.nbytes, offset)
        else:
            t = threading.Thread(target=self._sync_write,
                                 args=(path, buf, offset))
            t.start()
            self._fallback_jobs.append(t)
        return tid

    def pread(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        tid = self._track(buf)
        if self._native is not None:
            self._lib.ds_aio_pread(self._native, path.encode(),
                                   buf.ctypes.data, buf.nbytes, offset)
        else:
            t = threading.Thread(target=self._sync_read,
                                 args=(path, buf, offset))
            t.start()
            self._fallback_jobs.append(t)
        return tid

    @staticmethod
    def _sync_write(path: str, buf: np.ndarray, offset: int) -> None:
        with open(path, "r+b" if os.path.exists(path) else "wb") as fh:
            fh.seek(offset)
            fh.write(buf.tobytes())

    @staticmethod
    def _sync_read(path: str, buf: np.ndarray, offset: int) -> None:
        with open(path, "rb") as fh:
            fh.seek(offset)
            data = fh.read(buf.nbytes)
        buf[...] = np.frombuffer(data, dtype=buf.dtype).reshape(buf.shape)

    def drain(self) -> int:
        """Block until all in-flight ops complete; returns error count."""
        if self._native is not None:
            errs = int(self._lib.ds_aio_drain(self._native))
        else:
            for t in self._fallback_jobs:
                t.join()
            self._fallback_jobs.clear()
            errs = 0
        self._pinned.clear()
        return errs
