from deepspeed_tpu.io.async_io import AsyncIOEngine  # noqa: F401
