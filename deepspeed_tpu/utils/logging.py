"""Rank-aware logging for deepspeed_tpu.

Equivalent of reference ``deepspeed/utils/logging.py`` (``log_dist``,
``logger``): a process-wide logger whose helpers filter by jax process index
so multi-host TPU pods don't emit world_size copies of every line.
"""

import functools
import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVEL = os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper()


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu") -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(getattr(logging, LOG_LEVEL, logging.INFO))
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(
        logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
    lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index() -> int:
    # Avoid importing jax at module load (tests set env vars first).
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None,
             level: int = logging.INFO) -> None:
    """Log only on the given process ranks (default: rank 0).

    Reference: deepspeed/utils/logging.py:log_dist.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if my_rank in ranks or -1 in ranks:
        logger.log(level, "[Rank %s] %s", my_rank, message)


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen=set()) -> None:
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
