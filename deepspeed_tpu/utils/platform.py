"""Platform-selection workaround shared by CLIs and tools.

The axon TPU tunnel's sitecustomize hook force-registers its plugin and
programmatically overrides ``JAX_PLATFORMS`` after env processing; jax's
config knob wins over the hook, so tools that want to honor the user's
env choice (e.g. ``JAX_PLATFORMS=cpu`` for a virtual-device run) must
re-assert it before backend init. ``tests/conftest.py`` applies the same
workaround for the unit suite.
"""

import os


def sync_jax_platform_env() -> None:
    """Re-assert the JAX_PLATFORMS env var via jax.config (hook-proof)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax
        jax.config.update("jax_platforms", platforms)
