from deepspeed_tpu.utils.logging import log_dist, logger, print_rank_0
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "print_rank_0",
           "SynchronizedWallClockTimer", "ThroughputTimer"]


def see_memory_usage(message: str, force: bool = False) -> None:
    """Reference deepspeed/utils see_memory_usage: log device memory
    telemetry at checkpoints in the code. TPU numbers come from the
    accelerator L0 memory_stats (device HBM via PJRT)."""
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.utils.logging import logger
    if not force:
        return
    stats = get_accelerator().memory_stats() or {}
    used = stats.get("bytes_in_use", stats.get("bytes_used", 0))
    peak = stats.get("peak_bytes_in_use", used)
    limit = stats.get("bytes_limit", 0)
    logger.info(
        f"{message} | HBM used {used / 2**30:.2f} GiB "
        f"(peak {peak / 2**30:.2f}"
        + (f" / limit {limit / 2**30:.2f}" if limit else "") + " GiB)")
