from deepspeed_tpu.utils.logging import log_dist, logger, print_rank_0
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "print_rank_0",
           "SynchronizedWallClockTimer", "ThroughputTimer"]
