"""JAX version-compatibility shims.

The framework targets the stable ``jax.shard_map`` spelling (jax >= 0.5
moved it out of ``jax.experimental`` and renamed ``check_rep`` →
``check_vma``, ``auto`` → its complement ``axis_names``). On older
jaxlibs, :func:`install` aliases an adapter under ``jax.shard_map`` that
translates the new keyword surface to the experimental one, so every call
site (and tests) can use one spelling regardless of the installed jax.
"""

import inspect
import os

import jax


def install() -> None:
    if os.environ.get("DSTPU_NO_JAX_COMPAT"):     # escape hatch
        return
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in inspect.signature(_sm).parameters:
        jax.shard_map = _sm
        return

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        if axis_names is not None and mesh is not None:
            # new API names the MANUAL axes; old API names the AUTO rest
            kw.setdefault("auto", frozenset(mesh.axis_names) -
                          frozenset(axis_names))
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)

    jax.shard_map = shard_map
