"""Wall-clock + throughput timers.

Equivalent of reference ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer``:44, ``ThroughputTimer``:199). On TPU,
"synchronized" means block_until_ready on a device array rather than a CUDA
event pair; under jit the engine only times at step granularity to avoid
breaking async dispatch.
"""

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

try:
    import psutil
    _PSUTIL = True
except Exception:  # pragma: no cover
    _PSUTIL = False


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self.records: List[float] = []

    def start(self) -> None:
        if self.started:
            raise RuntimeError(f"timer {self.name} already started")
        self._start = time.perf_counter()
        self.started = True

    def stop(self, record: bool = True) -> None:
        if not self.started:
            raise RuntimeError(f"timer {self.name} not started")
        end = time.perf_counter()
        delta = end - self._start
        self._elapsed += delta
        if record:
            self.records.append(delta)
        self.started = False
        # mirror every stop into the trace (no-op while tracing is off)
        from deepspeed_tpu.telemetry import tracer
        tracer.complete(f"timer/{self.name}", self._start, end)

    def reset(self) -> None:
        """Clear ALL accumulated state — elapsed, records, and any
        in-flight start (a reset mid-window must not leave a stale
        ``started`` that makes the next ``start()`` raise)."""
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self.records.clear()

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed time in seconds since last reset (0.0 when the timer
        never ran). A running timer is sampled without losing the window:
        stop(record=False) + immediate restart."""
        if self.started:
            self.stop(record=False)
            self.start()
        value = self._elapsed
        if reset:
            self._elapsed = 0.0
        return value

    def mean(self) -> float:
        """Mean of recorded stop() intervals; 0.0 with no records."""
        return sum(self.records) / len(self.records) if self.records else 0.0


class SynchronizedWallClockTimer:
    """Named timer registry (reference utils/timer.py:44)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True, ranks: Optional[List[int]] = None) -> None:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    @staticmethod
    def memory_usage() -> str:
        if not _PSUTIL:
            return "mem: n/a"
        vm = psutil.virtual_memory()
        return f"host mem used: {vm.used / 2**30:.2f} GB ({vm.percent}%)"


class ThroughputTimer:
    """Samples/sec + TFLOPs tracking (reference utils/timer.py:199)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self._start = 0.0
        self.started = False

    def start(self) -> None:
        self.started = True
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True,
             sync=None) -> None:
        """``sync`` — a device array (e.g. the step loss) to block on before
        reading the clock. Without it the timer measures only async-dispatch
        latency, not step latency (the round-1 bug: "3519 samples/s" printed
        for a ~1 s/step run)."""
        if not self.started:
            return
        will_report = (self.steps_per_output and
                       (self.global_step_count + 1) % self.steps_per_output == 0)
        if sync is not None and will_report:
            # block only on reporting steps: a per-step sync would stall the
            # async dispatch pipeline (and adds a host round-trip per step).
            # Scalars are FETCHED, not blocked on — remote runtimes (e.g.
            # the axon tunnel) only execute on fetch, so block_until_ready
            # there would time dispatch, not the step
            import jax
            if getattr(sync, "size", 0) == 1:
                jax.device_get(sync)
            else:
                jax.block_until_ready(sync)
        self.started = False
        if global_step:
            self.global_step_count += 1
        duration = time.perf_counter() - self._start
        if self.global_step_count > self.start_step:
            self.total_elapsed_time += duration
            if report_speed and self.steps_per_output and \
                    self.global_step_count % self.steps_per_output == 0:
                log_dist(
                    f"step={self.global_step_count}, "
                    f"throughput={self.avg_samples_per_sec():.2f} samples/s, "
                    f"latency={duration:.3f} s")

    def avg_samples_per_sec(self) -> float:
        steps = self.global_step_count - self.start_step
        if steps > 0 and self.total_elapsed_time > 0:
            return self.batch_size * steps / self.total_elapsed_time
        return 0.0
