"""Pytree path utilities shared by compression / AutoTP / debug tooling
(reference analogue: the module-name walks in module_inject and
compression both key layers by dotted module paths)."""

from typing import Any, Iterator, Tuple

import jax


def path_key(path) -> str:
    """Canonical '/'-joined string for a tree_flatten_with_path path —
    the ONE place the key format lives (DictKey/SequenceKey/attr names)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def leaf_items(params: Any) -> Iterator[Tuple[str, Any]]:
    """(path_key, leaf) pairs of a pytree."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        yield path_key(path), leaf
