"""TPU-native optimizer library.

Replaces the reference's fused/CPU optimizer kernels
(csrc/adam/multi_tensor_adam.cu + ops/adam/fused_adam.py:18,
csrc/lamb/fused_lamb_cuda.cu + ops/lamb/fused_lamb.py:14,
csrc/lion/multi_tensor_lion.cu + ops/lion/fused_lion.py:17,
csrc/adagrad/cpu_adagrad.cpp, runtime/zero/muon/muon_optimizer.py:14).

Design: each optimizer is an ``Optimizer(init, update)`` pair over a pytree
of parameters. ``update`` consumes grads and a scalar ``lr`` and returns the
*new params* plus new state — not optax-style "updates" — because mixed
precision is first-class: when params are bf16, the state carries an fp32
master copy (the reference's flat fp32 partitions,
runtime/bf16_optimizer.py:35) and the math runs on the master, with a cast
back to the compute dtype at the end. XLA fuses the whole sweep into a few
elementwise kernels over each buffer — the multi-tensor-apply machinery of
the CUDA path is unnecessary.

Everything here is jit-compatible and shape-polymorphic over the pytree, so
the same code runs replicated (ZeRO-0), with sharded state (ZeRO-1/2), or
fully sharded (ZeRO-3) purely by virtue of the shardings the engine installs
on ``state``.
"""

import math
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Any
OptState = Dict[str, Any]


class Optimizer(NamedTuple):
    init: Callable[[Params], OptState]
    update: Callable[[Params, OptState, Params, jax.Array], Tuple[Params, OptState]]
    #: static metadata (name, hyperparams) for checkpointing
    hyperparams: Dict[str, Any]


def _to_f32(t):
    return jax.tree.map(lambda x: x.astype(jnp.float32), t)


def _needs_master(params) -> bool:
    return any(x.dtype != jnp.float32 for x in jax.tree.leaves(params))


def _zeros_like_f32(params):
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def _get_master(state: OptState, params: Params) -> Params:
    """fp32 view of the weights: master copy if present, else params."""
    return state["master"] if "master" in state else params


def _finish(state: OptState, new_master: Params, params: Params,
            new_inner: Dict[str, Any]) -> Tuple[Params, OptState]:
    """Cast master back to compute dtype and rebuild state."""
    new_params = jax.tree.map(
        lambda m, p: m.astype(p.dtype), new_master, params)
    out = dict(state)
    out.update(new_inner)
    if "master" in state:
        out["master"] = new_master
    return new_params, out


# ---------------------------------------------------------------------------
# Adam / AdamW  (reference ops/adam/fused_adam.py:18 — adam_w_mode flag)
# ---------------------------------------------------------------------------

def adam(beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0, adam_w_mode: bool = True,
         bias_correction: bool = True, state_dtype: Any = None,
         master_weights: bool = True) -> Optimizer:
    """``state_dtype``/``master_weights`` are the TPU analogue of the
    reference's reduced-precision optimizer memory knobs
    (``fp16_master_weights_and_gradients``, stage_1_and_2.py:159): moments
    stored in ``state_dtype`` (default fp32), and ``master_weights=False``
    drops the fp32 master so bf16 params update in-place — 8 bytes/param
    instead of 14, the config that fits a >1B model on one 16G v5e. The
    update math always runs in fp32 regardless of storage dtype."""
    state_dtype = jnp.float32 if state_dtype is None else \
        jnp.dtype(state_dtype)
    hp = dict(name="adamw" if adam_w_mode else "adam", beta1=beta1,
              beta2=beta2, eps=eps, weight_decay=weight_decay,
              adam_w_mode=adam_w_mode, bias_correction=bias_correction,
              state_dtype=str(state_dtype), master_weights=master_weights)

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, state_dtype)
        state = {"step": jnp.zeros((), jnp.int32),
                 "exp_avg": jax.tree.map(zeros, params),
                 "exp_avg_sq": jax.tree.map(zeros, params)}
        if master_weights and _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(m, v, g, p):
            m32, v32 = m.astype(jnp.float32), v.astype(jnp.float32)
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay and not adam_w_mode:
                g = g + weight_decay * p32
            m32 = beta1 * m32 + (1 - beta1) * g
            v32 = beta2 * v32 + (1 - beta2) * (g * g)
            mhat = m32 / bc1
            vhat = v32 / bc2
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay and adam_w_mode:
                upd = upd + weight_decay * p32
            # p is the fp32 master when one exists, else the param itself —
            # either way the stored dtype is p.dtype
            return (m32.astype(state_dtype), v32.astype(state_dtype),
                    (p32 - lr * upd).astype(p.dtype))

        flat = jax.tree.map(leaf, state["exp_avg"], state["exp_avg_sq"],
                            grads, master)
        new_m = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_master = jax.tree.map(lambda t: t[2], flat,
                                  is_leaf=lambda t: isinstance(t, tuple))
        return _finish(state, new_master, params,
                       {"step": step, "exp_avg": new_m, "exp_avg_sq": new_v})

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# LAMB  (reference ops/lamb/fused_lamb.py:14 — layerwise trust ratio)
# ---------------------------------------------------------------------------

def lamb(beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-6,
         weight_decay: float = 0.0, max_coeff: float = 10.0,
         min_coeff: float = 0.01) -> Optimizer:
    hp = dict(name="lamb", beta1=beta1, beta2=beta2, eps=eps,
              weight_decay=weight_decay, max_coeff=max_coeff,
              min_coeff=min_coeff)

    def init(params):
        state = {"step": jnp.zeros((), jnp.int32),
                 "exp_avg": _zeros_like_f32(params),
                 "exp_avg_sq": _zeros_like_f32(params)}
        if _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        g32 = _to_f32(grads)

        def leaf(m, v, g, p):
            m = beta1 * m + (1 - beta1) * g
            v = beta2 * v + (1 - beta2) * (g * g)
            upd = m / (jnp.sqrt(v) + eps)
            if weight_decay:
                upd = upd + weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(upd.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            return m, v, p - lr * trust * upd

        flat = jax.tree.map(leaf, state["exp_avg"], state["exp_avg_sq"],
                            g32, master)
        new_m = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_master = jax.tree.map(lambda t: t[2], flat,
                                  is_leaf=lambda t: isinstance(t, tuple))
        return _finish(state, new_master, params,
                       {"step": step, "exp_avg": new_m, "exp_avg_sq": new_v})

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# Lion  (reference ops/lion/fused_lion.py:17)
# ---------------------------------------------------------------------------

def lion(beta1: float = 0.9, beta2: float = 0.99,
         weight_decay: float = 0.0) -> Optimizer:
    hp = dict(name="lion", beta1=beta1, beta2=beta2,
              weight_decay=weight_decay)

    def init(params):
        state = {"step": jnp.zeros((), jnp.int32),
                 "exp_avg": _zeros_like_f32(params)}
        if _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        g32 = _to_f32(grads)

        def leaf(m, g, p):
            upd = jnp.sign(beta1 * m + (1 - beta1) * g)
            if weight_decay:
                p = p * (1 - lr * weight_decay)
            m = beta2 * m + (1 - beta2) * g
            return m, p - lr * upd

        flat = jax.tree.map(leaf, state["exp_avg"], g32, master)
        new_m = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_master = jax.tree.map(lambda t: t[1], flat,
                                  is_leaf=lambda t: isinstance(t, tuple))
        return _finish(state, new_master, params,
                       {"step": step, "exp_avg": new_m})

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# Adagrad  (reference csrc/adagrad/cpu_adagrad.cpp)
# ---------------------------------------------------------------------------

def adagrad(eps: float = 1e-10, weight_decay: float = 0.0) -> Optimizer:
    hp = dict(name="adagrad", eps=eps, weight_decay=weight_decay)

    def init(params):
        state = {"step": jnp.zeros((), jnp.int32),
                 "sum_sq": _zeros_like_f32(params)}
        if _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        g32 = _to_f32(grads)

        def leaf(s, g, p):
            if weight_decay:
                g = g + weight_decay * p
            s = s + g * g
            return s, p - lr * g / (jnp.sqrt(s) + eps)

        flat = jax.tree.map(leaf, state["sum_sq"], g32, master)
        new_s = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_master = jax.tree.map(lambda t: t[1], flat,
                                  is_leaf=lambda t: isinstance(t, tuple))
        return _finish(state, new_master, params,
                       {"step": step, "sum_sq": new_s})

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# SGD (+momentum) — reference falls back to torch.optim.SGD
# ---------------------------------------------------------------------------

def sgd(momentum: float = 0.0, weight_decay: float = 0.0,
        nesterov: bool = False) -> Optimizer:
    hp = dict(name="sgd", momentum=momentum, weight_decay=weight_decay,
              nesterov=nesterov)

    def init(params):
        state = {"step": jnp.zeros((), jnp.int32)}
        if momentum:
            state["momentum"] = _zeros_like_f32(params)
        if _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        g32 = _to_f32(grads)
        new_inner: Dict[str, Any] = {"step": step}
        if weight_decay:
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        if momentum:
            buf = jax.tree.map(lambda b, g: momentum * b + g,
                               state["momentum"], g32)
            new_inner["momentum"] = buf
            if nesterov:
                g32 = jax.tree.map(lambda g, b: g + momentum * b, g32, buf)
            else:
                g32 = buf
        new_master = jax.tree.map(lambda p, g: p - lr * g, master, g32)
        return _finish(state, new_master, params, new_inner)

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# Muon  (reference runtime/zero/muon/muon_optimizer.py:14,
#        original_muon.py:36–267 — Newton–Schulz orthogonalized momentum on
#        2-D weights, Adam for the rest)
# ---------------------------------------------------------------------------

def _newton_schulz(G: jax.Array, steps: int = 5, eps: float = 1e-7) -> jax.Array:
    """Quintic Newton–Schulz iteration approximating UV^T of G = USV^T.

    Coefficients per the public Muon recipe (reference
    original_muon.py:zeropower_via_newtonschulz5). Runs in bf16 on the MXU.
    """
    a, b, c = 3.4445, -4.7750, 2.0315
    transpose = G.shape[0] > G.shape[1]
    X = G.astype(jnp.bfloat16)
    if transpose:
        X = X.T
    X = X / (jnp.linalg.norm(X.astype(jnp.float32)) + eps).astype(jnp.bfloat16)
    for _ in range(steps):
        A = X @ X.T
        B = b * A + c * (A @ A)
        X = a * X + B @ X
    if transpose:
        X = X.T
    return X


def muon(beta: float = 0.95, weight_decay: float = 0.0, ns_steps: int = 5,
         adam_beta1: float = 0.9, adam_beta2: float = 0.999,
         adam_eps: float = 1e-8) -> Optimizer:
    """2-D weight matrices get orthogonalized momentum; everything else
    (embeddings are excluded in the reference by the user; here: non-2D
    leaves and leaves whose path mentions 'embed'/'norm'/'bias') gets Adam.

    Stacked-layer 3-D weights [L, in, out] are treated as L independent 2-D
    matrices via vmap — matching per-layer semantics of the reference while
    keeping the scan-stacked layout.
    """
    hp = dict(name="muon", beta=beta, weight_decay=weight_decay,
              ns_steps=ns_steps)

    def _is_muon_leaf(path: str, x) -> bool:
        if x.ndim < 2:
            return False
        lowered = path.lower()
        return not any(k in lowered for k in ("embed", "norm", "bias", "lm_head"))

    def _mask(params):
        # Static: derived from the pytree *structure*, never from traced values.
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        return [_is_muon_leaf("/".join(str(k) for k in path), x)
                for path, x in flat]

    def init(params):
        # per-leaf state only where the update reads it: momentum for Muon
        # leaves, Adam moments for the rest (scalar placeholders elsewhere
        # keep pytree structure aligned without burning HBM)
        mask = _mask(params)
        flat, treedef = jax.tree_util.tree_flatten(params)

        def select(keep):
            leaves = [jnp.zeros(p.shape, jnp.float32) if k == keep
                      else jnp.zeros((), jnp.float32)
                      for p, k in zip(flat, mask)]
            return jax.tree_util.tree_unflatten(treedef, leaves)

        state = {"step": jnp.zeros((), jnp.int32),
                 "momentum": select(True),
                 "exp_avg": select(False),
                 "exp_avg_sq": select(False)}
        if _needs_master(params):
            state["master"] = _to_f32(params)
        return state

    def update(grads, state, params, lr):
        step = state["step"] + 1
        master = _get_master(state, params)
        g32 = _to_f32(grads)
        mask = _mask(params)
        bc1 = 1.0 - adam_beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - adam_beta2 ** step.astype(jnp.float32)

        leaves_g, treedef = jax.tree_util.tree_flatten(g32)
        leaves_p = treedef.flatten_up_to(master)
        leaves_mom = treedef.flatten_up_to(state["momentum"])
        leaves_m = treedef.flatten_up_to(state["exp_avg"])
        leaves_v = treedef.flatten_up_to(state["exp_avg_sq"])

        out_p, out_mom, out_m, out_v = [], [], [], []
        for is_muon, g, p, mom, m, v in zip(mask, leaves_g, leaves_p,
                                            leaves_mom, leaves_m, leaves_v):
            if is_muon:
                mom = beta * mom + g
                eff = g + beta * mom   # nesterov-style
                mat = eff
                if mat.ndim == 2:
                    ortho = _newton_schulz(mat, ns_steps)
                else:
                    flat2d = mat.reshape(mat.shape[0], mat.shape[1], -1)
                    ortho = jax.vmap(lambda x: _newton_schulz(x, ns_steps))(flat2d)
                    ortho = ortho.reshape(mat.shape)
                scale = math.sqrt(max(1.0, mat.shape[-2] / mat.shape[-1]))
                upd = ortho.astype(jnp.float32) * scale
                if weight_decay:
                    upd = upd + weight_decay * p
                out_p.append(p - lr * upd)
                out_mom.append(mom)
                out_m.append(m)
                out_v.append(v)
            else:
                m = adam_beta1 * m + (1 - adam_beta1) * g
                v = adam_beta2 * v + (1 - adam_beta2) * (g * g)
                upd = (m / bc1) / (jnp.sqrt(v / bc2) + adam_eps)
                if weight_decay:
                    upd = upd + weight_decay * p
                out_p.append(p - lr * upd)
                out_mom.append(mom)
                out_m.append(m)
                out_v.append(v)

        new_master = jax.tree_util.tree_unflatten(treedef, out_p)
        new_inner = {"step": step,
                     "momentum": jax.tree_util.tree_unflatten(treedef, out_mom),
                     "exp_avg": jax.tree_util.tree_unflatten(treedef, out_m),
                     "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, out_v)}
        return _finish(state, new_master, params, new_inner)

    return Optimizer(init, update, hp)


# ---------------------------------------------------------------------------
# Registry — reference engine.py:_configure_basic_optimizer:1541 name dispatch
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Callable[..., Optimizer]] = {}


def register_optimizer(name: str, factory: Callable[..., Optimizer]) -> None:
    _REGISTRY[name.lower()] = factory


for _n, _f in [("adam", lambda **kw: adam(**{"adam_w_mode": False, **kw})),
               ("adamw", adam),
               ("fusedadam", adam),
               ("lamb", lamb),
               ("lion", lion),
               ("adagrad", adagrad),
               ("sgd", sgd),
               ("muon", muon)]:
    register_optimizer(_n, _f)

#: torch-style param names accepted in config "params" blocks, mapped to ours
_PARAM_ALIASES = {
    "lr": None,              # handled by the engine/scheduler, not the optimizer
    "betas": ("beta1", "beta2"),
    "bias_correction": "bias_correction",
}


def build_optimizer(name: str, params: Optional[Dict[str, Any]] = None) -> Tuple[Optimizer, float]:
    """Build from a config block (reference "optimizer": {"type","params"}).

    Returns (optimizer, base_lr) — lr is owned by the LR schedule.
    """
    params = dict(params or {})
    base_lr = float(params.pop("lr", 1e-3))
    betas = params.pop("betas", None)
    if betas is not None:
        params["beta1"], params["beta2"] = float(betas[0]), float(betas[1])
    params.pop("torch_adam", None)
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown optimizer '{name}'; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key](**params), base_lr
