"""Python wrapper for the native host Adam (ZeRO-Offload optimizer).

Reference: ``ops/adam/cpu_adam.py:13`` (DeepSpeedCPUAdam). Operates on
flat fp32 numpy buffers (the host mirror of the reference's flat fp32
partitions); falls back to a pure-numpy step when no C++ toolchain exists.
"""

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import is_native_available, load_host_adam


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class HostAdam:
    """Fused Adam/AdamW over one flat fp32 parameter buffer."""

    def __init__(self, num_elements: int, lr: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 use_native: Optional[bool] = None,
                 allocate_state: bool = True):
        """``allocate_state=False`` skips the moment buffers — for callers
        that keep moments elsewhere (the NVMe windowed sweep) and drive
        :meth:`step_buffers` directly; :meth:`step` then raises."""
        self.n = int(num_elements)
        self.lr = lr
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self.exp_avg = np.zeros(self.n, np.float32) if allocate_state else None
        self.exp_avg_sq = (np.zeros(self.n, np.float32) if allocate_state
                           else None)
        if use_native is None:
            use_native = is_native_available()
        self._lib = load_host_adam() if use_native else None

    def step_buffers(self, params: np.ndarray, grads: np.ndarray,
                     exp_avg: np.ndarray, exp_avg_sq: np.ndarray,
                     step: int, lr: float) -> None:
        """One fused Adam sweep over caller-provided flat fp32 buffers with
        an explicit global step (so windowed callers share one bias
        correction). The single home of the Adam math — native and numpy
        paths both live here."""
        n = params.size
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        assert grads.size == exp_avg.size == exp_avg_sq.size == n
        if grads.dtype != np.float32:
            grads = grads.astype(np.float32)
        grads = np.ascontiguousarray(grads)
        if self._lib is not None:
            self._lib.ds_host_adam_step(
                _f32p(params), _f32p(grads), _f32p(exp_avg),
                _f32p(exp_avg_sq), n, step, lr,
                self.beta1, self.beta2, self.eps, self.weight_decay,
                1 if self.adamw_mode else 0)
            return
        # numpy fallback (identical math)
        g = grads
        if not self.adamw_mode and self.weight_decay:
            g = g + self.weight_decay * params
        exp_avg *= self.beta1
        exp_avg += (1 - self.beta1) * g
        exp_avg_sq *= self.beta2
        exp_avg_sq += (1 - self.beta2) * g * g
        bc1 = 1 - self.beta1 ** step
        bc2 = 1 - self.beta2 ** step
        update = (exp_avg / bc1) / (np.sqrt(exp_avg_sq / bc2) + self.eps)
        if self.adamw_mode and self.weight_decay:
            update = update + self.weight_decay * params
        params -= lr * update

    def step(self, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        """In-place update of ``params`` (flat fp32, C-contiguous)."""
        if self.exp_avg is None:
            raise RuntimeError("HostAdam built with allocate_state=False "
                               "has no moment buffers; use step_buffers")
        assert params.size == self.n == grads.size
        self.step_count += 1
        self.step_buffers(params, grads, self.exp_avg, self.exp_avg_sq,
                          self.step_count, self.lr if lr is None
                          else float(lr))

    def grad_norm(self, grads: np.ndarray) -> float:
        if self._lib is not None and grads.dtype == np.float32 and \
                grads.flags["C_CONTIGUOUS"]:
            return float(np.sqrt(
                self._lib.ds_l2_norm_sq(_f32p(grads), grads.size)))
        return float(np.linalg.norm(grads.astype(np.float64)))


class HostAdagrad:
    """Fused host Adagrad over one flat fp32 buffer (reference
    ``csrc/adagrad/cpu_adagrad.cpp`` / ``ops/adagrad/cpu_adagrad.py``)."""

    def __init__(self, num_elements: int, lr: float = 1e-2,
                 eps: float = 1e-10, weight_decay: float = 0.0,
                 use_native: Optional[bool] = None):
        self.n = int(num_elements)
        self.lr, self.eps, self.weight_decay = lr, eps, weight_decay
        self.step_count = 0
        self.exp_avg_sq = np.zeros(self.n, np.float32)
        if use_native is None:
            use_native = is_native_available()
        self._lib = load_host_adam() if use_native else None

    def step(self, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        assert params.size == self.n == grads.size
        self.step_count += 1
        lr = self.lr if lr is None else float(lr)
        grads = np.ascontiguousarray(grads, np.float32)
        if self._lib is not None:
            self._lib.ds_host_adagrad_step(
                _f32p(params), _f32p(grads), _f32p(self.exp_avg_sq),
                self.n, lr, self.eps, self.weight_decay)
            return
        g = grads + self.weight_decay * params if self.weight_decay else \
            grads
        self.exp_avg_sq += g * g
        params -= lr * g / (np.sqrt(self.exp_avg_sq) + self.eps)


class HostLion:
    """Fused host Lion over one flat fp32 buffer (reference
    ``csrc/lion/cpu_lion_impl.cpp`` / ``ops/lion/cpu_lion.py``)."""

    def __init__(self, num_elements: int, lr: float = 1e-4,
                 beta1: float = 0.9, beta2: float = 0.99,
                 weight_decay: float = 0.0,
                 use_native: Optional[bool] = None):
        self.n = int(num_elements)
        self.lr = lr
        self.beta1, self.beta2 = beta1, beta2
        self.weight_decay = weight_decay
        self.step_count = 0
        self.exp_avg = np.zeros(self.n, np.float32)
        if use_native is None:
            use_native = is_native_available()
        self._lib = load_host_adam() if use_native else None

    def step(self, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        assert params.size == self.n == grads.size
        self.step_count += 1
        lr = self.lr if lr is None else float(lr)
        grads = np.ascontiguousarray(grads, np.float32)
        if self._lib is not None:
            self._lib.ds_host_lion_step(
                _f32p(params), _f32p(grads), _f32p(self.exp_avg),
                self.n, lr, self.beta1, self.beta2, self.weight_decay)
            return
        c = self.beta1 * self.exp_avg + (1 - self.beta1) * grads
        params -= lr * (np.sign(c) + self.weight_decay * params)
        self.exp_avg *= self.beta2
        self.exp_avg += (1 - self.beta2) * grads
