"""Pallas grouped (expert-ragged) matmul suite for dropless MoE.

The TPU answer to the reference's grouped-GEMM MoE kernels
(``inference/v2/kernels/cutlass_ops/moe_gemm/`` — CUTLASS grouped GEMM over
per-expert problem sizes; training-side dispatch in
``moe/sharded_moe.py``). Three design moves, none of which translate from
the CUDA implementation:

**Block-aligned dropless dispatch.** MegaBlocks-style grouped kernels pay
for tiles that straddle expert boundaries (per-tile group metadata, masked
accumulation, output revisiting). Instead we pad each expert's row range up
to the kernel's m-tile size when building the sorted layout, so every
m-tile belongs to EXACTLY one expert: the only per-tile metadata is one
scalar-prefetched ``group_of_tile`` vector consumed by the weight
BlockSpec index maps, and the matmul body is a plain dense tile. Expected
padding cost is ``E·bm/2`` rows (~3% of a 32K-row batch at bm=256) —
measured far below the straddle-tile machinery it replaces
(``megablox.gmm`` benched 2.4x slower than even ``lax.ragged_dot`` on
v5e, docs/kernels.md).

**Counting-sort dispatch, no argsort.** The (token, slot)→position map is
a cumulative histogram (one [S·k, E] cumsum) instead of a 32K-element
argsort — TPU sorts are lane-serial and measurably dominate the dispatch
cost the r4 decomposition attributed to "sort/gather/scatter".

**Fused GLU matmuls.** One kernel computes gate AND up projections per LHS
fetch (halving activation reads for the first two matmuls); the down
kernel recomputes ``silu(gate)·up`` from the saved pre-activations in its
epilogue, so the [R, ffn] hidden tensor is never materialized in HBM.

**All-Pallas backward.** The custom VJP keeps every backward matmul in
Pallas: dgate/dup with the dH product AND the dwo outer product fused
into one kernel (gate/up/dY stream through VMEM once); dxs as a dual
full-K grouped matmul on the weights' native layouts (no transposed
weight copies in HBM); dwg/dwi as grouped outer products whose running
sums live in VMEM scratch and write each expert's f32 block exactly once
(accumulating into out_ref round-trips the block through HBM every
step). ``DSTPU_GMM_DW=ragged`` falls back to ``lax.ragged_dot_general``
for the weight grads — exact over the aligned layout because padding
rows carry zero activations and zero gradients.

**Gather-only dispatch.** Counting sort yields BOTH permutation
directions, so dispatch and combine are pure gathers in fwd and bwd
(:func:`gather_rows` / :func:`gather_combine`) — TPU row scatter-adds
serialize per index.

**Fused combine weights (r5).** Passing ``w`` to :func:`grouped_glu_ffn`
applies the per-row combine weights INSIDE the down kernel and computes
their gradient (``dw[r] = dZ[r]·y[r]``, the router's training signal)
inside the dgdu kernel as a per-f-tile ``rowsum(dh·h)`` — both already
have the operands streaming through VMEM. The combine then collapses to
the residual-free :func:`gather_sum`: no ``[R,d]`` elementwise scale in
fwd or bwd, no separate ``[R,d]`` row-dot for ``dw``.

**Residual-free backward (r5).** The scaled path's dgdu kernel
(:func:`_dgdu_rc_kernel`) recomputes the GLU pre-activations in-kernel
from ``xs``, so the VJP residuals carry NO ``[R, f]`` tensors at all:
under any remat policy the layer backward re-runs zero kernels, and
gate/up never round-trip HBM in the backward (the old path either
re-ran the gate_up kernel — writing 2×[R,f] that dgdu then re-read —
or stacked 4.7 GB of ``moe_glu`` residuals across the layer scan,
which measured SLOWER than the re-run).

Parity is asserted against a per-expert einsum reference in
tests/test_grouped_matmul.py; integration (full dropless layer fwd+bwd vs
the ragged_dot path, including router gradients) in tests/test_moe.py.
Measured on the r5 1B/8e bench: 26.3% → 35.9% active-param MFU.
"""

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["aligned_dispatch", "grouped_glu_ffn", "gather_rows",
           "gather_combine", "gather_sum", "supported", "pick_blocks"]

_LANE = 128
_VMEM_BUDGET = 12 * 2**20   # double-buffered per-step bytes we allow


# ---------------------------------------------------------------------------
# dispatch metadata
# ---------------------------------------------------------------------------

def aligned_dispatch(topi: jax.Array, topv: jax.Array, num_experts: int,
                     bm: int) -> Tuple[jax.Array, jax.Array, jax.Array,
                                       jax.Array, jax.Array, jax.Array]:
    """Counting-sort (token, slot) assignments into a block-aligned layout.

    topi/topv: [k, S] expert ids / combine weights, SLOT-MAJOR (the
    whole routing chain runs transposed — tokens on lanes; see
    ``topk_gates_t``). Returns:

    - ``sorted_tok`` [R_pad] int32 — source token for each sorted row;
      padding rows hold the sentinel ``S`` (callers gather from an
      ``xf`` with a zero row appended at index S).
    - ``sorted_w`` [R_pad] — combine weight per sorted row, 0 on padding.
      Differentiable w.r.t. ``topv`` (the only float input).
    - ``group_of_tile`` [R_pad // bm] int32 — owning expert per m-tile.
    - ``sizes_padded`` [E] int32 — per-expert row count INCLUDING its
      alignment padding; the last entry also absorbs the dead tail up
      to R_pad, whose rows the kernels SKIP and leave unspecified (the
      ragged dw fallback zero-masks them before reducing).
    - ``pos`` [k, S] int32 — the INVERSE map: row index of each (slot,
      token) assignment in the sorted layout. Having both directions
      lets dispatch AND combine run as pure gathers in both fwd and bwd
      (:func:`gather_rows` / :func:`gather_combine`) — TPU row
      scatter-adds serialize and measured far slower than gathers.
      ``pos[slot]`` is a clean [S] lanes-major vector per slot.
    - ``live_tiles`` [1] int32 — number of m-tiles containing aligned
      content; every kernel skips tiles at/past it, so rows beyond
      ``live_tiles*bm`` are UNSPECIFIED in all produced arrays.

    All shapes are static: R_pad = round_up(S·k, bm) + E·bm bounds the
    aligned total for any routing.
    """
    k, s = topi.shape
    r0 = s * k
    e = num_experts
    r_pad = _round_up(r0, bm) + e * bm
    flat_e = topi.reshape(-1).astype(jnp.int32)      # [R0] slot-major
    # transposed [E, R0] histogram: E lives on SUBLANES and R0 on lanes,
    # so the running-count cumsum vectorizes over full 128-lane tiles —
    # the [R0, E] orientation used 8 of 128 lanes and profiled at
    # ~0.5ms/layer on the 16K-token bench
    onehot_t = (flat_e[None, :] ==
                jnp.arange(e, dtype=jnp.int32)[:, None]).astype(jnp.int32)
    cum_t = jnp.cumsum(onehot_t, axis=1)                      # [E, R0]
    counts = cum_t[:, -1]                                     # [E]
    # aligned starts: each group begins on an m-tile boundary. Every
    # expert gets AT LEAST one tile (all-sentinel when empty): the dw
    # kernels zero-init each group's output blocks on first visit, so an
    # expert with no tiles would return uninitialized memory as its
    # weight gradient.
    aligned = jnp.maximum(_round_up_arr(counts, bm), bm)
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(aligned)[:-1].astype(jnp.int32)])
    # rank of each assignment within its expert = exclusive running count
    rank = jnp.take_along_axis(cum_t, flat_e[None, :],
                               axis=0)[0] - 1                 # [R0]
    pos = starts[flat_e] + rank                               # [R0]
    tok = (jnp.arange(r0, dtype=jnp.int32) % s)               # source token
    # pos is a permutation into [0, r_pad) — tell XLA (unique + in
    # bounds) so the TPU scatter lowering can skip the serializing
    # duplicate-combine path
    sorted_tok = jnp.full((r_pad,), s, jnp.int32).at[pos].set(
        tok, unique_indices=True, mode="promise_in_bounds")
    sorted_w = jnp.zeros((r_pad,), topv.dtype).at[pos].set(
        topv.reshape(-1), unique_indices=True, mode="promise_in_bounds")
    nm = r_pad // bm
    tile_starts = jnp.arange(nm, dtype=jnp.int32) * bm
    group_of_tile = (jnp.searchsorted(starts, tile_starts, side="right")
                     .astype(jnp.int32) - 1)
    # last group's padded size absorbs the tail tiles beyond the data
    ends = jnp.concatenate([starts[1:], jnp.array([r_pad], jnp.int32)])
    sizes_padded = (ends - starts).astype(jnp.int32)
    # tiles past the aligned content are pure sentinel — the kernels
    # skip their compute entirely (R_pad is a worst-case STATIC bound;
    # the average waste it would cost is ~E*bm/2 rows of matmul)
    live_tiles = (jnp.sum(aligned) // bm).astype(jnp.int32)[None]
    return (sorted_tok, sorted_w, group_of_tile, sizes_padded,
            pos.reshape(k, s), live_tiles)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _round_up_arr(x: jax.Array, m: int) -> jax.Array:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# gather-only dispatch / combine
#
# TPU row scatter-adds serialize per index; the counting-sort layout gives
# BOTH permutation directions up front, so each direction's VJP is
# expressed with the opposite gather — no [R, d] scatter anywhere in the
# layer, fwd or bwd.
# ---------------------------------------------------------------------------

@jax.custom_vjp
def gather_rows(xf1: jax.Array, sorted_tok: jax.Array,
                pos: jax.Array) -> jax.Array:
    """xs[r] = xf1[sorted_tok[r]] — dispatch gather into sorted order.

    xf1 [S+1, d] (a zero sentinel row appended at index S), sorted_tok
    [R_pad], pos [k, S]. The VJP accumulates via the inverse gather:
    dxf1[t] = Σ_slot dxs[pos[slot, t]]; the sentinel row's gradient is
    dropped (callers append a constant zero row, whose gradient the
    enclosing concat discards anyway).
    """
    return xf1[sorted_tok]


def _gather_rows_fwd(xf1, sorted_tok, pos):
    return xf1[sorted_tok], (pos, sorted_tok.shape)


def _gather_rows_bwd(res, dxs):
    pos, tok_shape = res
    # k unrolled gathers + adds, NOT dxs[pos].sum(0): the [k, S, d]
    # intermediate and its reduce was one of the profiled per-layer
    # hot spots
    dxf = dxs[pos[0]]
    for slot in range(1, pos.shape[0]):
        dxf = dxf + dxs[pos[slot]]
    dxf1 = jnp.concatenate([dxf, jnp.zeros((1, dxs.shape[-1]), dxs.dtype)])
    return (dxf1, np.zeros(tok_shape, jax.dtypes.float0),
            np.zeros(pos.shape, jax.dtypes.float0))


gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


@jax.custom_vjp
def gather_combine(y: jax.Array, w: jax.Array, sorted_tok: jax.Array,
                   pos: jax.Array) -> jax.Array:
    """out[t] = Σ_slot w[pos[slot,t]] · y[pos[slot,t]] — the combine as a
    gather over the inverse map instead of a scatter-add over tokens.

    y [R_pad, d], w [R_pad] (zero on padding rows), pos [k, S] →
    out [S, d]. Differentiable in y AND w (w carries the router's gate
    values, so its gradient trains the router).
    """
    return _combine_impl(y, w, pos)


def _combine_impl(y, w, pos):
    # k unrolled gathers + adds (see _gather_rows_bwd for why)
    yw = y * w[:, None].astype(y.dtype)
    out = yw[pos[0]]
    for slot in range(1, pos.shape[0]):
        out = out + yw[pos[slot]]
    return out


def _gather_combine_fwd(y, w, sorted_tok, pos):
    return _combine_impl(y, w, pos), (y, w, sorted_tok, pos.shape)


def _gather_combine_bwd(res, dout):
    y, w, sorted_tok, pos_shape = res
    dout1 = jnp.concatenate(
        [dout, jnp.zeros((1, dout.shape[-1]), dout.dtype)])
    d_rows = dout1[sorted_tok]                                # [R_pad, d]
    dy = d_rows * w[:, None].astype(d_rows.dtype)
    if os.environ.get("DSTPU_GMM_DCOMBINE") == "zero":
        # BENCH-ONLY diagnostic: skip the combine-weight gradient (cuts
        # the router's training signal) to expose its cost
        dw = jnp.zeros_like(w)
    else:
        dw = jnp.sum(d_rows.astype(jnp.float32) * y.astype(jnp.float32),
                     axis=-1).astype(w.dtype)
    return (dy, dw, np.zeros(sorted_tok.shape, jax.dtypes.float0),
            np.zeros(pos_shape, jax.dtypes.float0))


gather_combine.defvjp(_gather_combine_fwd, _gather_combine_bwd)


# ---------------------------------------------------------------------------
# block-size selection
# ---------------------------------------------------------------------------

def _block(dim: int, target: int) -> int:
    """min(dim rounded up to a lane multiple, target). Blocks need NOT
    divide the dim — grids use cdiv and Pallas masks the edge blocks
    (partial reads only ever feed lanes whose outputs are also masked)."""
    return min(_round_up(dim, _LANE), target)


def pick_blocks(d: int, f: int, itemsize: int = 2
                ) -> Tuple[int, int, int]:
    """(bm, bnf, bnd) for the kernel suite, shrunk to the VMEM budget.

    Env overrides: DSTPU_GMM_BM / DSTPU_GMM_BNF / DSTPU_GMM_BND govern
    the forward kernels; the backward kernels size their own tiles
    (DSTPU_GMM_BNF_BWD in :func:`_dgdu_rc`, DSTPU_GMM_BND_BWD in
    :func:`_dxs`).
    """
    # forward-kernel tiles (the backward sizes its own: _dgdu_rc /
    # _dxs). bnf=1024 from the r5 trace: gate_up measured 3.1 ms/layer
    # there vs 4.9 at 256 on the 1B/8e bench (the 256 sweep win predated
    # the backward's independent knobs); bm > 256 fails to compile
    bnf_env = int(os.environ.get("DSTPU_GMM_BNF", 0))
    bnf = _block(f, bnf_env or 1024)
    bnd = _block(d, int(os.environ.get("DSTPU_GMM_BND", 512)))
    bm = int(os.environ.get("DSTPU_GMM_BM", 0)) or 256
    # dominant per-step footprint (gate_up kernel): xs + 2 weight blocks +
    # 2 out blocks, double-buffered. The 2·d·bnf weight term is
    # bm-INDEPENDENT, so big-d geometries must shrink bnf first (an
    # explicit env bnf is honored as given); bm shrinks last.
    step = lambda: (bm * d + 2 * d * bnf + 2 * bm * bnf) * itemsize * 2
    if not bnf_env:
        while bnf > 256 and step() > _VMEM_BUDGET:
            bnf //= 2
    while bm > 16 and step() > _VMEM_BUDGET:
        bm //= 2
    if bnf_env and step() > _VMEM_BUDGET:
        # auto-sizing silently degrades; an explicit pin that cannot fit
        # even at the floor bm must fail loudly instead of OOMing VMEM
        # deep inside Mosaic with an unrelated-looking error
        raise ValueError(
            f"DSTPU_GMM_BNF={bnf_env} needs {step()} bytes of VMEM for "
            f"the gate_up tiles at d={d} (> {_VMEM_BUDGET} budget) even "
            f"at bm={bm}; lower the override")
    return bm, bnf, bnd


def supported(d: int, f: int) -> bool:
    """Shape gate: both matmul dims must tile to the 128-lane rule."""
    return d % _LANE == 0 and f % _LANE == 0


# ---------------------------------------------------------------------------
# kernels — grid (n_tiles, m_tiles), m innermost: group_of_tile is
# monotone in m, so weight blocks refetch only on expert transitions
# ---------------------------------------------------------------------------

def _gate_up_kernel(g_ref, lt_ref, xs_ref, wg_ref, wi_ref, gate_ref,
                    up_ref):
    @pl.when(pl.program_id(1) < lt_ref[0])
    def _():
        xs = xs_ref[...]
        gate_ref[...] = jnp.dot(xs, wg_ref[0],
                                preferred_element_type=jnp.float32
                                ).astype(gate_ref.dtype)
        up_ref[...] = jnp.dot(xs, wi_ref[0],
                              preferred_element_type=jnp.float32
                              ).astype(up_ref.dtype)


def _down_kernel(g_ref, lt_ref, gate_ref, up_ref, wo_ref, y_ref):
    @pl.when(pl.program_id(1) < lt_ref[0])
    def _():
        g32 = gate_ref[...].astype(jnp.float32)
        u32 = up_ref[...].astype(jnp.float32)
        h = (jax.nn.silu(g32) * u32).astype(wo_ref.dtype)
        y_ref[...] = jnp.dot(h, wo_ref[0],
                             preferred_element_type=jnp.float32
                             ).astype(y_ref.dtype)


def _down_w_kernel(g_ref, lt_ref, gate_ref, up_ref, w_ref, wo_ref, z_ref):
    """Down projection with the per-row combine weight fused into the
    epilogue: Z = diag(w)·(silu(gate)·up)·wo[g]. ``w_ref`` is a
    lanes-major (1, bm) tile row (the flash kernels' lse layout)."""
    @pl.when(pl.program_id(1) < lt_ref[0])
    def _():
        g32 = gate_ref[...].astype(jnp.float32)
        u32 = up_ref[...].astype(jnp.float32)
        h = (jax.nn.silu(g32) * u32).astype(wo_ref.dtype)
        y = jnp.dot(h, wo_ref[0], preferred_element_type=jnp.float32)
        w = w_ref[0, 0].astype(jnp.float32)                  # [bm] lanes
        z_ref[...] = (y * w[:, None]).astype(z_ref.dtype)


def _dgdu_kernel(g_ref, lt_ref, dy_ref, wo_ref, gate_ref, up_ref,
                 dg_ref, du_ref, dwo_ref, acc_o):
    """dH = dY·wo[g]^T (contracted on wo's own [f, d] layout — no
    transposed weight copy in HBM); dgate/dup epilogue; PLUS the dwo
    outer product — gate/up/dY are already streaming through VMEM here,
    so dwo costs one extra dot instead of a whole kernel's HBM re-sweep.
    Accumulates in VMEM scratch, written once per group (see
    _dw_pair_kernel for why not out_ref)."""
    i = pl.program_id(1)
    nm = pl.num_programs(1)
    live = lt_ref[0]

    @pl.when(i < live)
    def _():
        first = jnp.logical_or(
            i == 0, g_ref[i] != g_ref[jnp.maximum(i - 1, 0)])

        @pl.when(first)
        def _():
            acc_o[...] = jnp.zeros_like(acc_o)

        dy = dy_ref[...]
        dh = lax.dot_general(dy, wo_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        g32 = gate_ref[...].astype(jnp.float32)
        u32 = up_ref[...].astype(jnp.float32)
        sg = jax.nn.sigmoid(g32)
        silu_g = g32 * sg
        dsilu = sg * (1.0 + g32 * (1.0 - sg))
        dg_ref[...] = (dh * u32 * dsilu).astype(dg_ref.dtype)
        du_ref[...] = (dh * silu_g).astype(du_ref.dtype)
        h = (silu_g * u32).astype(dy.dtype)
        acc_o[...] += lax.dot_general(
            h, dy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        # the LAST live tile flushes group E-1 (dead tiles never run)
        last = jnp.logical_or(
            i + 1 >= live, g_ref[i] != g_ref[jnp.minimum(i + 1, nm - 1)])

        @pl.when(last)
        def _():
            dwo_ref[0] = acc_o[...]


def _dgdu_rc_kernel(g_ref, lt_ref, dz_ref, w_ref, xs_ref, wg_ref, wi_ref,
                    wo_ref, dg_ref, du_ref, dwo_ref, dwp_ref, acc_o, *,
                    f_total, bnf):
    """The scaled-FFN backward tile with the GLU pre-activations
    RECOMPUTED in-kernel from ``xs`` instead of read from HBM.

    Upstream dZ arrives UNSCALED by the combine weights (the combine is
    a plain gather-sum), so this kernel additionally produces the
    combine-weight gradient ``dw[r] = dZ[r]·y[r] = Σ_f dh[r,f]·h[r,f]``
    as per-f-tile partials (``dwp_ref``; summed over f-tiles by the
    caller), and dgate/dup/dwo pick up the per-row w factor
    (``d(h·wo) = w ⊙ dZ``).

    This removes the remat re-run of the gate_up kernel from the layer
    backward entirely: the scaled FFN's VJP residuals are just
    (xs, w, weights, dispatch metadata) — xs is already kept by the
    ``moe_xs`` save — so under ANY remat policy the backward re-runs
    nothing and gate/up never round-trip HBM in the backward (the
    re-run wrote 2×[R,f] and this kernel re-read them; both gone for
    the cost of streaming xs once per f-tile). Grid (n_f, n_m), m
    innermost; wg/wi blocks ride the existing expert-monotone index
    maps so they refetch only on transitions."""
    i = pl.program_id(1)
    nm = pl.num_programs(1)
    j = pl.program_id(0)
    live = lt_ref[0]

    @pl.when(i < live)
    def _():
        first = jnp.logical_or(
            i == 0, g_ref[i] != g_ref[jnp.maximum(i - 1, 0)])

        @pl.when(first)
        def _():
            acc_o[...] = jnp.zeros_like(acc_o)

        dz = dz_ref[...]
        w32 = w_ref[0, 0].astype(jnp.float32)                # [bm] lanes
        xs = xs_ref[...]
        # recompute this f-tile's gate/up (bitwise the forward kernel's
        # math: bf16 operands, f32 MXU accumulation, cast back)
        g32 = jnp.dot(xs, wg_ref[0],
                      preferred_element_type=jnp.float32)
        u32 = jnp.dot(xs, wi_ref[0],
                      preferred_element_type=jnp.float32)
        g32 = g32.astype(dz.dtype).astype(jnp.float32)
        u32 = u32.astype(dz.dtype).astype(jnp.float32)
        dh = lax.dot_general(dz, wo_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        sg = jax.nn.sigmoid(g32)
        silu_g = g32 * sg
        h32 = silu_g * u32
        if f_total % bnf:
            col = lax.broadcasted_iota(jnp.int32, h32.shape, 1)
            valid = (col + j * bnf) < f_total
            prod = jnp.where(valid, dh * h32, 0.0)
        else:
            prod = dh * h32
        dwp_ref[0, 0, 0, :] = jnp.sum(prod, axis=1)
        dhw = dh * w32[:, None]
        dsilu = sg * (1.0 + g32 * (1.0 - sg))
        dg_ref[...] = (dhw * u32 * dsilu).astype(dg_ref.dtype)
        du_ref[...] = (dhw * silu_g).astype(du_ref.dtype)
        h = h32.astype(dz.dtype)
        dzw = (dz.astype(jnp.float32) * w32[:, None]).astype(dz.dtype)
        acc_o[...] += lax.dot_general(
            h, dzw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        last = jnp.logical_or(
            i + 1 >= live, g_ref[i] != g_ref[jnp.minimum(i + 1, nm - 1)])

        @pl.when(last)
        def _():
            dwo_ref[0] = acc_o[...]


def _dxs_kernel(g_ref, lt_ref, dg_ref, du_ref, wg_ref, wi_ref, dxs_ref):
    # contract f on the weights' native [d, f] layout (wg block is
    # (1, bnd, f) — a d-slice), avoiding transposed HBM weight copies
    @pl.when(pl.program_id(1) < lt_ref[0])
    def _():
        acc = lax.dot_general(dg_ref[...], wg_ref[0],
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        acc += lax.dot_general(du_ref[...], wi_ref[0],
                               (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
        dxs_ref[...] = acc.astype(dxs_ref.dtype)


def _dw_pair_kernel(g_ref, lt_ref, xs_ref, dg_ref, du_ref, dwg_ref,
                    dwi_ref, acc_g, acc_i):
    """Grouped outer products dwg[e] = Σ xs^T dg, dwi[e] = Σ xs^T du.

    Grid (n_f_tiles, n_m_tiles), m innermost: g[i] is monotone in i, so
    each (expert, j) output block is owned by ONE consecutive run of
    steps. The running sums live in VMEM *scratch* and the output block
    is written exactly once, on the group's last tile — accumulating
    into out_ref directly round-trips the 4MB f32 block through HBM
    every step (measured 10% MXU efficiency vs ~2ms ideal)."""
    i = pl.program_id(1)
    nm = pl.num_programs(1)
    live = lt_ref[0]

    @pl.when(i < live)
    def _():
        first = jnp.logical_or(
            i == 0, g_ref[i] != g_ref[jnp.maximum(i - 1, 0)])

        @pl.when(first)
        def _():
            acc_g[...] = jnp.zeros_like(acc_g)
            acc_i[...] = jnp.zeros_like(acc_i)

        xs = xs_ref[...]
        acc_g[...] += lax.dot_general(
            xs, dg_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_i[...] += lax.dot_general(
            xs, du_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        last = jnp.logical_or(
            i + 1 >= live, g_ref[i] != g_ref[jnp.minimum(i + 1, nm - 1)])

        @pl.when(last)
        def _():
            dwg_ref[0] = acc_g[...]
            dwi_ref[0] = acc_i[...]


def _dw_pair(xs, dg, du, g_of_tile, live_tiles, num_experts, bm,
             interpret):
    """→ (dwg, dwi) [E, d, f] f32."""
    r_pad, d = xs.shape
    f = dg.shape[-1]
    bnf = max(_LANE, min(512, _round_up(f, _LANE)))
    grid = (pl.cdiv(f, bnf), r_pad // bm)
    specs = [
        pl.BlockSpec((bm, d), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
    ]
    out_specs = [pl.BlockSpec((1, d, bnf), lambda j, i, g, lt: (g[i], 0, j))] * 2
    shape = [jax.ShapeDtypeStruct((num_experts, d, f), jnp.float32)] * 2
    scratch = [pltpu.VMEM((d, bnf), jnp.float32)] * 2
    return _grid_call(_dw_pair_kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, xs, dg, du,
                      scratch=scratch)


def _grid_call(kernel, grid, in_specs, out_specs, out_shape, interpret,
               group_of_tile, live_tiles, *args, scratch=None):
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=grid,
            in_specs=in_specs, out_specs=out_specs,
            scratch_shapes=scratch or []),
        out_shape=out_shape,
        interpret=interpret,
    )(group_of_tile, live_tiles, *args)


def _gate_up(xs, wg, wi, g_of_tile, live_tiles, bm, bnf, interpret):
    r_pad, d = xs.shape
    f = wg.shape[-1]
    grid = (pl.cdiv(f, bnf), r_pad // bm)
    specs = [
        pl.BlockSpec((bm, d), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, d, bnf), lambda j, i, g, lt: (g[i], 0, j)),
        pl.BlockSpec((1, d, bnf), lambda j, i, g, lt: (g[i], 0, j)),
    ]
    out_specs = [pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j))] * 2
    shape = [jax.ShapeDtypeStruct((r_pad, f), xs.dtype)] * 2
    return _grid_call(_gate_up_kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, xs, wg, wi)


def _down(gate, up, wo, g_of_tile, live_tiles, bm, bnd, interpret):
    r_pad, f = gate.shape
    d = wo.shape[-1]
    grid = (pl.cdiv(d, bnd), r_pad // bm)
    specs = [
        pl.BlockSpec((bm, f), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((bm, f), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, f, bnd), lambda j, i, g, lt: (g[i], 0, j)),
    ]
    out_specs = pl.BlockSpec((bm, bnd), lambda j, i, g, lt: (i, j))
    shape = jax.ShapeDtypeStruct((r_pad, d), gate.dtype)
    return _grid_call(_down_kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, gate, up, wo)


def _down_w(gate, up, w2, wo, g_of_tile, live_tiles, bm, bnd, interpret):
    r_pad, f = gate.shape
    d = wo.shape[-1]
    grid = (pl.cdiv(d, bnd), r_pad // bm)
    specs = [
        pl.BlockSpec((bm, f), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((bm, f), lambda j, i, g, lt: (i, 0)),
        # [nm, 1, bm] lanes-major: the TPU lowering requires the last
        # two block dims be (unit-or-full, 128-multiple)
        pl.BlockSpec((1, 1, bm), lambda j, i, g, lt: (i, 0, 0)),
        pl.BlockSpec((1, f, bnd), lambda j, i, g, lt: (g[i], 0, j)),
    ]
    out_specs = pl.BlockSpec((bm, bnd), lambda j, i, g, lt: (i, j))
    shape = jax.ShapeDtypeStruct((r_pad, d), gate.dtype)
    return _grid_call(_down_w_kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, gate, up, w2, wo)


def _dgdu_rc(dz, w2, xs, wg, wi, wo, g_of_tile, live_tiles, num_experts,
             bm, interpret):
    """→ (dg, du [R_pad, f], dwo [E, f, d] f32, dwp [n_f, nm, 1, bm]).
    f-tile size: DSTPU_GMM_BNF_BWD (default 256 — dz AND xs re-stream
    once per f-tile here, so bigger tiles cut the dominant HBM term;
    512 is the VMEM ceiling with the dwo accumulator resident)."""
    r_pad, d = dz.shape
    f = wg.shape[-1]
    # clamp at 512 regardless of the env: wg+wi+wo blocks plus the
    # (bnf, d) f32 dwo accumulator exceed scoped VMEM past it
    # (measured: 16.98M vs the 16M limit at bnf=512 on the 1B/8e bench)
    bnf = min(_block(f, int(os.environ.get("DSTPU_GMM_BNF_BWD", 256))),
              512)
    nf = pl.cdiv(f, bnf)
    nm = r_pad // bm
    grid = (nf, nm)
    specs = [
        pl.BlockSpec((bm, d), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, 1, bm), lambda j, i, g, lt: (i, 0, 0)),
        pl.BlockSpec((bm, d), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, d, bnf), lambda j, i, g, lt: (g[i], 0, j)),
        pl.BlockSpec((1, d, bnf), lambda j, i, g, lt: (g[i], 0, j)),
        pl.BlockSpec((1, bnf, d), lambda j, i, g, lt: (g[i], j, 0)),
    ]
    out_specs = [
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((1, bnf, d), lambda j, i, g, lt: (g[i], j, 0)),
        pl.BlockSpec((1, 1, 1, bm), lambda j, i, g, lt: (j, i, 0, 0)),
    ]
    shape = [jax.ShapeDtypeStruct((r_pad, f), dz.dtype),
             jax.ShapeDtypeStruct((r_pad, f), dz.dtype),
             jax.ShapeDtypeStruct((num_experts, f, d), jnp.float32),
             jax.ShapeDtypeStruct((nf, nm, 1, bm), jnp.float32)]
    scratch = [pltpu.VMEM((bnf, d), jnp.float32)]
    kernel = functools.partial(_dgdu_rc_kernel, f_total=f, bnf=bnf)
    return _grid_call(kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, dz, w2, xs, wg,
                      wi, wo, scratch=scratch)


def _dgdu(dy, wo, gate, up, g_of_tile, live_tiles, num_experts, bm,
          bnf, interpret):
    """→ (dg, du [R_pad, f], dwo [E, f, d] f32). Takes wo in its native
    [E, f, d] layout (f-slice blocks). The dwo accumulator block
    (1, bnf, d) f32 shares the step, so bnf is capped at 512 here to
    hold the VMEM budget."""
    r_pad, d = dy.shape
    f = gate.shape[-1]
    bnf = min(bnf, 512)
    grid = (pl.cdiv(f, bnf), r_pad // bm)
    specs = [
        pl.BlockSpec((bm, d), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, bnf, d), lambda j, i, g, lt: (g[i], j, 0)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
    ]
    out_specs = [
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((bm, bnf), lambda j, i, g, lt: (i, j)),
        pl.BlockSpec((1, bnf, d), lambda j, i, g, lt: (g[i], j, 0)),
    ]
    shape = [jax.ShapeDtypeStruct((r_pad, f), gate.dtype),
             jax.ShapeDtypeStruct((r_pad, f), gate.dtype),
             jax.ShapeDtypeStruct((num_experts, f, d), jnp.float32)]
    scratch = [pltpu.VMEM((bnf, d), jnp.float32)]
    return _grid_call(_dgdu_kernel, grid, specs, out_specs, shape,
                      interpret, g_of_tile, live_tiles, dy, wo, gate, up,
                      scratch=scratch)


def _dxs(dg, du, wg, wi, g_of_tile, live_tiles, bm, bnd, interpret):
    """dxs = dg·wg^T + du·wi^T with the weights in their native [E, d, f]
    layout (d-slice blocks, contraction on f).

    dg/du stream ONCE PER d-TILE here — the kernel's dominant HBM term
    (full-f rows: n_d × 2×[R,f]). So instead of halving the d-tile to
    fit the two full-K weight blocks in VMEM (4 d-tiles → 1.57 GB of
    dg/du traffic at the 16K-token bench), SUBDIVIDE the m-tiles to
    bm_x = 128: the aligned layout's tile boundaries are multiples of
    bm, so every 128-sub-tile still has one owning expert
    (``repeat(group_of_tile, bm/128)``) and d-tiles stay big.
    DSTPU_GMM_BND_BWD overrides the d-tile (default 512 → 2 sweeps)."""
    r_pad, f = dg.shape
    d = wg.shape[1]
    bnd_env = int(os.environ.get("DSTPU_GMM_BND_BWD", 0))
    if bm > 128 and bm % 128 == 0:
        bm_x = 128
        sub = bm // bm_x
        g_x = jnp.repeat(g_of_tile, sub)
        lt_x = live_tiles * sub
        bnd = _block(d, bnd_env or 512)
    else:
        # bm not 128-divisible: sub-tiles would straddle expert
        # boundaries — keep whole m-tiles and halve the d-tile for VMEM
        # (the pre-subdivision behavior)
        bm_x, g_x, lt_x = bm, g_of_tile, live_tiles
        bnd = max(_LANE, bnd // 2)
        bnd_env = 0          # the override only governs the 128-sub path
    # per-step footprint, double-buffered: dg + du rows (bm_x, f), two
    # full-f weight d-slices (bnd, f), one out block (bm_x, bnd). The
    # 2·bnd·f weight term scales with f, so long-ffn geometries must
    # clamp bnd the same way pick_blocks clamps bnf
    itemsize = dg.dtype.itemsize
    step = lambda: (2 * bm_x * f + 2 * bnd * f + bm_x * bnd) * itemsize * 2
    if bnd_env:
        if step() > _VMEM_BUDGET:
            raise ValueError(
                f"DSTPU_GMM_BND_BWD={bnd_env} needs {step()} bytes of "
                f"VMEM for the dxs tiles at f={f} (> {_VMEM_BUDGET} "
                f"budget); lower the override")
    else:
        while bnd > _LANE and step() > _VMEM_BUDGET:
            bnd //= 2
    grid = (pl.cdiv(d, bnd), r_pad // bm_x)
    specs = [
        pl.BlockSpec((bm_x, f), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((bm_x, f), lambda j, i, g, lt: (i, 0)),
        pl.BlockSpec((1, bnd, f), lambda j, i, g, lt: (g[i], j, 0)),
        pl.BlockSpec((1, bnd, f), lambda j, i, g, lt: (g[i], j, 0)),
    ]
    out_specs = pl.BlockSpec((bm_x, bnd), lambda j, i, g, lt: (i, j))
    shape = jax.ShapeDtypeStruct((r_pad, d), dg.dtype)
    return _grid_call(_dxs_kernel, grid, specs, out_specs, shape,
                      interpret, g_x, lt_x, dg, du, wg, wi)


# ---------------------------------------------------------------------------
# the differentiable FFN
# ---------------------------------------------------------------------------

def _dw_ragged(lhs, grad, sizes_padded, num_experts):
    """Weight gradient dW[e] = lhs[rows_e]^T @ grad[rows_e] via
    ragged_dot_general with the ragged dimension on the contraction —
    exact over the aligned layout because padding rows are zero in both
    operands.

    DSTPU_GMM_DW=zero is a BENCH-ONLY diagnostic that skips the weight
    gradients entirely (wrong training math) to expose their cost.
    """
    if os.environ.get("DSTPU_GMM_DW") == "zero":
        return jnp.zeros((num_experts, lhs.shape[1], grad.shape[1]),
                         lhs.dtype)
    if not hasattr(lax, "ragged_dot_general"):
        # older jax: no ragged-CONTRACTION primitive — fall back to a
        # segment-masked einsum (exact: padding rows are zero in both
        # operands; rows past the total land in no segment)
        ends = jnp.cumsum(sizes_padded)
        row = jnp.arange(lhs.shape[0], dtype=ends.dtype)[:, None]
        seg = ((row >= ends - sizes_padded) & (row < ends)
               ).astype(jnp.float32)                     # [R, E]
        return jnp.einsum("re,rd,rf->edf", seg,
                          lhs.astype(jnp.float32),
                          grad.astype(jnp.float32)).astype(lhs.dtype)
    dims = lax.RaggedDotDimensionNumbers(
        dot_dimension_numbers=(((0,), (0,)), ((), ())),
        lhs_ragged_dimensions=[0], rhs_group_dimensions=[])
    return lax.ragged_dot_general(
        lhs, grad, sizes_padded, dims,
        preferred_element_type=jnp.float32).astype(lhs.dtype)


@functools.lru_cache(maxsize=None)
def _build_ffn(bm: int, bnf: int, bnd: int, interpret: bool):
    """custom_vjp'd (xs, wg, wi, wo, group_of_tile, sizes_padded,
    live_tiles) -> Y. Rows at/past ``live_tiles * bm`` are UNSPECIFIED
    in every produced array (the kernels skip those tiles outright) —
    consumers must address rows through the dispatch maps only."""

    @jax.custom_vjp
    def ffn(xs, wg, wi, wo, g_of_tile, sizes_padded, live_tiles):
        gate, up = _gate_up(xs, wg, wi, g_of_tile, live_tiles, bm, bnf,
                            interpret)
        return _down(gate, up, wo, g_of_tile, live_tiles, bm, bnd,
                     interpret)

    def fwd(xs, wg, wi, wo, g_of_tile, sizes_padded, live_tiles):
        from jax.ad_checkpoint import checkpoint_name
        gate, up = _gate_up(xs, wg, wi, g_of_tile, live_tiles, bm, bnf,
                            interpret)
        # named so remat policies can SAVE the GLU pre-activations:
        # without them the layer backward re-runs the gate/up/down
        # kernels (3 of the FFN's 12 executed matmul units) just to
        # rebuild these residuals. ~2x[R, ffn] bf16 per layer — a
        # policy opt-in, not a default
        gate = checkpoint_name(gate, "moe_glu")
        up = checkpoint_name(up, "moe_glu")
        y = _down(gate, up, wo, g_of_tile, live_tiles, bm, bnd, interpret)
        return y, (xs, gate, up, wg, wi, wo, g_of_tile, sizes_padded,
                   live_tiles)

    def bwd(res, dy):
        (xs, gate, up, wg, wi, wo, g_of_tile, sizes_padded,
         live_tiles) = res
        e = wg.shape[0]
        dg, du, dwo32 = _dgdu(dy, wo, gate, up, g_of_tile, live_tiles,
                              e, bm, bnf, interpret)
        dxs = _dxs(dg, du, wg, wi, g_of_tile, live_tiles, bm, bnd,
                   interpret)
        dw_mode = os.environ.get("DSTPU_GMM_DW", "pallas")
        if dw_mode == "pallas":
            dwg, dwi = _dw_pair(xs, dg, du, g_of_tile, live_tiles, e,
                                bm, interpret)
            dwg = dwg.astype(wg.dtype)
            dwi = dwi.astype(wi.dtype)
            dwo = dwo32.astype(wo.dtype)
        else:   # 'ragged' (XLA fallback) / 'zero' (bench diagnostic)
            # the skipped dead-tail tiles leave dg/du/gate/up
            # UNINITIALIZED there, and sizes_padded[E-1] absorbs that
            # tail — zero it before the ragged reduction or 0*NaN
            # poisons the last expert's weight grads
            row = jnp.arange(xs.shape[0], dtype=jnp.int32)[:, None]
            alive = row < live_tiles[0] * bm
            dg_z = jnp.where(alive, dg, 0)
            du_z = jnp.where(alive, du, 0)
            dwg = _dw_ragged(xs, dg_z, sizes_padded, e)
            dwi = _dw_ragged(xs, du_z, sizes_padded, e)
            hidden = jnp.where(
                alive,
                (jax.nn.silu(gate.astype(jnp.float32))
                 * up.astype(jnp.float32)).astype(gate.dtype), 0)
            dwo = _dw_ragged(hidden, dy, sizes_padded, e)
        return (dxs, dwg, dwi, dwo,
                np.zeros(g_of_tile.shape, jax.dtypes.float0),
                np.zeros(sizes_padded.shape, jax.dtypes.float0),
                np.zeros(live_tiles.shape, jax.dtypes.float0))

    ffn.defvjp(fwd, bwd)
    return ffn


@functools.lru_cache(maxsize=None)
def _build_ffn_w(bm: int, bnf: int, bnd: int, interpret: bool):
    """Scaled variant: (xs, w2, wg, wi, wo, meta…) -> Z with the per-row
    combine weights applied in the down kernel and their gradient
    computed in the dgdu kernel (see :func:`_dgdu_rc_kernel`). The VJP
    residuals are just (xs, w2, weights, dispatch metadata) — no [R,f]
    tensors: the backward recomputes gate/up in-kernel, so under ANY
    remat policy the layer backward re-runs zero kernels (no ``moe_glu``
    save needed; that name only matters for the unscaled path)."""

    @jax.custom_vjp
    def ffn(xs, w2, wg, wi, wo, g_of_tile, sizes_padded, live_tiles):
        gate, up = _gate_up(xs, wg, wi, g_of_tile, live_tiles, bm, bnf,
                            interpret)
        return _down_w(gate, up, w2, wo, g_of_tile, live_tiles, bm, bnd,
                       interpret)

    def fwd(xs, w2, wg, wi, wo, g_of_tile, sizes_padded, live_tiles):
        gate, up = _gate_up(xs, wg, wi, g_of_tile, live_tiles, bm, bnf,
                            interpret)
        z = _down_w(gate, up, w2, wo, g_of_tile, live_tiles, bm, bnd,
                    interpret)
        # residuals carry NO [R, f] tensors: the backward recomputes
        # gate/up in-kernel from xs (_dgdu_rc_kernel), so under any
        # remat policy the layer backward re-runs nothing and the GLU
        # pre-activations never round-trip HBM in the backward
        return z, (xs, w2, wg, wi, wo, g_of_tile, sizes_padded,
                   live_tiles)

    def bwd(res, dz):
        (xs, w2, wg, wi, wo, g_of_tile, sizes_padded,
         live_tiles) = res
        e = wg.shape[0]
        dg, du, dwo32, dwp = _dgdu_rc(dz, w2, xs, wg, wi, wo, g_of_tile,
                                      live_tiles, e, bm, interpret)
        if os.environ.get("DSTPU_GMM_DCOMBINE") == "zero":
            # BENCH-ONLY diagnostic: drop the router's training signal
            # to expose the combine-weight-grad cost
            dw2 = jnp.zeros_like(w2)
        else:
            # dwp m-tiles at/past live_tiles are SKIPPED by the kernel
            # (uninitialized memory) — mask them before handing the
            # combine-weight grad to the optimizer, or garbage/NaNs in
            # the dead tail poison the router update
            tile = jnp.arange(dwp.shape[1], dtype=jnp.int32)[:, None, None]
            dw2 = jnp.where(tile < live_tiles[0],
                            jnp.sum(dwp, axis=0), 0.0
                            ).astype(w2.dtype)            # [nm, 1, bm]
        dxs = _dxs(dg, du, wg, wi, g_of_tile, live_tiles, bm, bnd,
                   interpret)
        dw_mode = os.environ.get("DSTPU_GMM_DW", "pallas")
        if dw_mode == "pallas":
            dwg, dwi = _dw_pair(xs, dg, du, g_of_tile, live_tiles, e,
                                bm, interpret)
            dwg = dwg.astype(wg.dtype)
            dwi = dwi.astype(wi.dtype)
            dwo = dwo32.astype(wo.dtype)
        else:   # 'ragged' (XLA fallback) / 'zero' (bench diagnostic)
            row = jnp.arange(xs.shape[0], dtype=jnp.int32)[:, None]
            alive = row < live_tiles[0] * bm
            dg_z = jnp.where(alive, dg, 0)
            du_z = jnp.where(alive, du, 0)
            dwg = _dw_ragged(xs, dg_z, sizes_padded, e)
            dwi = _dw_ragged(xs, du_z, sizes_padded, e)
            # gate/up are no longer residuals — rebuild hidden over the
            # aligned layout (exact: padding rows are zero in xs)
            gate_r = lax.ragged_dot(xs, wg, sizes_padded)
            up_r = lax.ragged_dot(xs, wi, sizes_padded)
            hidden = jnp.where(
                alive,
                (jax.nn.silu(gate_r.astype(jnp.float32))
                 * up_r.astype(jnp.float32)).astype(gate_r.dtype), 0)
            # d(h·wo) = w ⊙ dZ under the fused scaling
            dzw = jnp.where(
                alive,
                dz * w2.reshape(-1, 1).astype(dz.dtype), 0)
            dwo = _dw_ragged(hidden, dzw, sizes_padded, e)
        return (dxs, dw2, dwg, dwi, dwo,
                np.zeros(g_of_tile.shape, jax.dtypes.float0),
                np.zeros(sizes_padded.shape, jax.dtypes.float0),
                np.zeros(live_tiles.shape, jax.dtypes.float0))

    ffn.defvjp(fwd, bwd)
    return ffn


@jax.custom_vjp
def gather_sum(z: jax.Array, sorted_tok: jax.Array,
               pos: jax.Array) -> jax.Array:
    """out[t] = Σ_slot z[pos[slot,t]] — the UNWEIGHTED combine gather for
    the scaled FFN (combine weights applied in-kernel; pos [k, S]).
    Residual-free: the VJP is the opposite gather, so nothing of the FFN
    output has to survive to (or be rebuilt for) the backward pass."""
    out = z[pos[0]]
    for slot in range(1, pos.shape[0]):
        out = out + z[pos[slot]]
    return out


def _gather_sum_fwd(z, sorted_tok, pos):
    return gather_sum(z, sorted_tok, pos), (sorted_tok, pos.shape)


def _gather_sum_bwd(res, dout):
    sorted_tok, pos_shape = res
    # sentinel rows (padding / dead tail) index the appended zero row
    dout1 = jnp.concatenate(
        [dout, jnp.zeros((1, dout.shape[-1]), dout.dtype)])
    return (dout1[sorted_tok], np.zeros(sorted_tok.shape,
                                        jax.dtypes.float0),
            np.zeros(pos_shape, jax.dtypes.float0))


gather_sum.defvjp(_gather_sum_fwd, _gather_sum_bwd)


def grouped_glu_ffn(xs: jax.Array, wg: jax.Array, wi: jax.Array,
                    wo: jax.Array, group_of_tile: jax.Array,
                    sizes_padded: jax.Array, live_tiles: jax.Array, *,
                    bm: int, bnf: int, bnd: int,
                    w: Optional[jax.Array] = None,
                    interpret: bool = False) -> jax.Array:
    """Grouped SwiGLU FFN over a block-aligned sorted row layout.

    xs [R_pad, d] (rows sorted by expert, padding rows zero), wg/wi
    [E, d, f], wo [E, f, d] → Y [R_pad, d].

    ``w=None``: unscaled output; the caller applies combine weights
    (gate-weight gradient stays in autodiff-land via
    :func:`gather_combine`). ``w`` [R_pad] (``sorted_w`` from
    :func:`aligned_dispatch`): the weights are fused into the down
    kernel, their gradient into the dgdu kernel, and the output is
    combined with the residual-free :func:`gather_sum` — the fast
    training path.
    """
    if w is None:
        return _build_ffn(bm, bnf, bnd, interpret)(
            xs, wg, wi, wo, group_of_tile, sizes_padded, live_tiles)
    if bm % _LANE:
        raise ValueError(
            f"grouped_glu_ffn(w=...): the fused-combine path's "
            f"lanes-major w tiles require bm % {_LANE} == 0, got bm={bm}"
            f"; pass w=None and apply combine weights via gather_combine")
    w2 = w.reshape(xs.shape[0] // bm, 1, bm)
    return _build_ffn_w(bm, bnf, bnd, interpret)(
        xs, w2, wg, wi, wo, group_of_tile, sizes_padded, live_tiles)
