"""Memory-efficient attention composed from XLA ops (no Pallas).

Flash-style chunked attention: query chunks are processed one at a time
against only the causally-visible key prefix, so the full [T, T] score
matrix never materializes in HBM — yet every op is a plain einsum XLA can
tile onto the MXU at full bf16 rate. ``jax.checkpoint`` per chunk keeps
backward memory at one chunk's scores.

Why this exists alongside ops/flash_attention.py (the Pallas kernel): on
some TPU runtimes (notably remote/chipless compile paths) Mosaic kernels
execute far below MXU rate while XLA einsums run at full speed; the engine
picks the implementation via config (model_factory.select_attention,
``tensor_parallel``-agnostic). Reference analogue: the v1 kernel-injection
attention vs the default torch path (deepspeed/ops/transformer/inference/
ds_attention.py) — same "fast kernel with a safe fallback" seam.
"""

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _chunk_attn(qg: jax.Array, k: jax.Array, v: jax.Array,
                q_start: int, k_start: int = 0, *,
                causal: bool, scale: float,
                alibi: Optional[jax.Array] = None,
                window: Optional[int] = None,
                key_mask: Optional[jax.Array] = None) -> jax.Array:
    """One query chunk vs a key slice starting at position ``k_start``.

    qg: [B, Cq, KV, G, Dh], k/v: [B, Tk, KV, Dh] → [B, Cq, KV, G, Dh].
    ``alibi``: per-head slopes [H] (BLOOM linear position bias).
    ``window``: causal sliding window (keys ≤ window behind the query).
    ``key_mask``: [B, Tk] bool, False = padding key (HF attention_mask —
    required for correctness on padded ENCODER batches, where padding
    is upstream of every real token).
    """
    b, cq, kvh, g, dh = qg.shape
    tk = k.shape[1]
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32) * scale
    qpos = q_start + jnp.arange(cq)
    kpos = k_start + jnp.arange(tk)
    if alibi is not None:
        rel = (kpos[None, :] - qpos[:, None]).astype(jnp.float32)
        scores = scores + alibi.reshape(kvh, g)[None, :, :, None, None] \
            * rel[None, None, None]
    if causal or window is not None:
        mask = qpos[:, None] >= kpos[None, :] if causal else \
            jnp.ones((cq, tk), bool)
        if window is not None:
            mask = mask & (kpos[None, :] > qpos[:, None] - window)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, None, :], scores,
                           _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bkgts,bskd->btkgd", probs, v)


def chunked_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = True,
                      q_offset: int = 0,
                      chunk_q: int = 256,
                      alibi: Optional[jax.Array] = None,
                      window: Optional[int] = None,
                      key_mask: Optional[jax.Array] = None) -> jax.Array:
    """q: [B, Tq, H, Dh], k/v: [B, Tk, KvH, Dh] → [B, Tq, H, Dh].

    The q-chunk loop is unrolled at trace time so each chunk attends to a
    STATIC causal key prefix — the causal lower triangle is genuinely
    skipped (half the FLOPs), not masked away. Each chunk is wrapped in
    ``jax.checkpoint``: backward recomputes that chunk's scores instead of
    saving [B, H, Tq, Tk] probabilities.
    """
    b, tq, h, dh = q.shape
    _, tk, kvh, _ = k.shape
    if tq <= chunk_q:
        return dot_product_attention_ref(q, k, v, causal, q_offset, alibi,
                                         window, key_mask)
    g = h // kvh
    scale = 1.0 / math.sqrt(dh)
    qg = q.reshape(b, tq, kvh, g, dh)

    def chunk_fn(qc, kc, vc, q_start, k_lo, km):
        return jax.checkpoint(
            partial(_chunk_attn, causal=causal, scale=scale, alibi=alibi,
                    window=window, key_mask=km),
            static_argnums=(3, 4))(qc, kc, vc, q_start, k_lo)

    # full chunks plus a static remainder chunk for non-multiple lengths
    bounds = list(range(0, tq, chunk_q)) + [tq]
    outs = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        q_start = lo + q_offset
        qc = jax.lax.slice_in_dim(qg, lo, hi, axis=1)
        k_lo = 0
        if causal or window is not None:
            # static key slice: causal prefix, minus keys left of the
            # sliding window (both bounds trace-time — the skipped FLOPs
            # are genuinely gone, not masked)
            k_end = min(tk, q_start + (hi - lo)) if causal else tk
            if window is not None:
                k_lo = max(0, q_start - window + 1)
            kc = jax.lax.slice_in_dim(k, k_lo, k_end, axis=1)
            vc = jax.lax.slice_in_dim(v, k_lo, k_end, axis=1)
        else:
            k_end = tk
            kc, vc = k, v
        km = None if key_mask is None else \
            jax.lax.slice_in_dim(key_mask, k_lo, k_end, axis=1)
        outs.append(chunk_fn(qc, kc, vc, q_start, k_lo, km))
    return jnp.concatenate(outs, axis=1).reshape(b, tq, h, dh)


def dot_product_attention_ref(q, k, v, causal=True, q_offset=0, alibi=None,
                              window=None, key_mask=None):
    """Single-chunk fallback (same math, full prefix)."""
    b, tq, h, dh = q.shape
    kvh = k.shape[2]
    qg = q.reshape(b, tq, kvh, h // kvh, dh)
    out = _chunk_attn(qg, k, v, q_offset, causal=causal,
                      scale=1.0 / math.sqrt(dh), alibi=alibi, window=window,
                      key_mask=key_mask)
    return out.reshape(b, tq, h, dh)
