"""JIT builder for the native (C++) ops.

Reference: ``op_builder/builder.py`` (OpBuilder ABC:116, jit_load:544 via
torch cpp_extension). TPU-native version: compile ``csrc/*.cpp`` with the
host toolchain into a shared library cached under
``~/.cache/deepspeed_tpu`` and load it through ctypes — no torch
dependency, no CUDA arch plumbing.
"""

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_CSRC = Path(__file__).resolve().parent.parent.parent / "csrc"
_CACHE = Path(os.environ.get(
    "DSTPU_CACHE_DIR", Path.home() / ".cache" / "deepspeed_tpu"))
_LOCK = threading.Lock()
_LIBS = {}

# NOTE: -ffast-math is deliberately absent — linking crtfastmath.o sets
# FTZ/DAZ process-wide at dlopen, silently changing numpy/jax numerics in
# the host process. The safe subset below still auto-vectorizes the loops.
_CXX_FLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
              "-march=native", "-fno-math-errno", "-fno-trapping-math",
              "-funroll-loops"]


class NativeOpBuilder:
    """One .cpp → one .so (reference OpBuilder: sources()/load())."""

    def __init__(self, name: str, sources=None):
        self.name = name
        self.sources = [str(_CSRC / s) for s in (sources or [f"{name}.cpp"])]

    def _signature(self) -> str:
        h = hashlib.sha256()
        for src in self.sources:
            with open(src, "rb") as fh:
                h.update(fh.read())
        h.update(" ".join(_CXX_FLAGS).encode())
        return h.hexdigest()[:16]

    def so_path(self) -> Path:
        return _CACHE / f"{self.name}_{self._signature()}.so"

    def build(self) -> Path:
        out = self.so_path()
        if out.exists():
            return out
        _CACHE.mkdir(parents=True, exist_ok=True)
        cxx = os.environ.get("CXX", "g++")
        # compile to a process-unique temp path, then atomically rename:
        # a concurrent process must never dlopen a half-written .so
        tmp = out.with_suffix(f".tmp{os.getpid()}.so")
        cmd = [cxx, *_CXX_FLAGS, "-o", str(tmp), *self.sources]
        logger.info(f"building native op '{self.name}': {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as exc:
            # -march=native can fail on exotic hosts: retry portable
            cmd_portable = [c for c in cmd if c != "-march=native"]
            try:
                subprocess.run(cmd_portable, check=True,
                               capture_output=True, text=True)
            except subprocess.CalledProcessError:
                raise RuntimeError(
                    f"native build of {self.name} failed:\n{exc.stderr}")
        os.replace(tmp, out)
        return out

    def load(self) -> ctypes.CDLL:
        with _LOCK:
            if self.name not in _LIBS:
                _LIBS[self.name] = ctypes.CDLL(str(self.build()))
            return _LIBS[self.name]


def is_native_available() -> bool:
    """True if a host C++ toolchain exists (tests skip native paths
    otherwise — reference pattern: builder.is_compatible())."""
    from shutil import which
    return which(os.environ.get("CXX", "g++")) is not None


def load_host_adam() -> ctypes.CDLL:
    lib = NativeOpBuilder("host_adam").load()
    lib.ds_host_adam_step.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int32]
    lib.ds_l2_norm_sq.restype = ctypes.c_double
    lib.ds_l2_norm_sq.argtypes = [ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_int64]
    lib.ds_bf16_to_f32.argtypes = [ctypes.POINTER(ctypes.c_uint16),
                                   ctypes.POINTER(ctypes.c_float),
                                   ctypes.c_int64]
    lib.ds_f32_to_bf16.argtypes = [ctypes.POINTER(ctypes.c_float),
                                   ctypes.POINTER(ctypes.c_uint16),
                                   ctypes.c_int64]
    lib.ds_host_adagrad_step.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.c_float, ctypes.c_float]
    lib.ds_host_lion_step.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.c_float, ctypes.c_float, ctypes.c_float]
    return lib


def load_async_io() -> ctypes.CDLL:
    lib = NativeOpBuilder("async_io").load()
    lib.ds_aio_create.restype = ctypes.c_void_p
    lib.ds_aio_create.argtypes = [ctypes.c_int32, ctypes.c_int32]
    lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
    for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                       ctypes.c_int64, ctypes.c_int64]
    lib.ds_aio_drain.restype = ctypes.c_int64
    lib.ds_aio_drain.argtypes = [ctypes.c_void_p]
    lib.ds_aio_completed.restype = ctypes.c_int64
    lib.ds_aio_completed.argtypes = [ctypes.c_void_p]
    return lib
