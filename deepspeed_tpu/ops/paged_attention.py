"""Paged attention — Pallas TPU kernel over a blocked KV arena.

The TPU-native replacement for the reference FastGen ragged kernels
(deepspeed/inference/v2/kernels/ragged_ops/: blocked_flash, blocked_kv_
rotary, logits_gather). The reference gathers paged KV with CUDA kernels
driven by per-sequence block tables; here the page table is a
scalar-prefetch operand, so each KV block's DMA source address is computed
*from the page table itself* inside the BlockSpec index map — the arena is
never gathered into a contiguous buffer in HBM.

Arena layout (one layer): ``[kv_heads, num_blocks + 1, block_size, head_dim]``.
The final block is a TRASH block: padded token slots and padded page-table
entries all point at it, so scatter/gather stay branch-free and
static-shape. Block size and head_dim are chosen to satisfy the (8, 128)
tile rule on the last two dims.

Two implementations with identical semantics (tested against each other):

- :func:`paged_attention_xla` — gather + masked softmax in pure XLA.
  Works everywhere, reference semantics, used for prefill chunks.
- :func:`paged_attention` — the Pallas kernel; online softmax accumulated
  across the page grid dimension, per-sequence block skipping via the
  prefetched context lengths.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Arena plumbing
# ---------------------------------------------------------------------------

def init_arena(num_layers: int, kv_heads: int, num_blocks: int,
               block_size: int, head_dim: int, dtype=jnp.bfloat16):
    """Paged KV arena with one extra trash block per layer.

    Returns {"k": A, "v": A} with A: [L, kvh, num_blocks+1, bs, dh].
    """
    shape = (num_layers, kv_heads, num_blocks + 1, block_size, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def write_kv(arena_k: jax.Array, arena_v: jax.Array, k: jax.Array,
             v: jax.Array, page_table: jax.Array, starts: jax.Array,
             counts: jax.Array):
    """Scatter a ragged chunk of new KV into one layer's arena.

    arena_k/arena_v: [kvh, nb+1, bs, dh]; k/v: [n, c, kvh, dh] new tokens
    (row i valid for j < counts[i]); page_table: [n, mb] physical block ids
    (padded entries may be anything — padded tokens route to trash);
    starts: [n] tokens already in KV per sequence.
    """
    kvh, nbp1, bs, dh = arena_k.shape
    n, c, _, _ = k.shape
    j = jnp.arange(c, dtype=jnp.int32)[None, :]                    # [1, c]
    pos = starts[:, None] + j                                      # [n, c]
    logical = pos // bs                                            # [n, c]
    offset = pos % bs
    phys = jnp.take_along_axis(page_table, jnp.minimum(
        logical, page_table.shape[1] - 1), axis=1)                 # [n, c]
    valid = j < counts[:, None]
    phys = jnp.where(valid, phys, nbp1 - 1)                        # → trash
    bi = phys.reshape(-1)
    oi = offset.reshape(-1)
    k_rows = k.reshape(n * c, kvh, dh).transpose(1, 0, 2)          # [kvh,nc,dh]
    v_rows = v.reshape(n * c, kvh, dh).transpose(1, 0, 2)
    arena_k = arena_k.at[:, bi, oi, :].set(
        k_rows.astype(arena_k.dtype), mode="drop")
    arena_v = arena_v.at[:, bi, oi, :].set(
        v_rows.astype(arena_v.dtype), mode="drop")
    return arena_k, arena_v


# ---------------------------------------------------------------------------
# XLA reference path (also the prefill path)
# ---------------------------------------------------------------------------

def paged_attention_xla(q: jax.Array, arena_k: jax.Array,
                        arena_v: jax.Array, page_table: jax.Array,
                        starts: jax.Array, counts: jax.Array) -> jax.Array:
    """Gather-then-attend over the paged arena (reference semantics).

    q: [n, c, H, dh] (query rows j >= counts[i] give garbage rows — the
    caller discards them); arena: [kvh, nb+1, bs, dh]; page_table: [n, mb];
    starts/counts: [n]. Returns [n, c, H, dh].
    """
    kvh, _, bs, dh = arena_k.shape
    n, c, h, _ = q.shape
    groups = h // kvh
    mb = page_table.shape[1]

    # [kvh, n, mb, bs, dh] → [n, kvh, mb*bs, dh]
    kg = arena_k[:, page_table].transpose(1, 0, 2, 3, 4) \
        .reshape(n, kvh, mb * bs, dh)
    vg = arena_v[:, page_table].transpose(1, 0, 2, 3, 4) \
        .reshape(n, kvh, mb * bs, dh)

    qg = q.reshape(n, c, kvh, groups, dh)
    s = jnp.einsum("nckgd,nksd->nkgcs", qg, kg.astype(q.dtype),
                   preferred_element_type=jnp.float32) / math.sqrt(dh)
    qpos = starts[:, None] + jnp.arange(c, dtype=jnp.int32)[None]  # [n, c]
    kpos = jnp.arange(mb * bs, dtype=jnp.int32)                    # [S]
    ctx = starts + counts                                          # [n]
    mask = (kpos[None, None] <= qpos[..., None]) & \
        (kpos[None, None] < ctx[:, None, None])                    # [n, c, S]
    s = jnp.where(mask[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(vg.dtype)
    out = jnp.einsum("nkgcs,nksd->nckgd", p, vg)
    return out.reshape(n, c, h, dh)


# ---------------------------------------------------------------------------
# Pallas kernel (decode / short-chunk path)
# ---------------------------------------------------------------------------

def _paged_kernel(pt_ref, starts_ref, counts_ref, q_ref, k_ref, v_ref,
                  o_ref, acc_ref, m_ref, l_ref, *, block_size: int,
                  chunk: int, scale: float):
    """Grid (n_seq, kvh, mb). Online softmax accumulated across the page
    (last, sequential) grid dimension in VMEM scratch.

    q_ref block: [1, 1, groups*chunk, dh] (rows = g*chunk + j);
    k_ref/v_ref block: [1, 1, block_size, dh] — the physical block chosen
    by the prefetched page table in the index map.
    """
    s_idx = pl.program_id(0)
    b = pl.program_id(2)
    nb = pl.num_programs(2)
    rows = q_ref.shape[2]

    @pl.when(b == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = starts_ref[s_idx]
    ctx = start + counts_ref[s_idx]

    @pl.when(b * block_size < ctx)
    def _compute():
        q = q_ref[0, 0]                                     # [rows, dh]
        k_blk = k_ref[0, 0]                                 # [bs, dh]
        v_blk = v_ref[0, 0]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        r = lax.broadcasted_iota(jnp.int32, (rows, block_size), 0)
        j = lax.rem(r, chunk)                               # query offset
        qpos = start + j
        kpos = b * block_size + \
            lax.broadcasted_iota(jnp.int32, (rows, block_size), 1)
        s = jnp.where((kpos <= qpos) & (kpos < ctx), s, _NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        blk_max = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, blk_max)
        p = jnp.exp(s - m_new[:, None])
        alive = m_new > _NEG_INF / 2
        p = jnp.where(alive[:, None], p, 0.0)
        corr = jnp.where(alive, jnp.exp(m_prev - m_new), 0.0)
        acc_ref[...] = acc_ref[...] * corr[:, None] + lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=1)
        m_ref[...] = m_new

    @pl.when(b == nb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def paged_attention(q: jax.Array, arena_k: jax.Array, arena_v: jax.Array,
                    page_table: jax.Array, starts: jax.Array,
                    counts: jax.Array, *, interpret: bool = False
                    ) -> jax.Array:
    """Pallas paged attention. Same contract as :func:`paged_attention_xla`.

    The page table is a scalar-prefetch operand: each (seq, head, page)
    program's K/V DMA reads block ``page_table[seq, page]`` directly from
    the arena — no HBM gather. Dead pages (beyond a sequence's context
    length) skip compute via ``pl.when``; their table entries must point at
    a real block (e.g. the trash block) so the DMA stays in bounds.
    """
    kvh, nbp1, bs, dh = arena_k.shape
    n, c, h, _ = q.shape
    groups = h // kvh
    mb = page_table.shape[1]
    rows = groups * c

    # [n, c, kvh, g, dh] → [n, kvh, g*c, dh] with row index = g*c + j
    qk = q.reshape(n, c, kvh, groups, dh).transpose(0, 2, 3, 1, 4) \
        .reshape(n, kvh, rows, dh)

    grid = (n, kvh, mb)
    kernel = functools.partial(_paged_kernel, block_size=bs, chunk=c,
                               scale=1.0 / math.sqrt(dh))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rows, dh),
                             lambda s, kh, b, pt, st, ct: (s, kh, 0, 0)),
                pl.BlockSpec((1, 1, bs, dh),
                             lambda s, kh, b, pt, st, ct:
                             (kh, pt[s, b], 0, 0)),
                pl.BlockSpec((1, 1, bs, dh),
                             lambda s, kh, b, pt, st, ct:
                             (kh, pt[s, b], 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rows, dh),
                lambda s, kh, b, pt, st, ct: (s, kh, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, dh), jnp.float32),
                pltpu.VMEM((rows,), jnp.float32),
                pltpu.VMEM((rows,), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, kvh, rows, dh), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), starts.astype(jnp.int32),
      counts.astype(jnp.int32), qk, arena_k, arena_v)

    # [n, kvh, g*c, dh] → [n, c, h, dh]
    return out.reshape(n, kvh, groups, c, dh).transpose(0, 3, 1, 2, 4) \
        .reshape(n, c, h, dh)


def supported(head_dim: int, block_size: int) -> bool:
    """Shape gate for the Pallas path: the KV block's last two dims
    (block_size, head_dim) must satisfy the (8, 128) tile rule, and the
    kernel only pays off on TPU. Query rows (groups × chunk) need no gate —
    Pallas pads the sublane dim."""
    return head_dim % 128 == 0 and block_size % 8 == 0 and \
        jax.default_backend() == "tpu"
