"""Paged attention — Pallas TPU kernel over a blocked KV arena.

The TPU-native replacement for the reference FastGen ragged kernels
(deepspeed/inference/v2/kernels/ragged_ops/: blocked_flash, blocked_kv_
rotary, logits_gather). The reference gathers paged KV with CUDA kernels
driven by per-sequence block tables; here the page table is a
scalar-prefetch operand, so each KV block's DMA source address is computed
*from the page table itself* inside the BlockSpec index map — the arena is
never gathered into a contiguous buffer in HBM.

Arena layout (one layer): ``[kv_heads, num_blocks + 1, block_size, head_dim]``.
The final block is a TRASH block: padded token slots and padded page-table
entries all point at it, so scatter/gather stay branch-free and
static-shape. Block size and head_dim are chosen to satisfy the (8, 128)
tile rule on the last two dims.

Two implementations with identical semantics (tested against each other):

- :func:`paged_attention_xla` — gather + masked softmax in pure XLA.
  Works everywhere, reference semantics, used for prefill chunks.
- :func:`paged_attention` — the Pallas kernel; online softmax accumulated
  across the page grid dimension, per-sequence block skipping via the
  prefetched context lengths.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Arena plumbing
# ---------------------------------------------------------------------------

def init_arena(num_layers: int, kv_heads: int, num_blocks: int,
               block_size: int, head_dim: int, dtype=jnp.bfloat16):
    """Paged KV arena with one extra trash block per layer.

    Returns {"k": A, "v": A} with A: [kvh, L*(num_blocks+1), bs, dh] —
    ONE flat block pool for all layers (layer l's logical block b lives at
    l*(num_blocks+1)+b; see :func:`layer_page_offset`). Flat so the
    engine's layer scan can thread the WHOLE arena as a carry and update
    it in place — a per-layer stacked arena would ride the scan as
    xs/ys, which cannot alias, forcing XLA to copy the full (multi-GB)
    arena every decode step.
    """
    shape = (kv_heads, num_layers * (num_blocks + 1), block_size, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def layer_page_offset(layer: jax.Array, num_blocks: int) -> jax.Array:
    """Absolute block id offset of ``layer``'s region in the flat pool."""
    return layer * (num_blocks + 1)


def write_kv(arena_k: jax.Array, arena_v: jax.Array, k: jax.Array,
             v: jax.Array, page_table: jax.Array, starts: jax.Array,
             counts: jax.Array, trash_block=None):
    """Scatter a ragged chunk of new KV into the arena.

    arena_k/arena_v: [kvh, NB, bs, dh] (one layer's region of the flat
    pool, or the whole pool with absolute page-table ids); k/v:
    [n, c, kvh, dh] new tokens (row i valid for j < counts[i]);
    page_table: [n, mb] physical block ids (padded entries may be
    anything — padded tokens route to ``trash_block``, default the pool's
    last block); starts: [n] tokens already in KV per sequence.
    """
    kvh, nbp1, bs, dh = arena_k.shape
    n, c, _, _ = k.shape
    if trash_block is None:
        trash_block = nbp1 - 1
    j = jnp.arange(c, dtype=jnp.int32)[None, :]                    # [1, c]
    pos = starts[:, None] + j                                      # [n, c]
    logical = pos // bs                                            # [n, c]
    offset = pos % bs
    phys = jnp.take_along_axis(page_table, jnp.minimum(
        logical, page_table.shape[1] - 1), axis=1)                 # [n, c]
    valid = j < counts[:, None]
    phys = jnp.where(valid, phys, trash_block)                     # → trash
    bi = phys.reshape(-1)
    oi = offset.reshape(-1)
    k_rows = k.reshape(n * c, kvh, dh).transpose(1, 0, 2)          # [kvh,nc,dh]
    v_rows = v.reshape(n * c, kvh, dh).transpose(1, 0, 2)
    arena_k = arena_k.at[:, bi, oi, :].set(
        k_rows.astype(arena_k.dtype), mode="drop")
    arena_v = arena_v.at[:, bi, oi, :].set(
        v_rows.astype(arena_v.dtype), mode="drop")
    return arena_k, arena_v


def copy_pages(arena: dict, src: jax.Array, dst: jax.Array,
               num_layers: int) -> dict:
    """Copy whole KV pages ``src[i] → dst[i]`` across every layer's region.

    The copy-on-write half of prefix caching: page tables are plain
    physical-id arrays, so several uids may reference the SAME page
    (full shared-prefix pages need no copy at all — the per-sequence
    ``starts``/``counts`` masking already keeps each row's reads inside
    its own context). Only a shared *partial* last page must be
    duplicated before its new owner appends into it, which is this op:
    one gather+scatter over the flat pool per {k, v}.

    arena: {"k","v"} flat pools [kvh, L*(nb+1), bs, dh]; src/dst: [m]
    logical page ids (< nb, layer-relative).
    """
    k = arena["k"]
    stride = k.shape[1] // num_layers            # nb + 1
    offs = jnp.arange(num_layers, dtype=jnp.int32)[:, None] * stride
    s = (offs + jnp.asarray(src, jnp.int32)[None, :]).reshape(-1)
    d = (offs + jnp.asarray(dst, jnp.int32)[None, :]).reshape(-1)
    return {"k": k.at[:, d].set(k[:, s]),
            "v": arena["v"].at[:, d].set(arena["v"][:, s])}


# ---------------------------------------------------------------------------
# XLA reference path (also the prefill path)
# ---------------------------------------------------------------------------

def _gather_pages(arena: jax.Array, page_table: jax.Array):
    """[kvh, nb+1, bs, dh] x [n, mb] → [n, kvh, mb*bs, dh]."""
    kvh, _, bs, dh = arena.shape
    n, mb = page_table.shape
    return arena[:, page_table].transpose(1, 0, 2, 3, 4) \
        .reshape(n, kvh, mb * bs, dh)


def _masked_attention(q: jax.Array, kg: jax.Array, vg: jax.Array,
                      mask: jax.Array, with_lse: bool):
    """Shared gathered-softmax core: q [n,c,h,dh], kg/vg [n,kvh,S,dh],
    mask broadcastable to [n,kvh,g,c,S]. Returns out [n,c,h,dh]
    (+ lse [n,c,h] fp32 when with_lse)."""
    n, c, h, dh = q.shape
    kvh = kg.shape[1]
    if h % kvh:
        raise ValueError(f"GQA requires kv heads to divide q heads "
                         f"(h={h}, kvh={kvh})")
    groups = h // kvh
    qg = q.reshape(n, c, kvh, groups, dh)
    s = jnp.einsum("nckgd,nksd->nkgcs", qg, kg.astype(q.dtype),
                   preferred_element_type=jnp.float32) / math.sqrt(dh)
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                     # [n,k,g,c]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("nkgcs,nksd->nckgd", p.astype(vg.dtype), vg) \
        / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = out.reshape(n, c, h, dh).astype(q.dtype)
    if not with_lse:
        return out
    lse = m + jnp.log(jnp.maximum(l, 1e-30))                    # [n,k,g,c]
    return out, lse.transpose(0, 3, 1, 2).reshape(n, c, h)


def paged_attention_xla(q: jax.Array, arena_k: jax.Array,
                        arena_v: jax.Array, page_table: jax.Array,
                        starts: jax.Array, counts: jax.Array) -> jax.Array:
    """Gather-then-attend over the paged arena (reference semantics).

    q: [n, c, H, dh] (query rows j >= counts[i] give garbage rows — the
    caller discards them); arena: [kvh, nb+1, bs, dh]; page_table: [n, mb];
    starts/counts: [n]. Returns [n, c, H, dh].
    """
    bs = arena_k.shape[2]
    n, c = q.shape[:2]
    mb = page_table.shape[1]
    kg = _gather_pages(arena_k, page_table)
    vg = _gather_pages(arena_v, page_table)
    qpos = starts[:, None] + jnp.arange(c, dtype=jnp.int32)[None]  # [n, c]
    kpos = jnp.arange(mb * bs, dtype=jnp.int32)                    # [S]
    ctx = starts + counts                                          # [n]
    mask = (kpos[None, None] <= qpos[..., None]) & \
        (kpos[None, None] < ctx[:, None, None])                    # [n, c, S]
    return _masked_attention(q, kg, vg, mask[:, None, None], False)


def paged_attention_hist_xla(q: jax.Array, arena_k: jax.Array,
                             arena_v: jax.Array, page_table: jax.Array,
                             starts: jax.Array):
    """HISTORY-only attention: row i's queries attend keys [0, starts[i])
    — the tokens already in the arena BEFORE the current chunk's write.
    Returns (out [n,c,h,dh], lse [n,c,h] fp32).

    Reading the pre-write arena is what breaks the per-layer write→read
    dependency XLA otherwise serializes (engine_v2.ragged_forward); the
    within-chunk causal part is computed separately and merged by
    logsumexp. Empty-history rows produce lse ≈ -1e30, so their (garbage)
    out vanishes in the merge — no special-casing for fresh rows mixed
    into a continuation batch.
    """
    bs = arena_k.shape[2]
    mb = page_table.shape[1]
    kg = _gather_pages(arena_k, page_table)
    vg = _gather_pages(arena_v, page_table)
    kpos = jnp.arange(mb * bs, dtype=jnp.int32)
    mask = kpos[None, :] < starts[:, None]                      # [n, S]
    return _masked_attention(q, kg, vg, mask[:, None, None, None, :],
                             True)


def merge_attention(out_a, lse_a, out_b, lse_b):
    """Combine two attention partials over DISJOINT key sets via their
    logsumexps (the flash-attention merge): outs [n,c,h,dh], lses
    [n,c,h] → merged out."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    denom = jnp.maximum(wa + wb, 1e-30)[..., None]
    return (out_a.astype(jnp.float32) * wa[..., None]
            + out_b.astype(jnp.float32) * wb[..., None]) / denom


def causal_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array):
    """Plain causal attention over one chunk returning (out, lse) for the
    history merge — XLA path ([n,c,h,dh] layout, GQA via head groups)."""
    c = q.shape[1]
    kg = k.transpose(0, 2, 1, 3)                                # [n,kvh,c,d]
    vg = v.transpose(0, 2, 1, 3)
    i = jnp.arange(c, dtype=jnp.int32)
    mask = (i[None, :] <= i[:, None])[None, None, None]
    return _masked_attention(q, kg, vg, mask, True)


# ---------------------------------------------------------------------------
# Pallas kernel (decode / short-chunk path)
# ---------------------------------------------------------------------------

def _paged_kernel(pt_ref, starts_ref, counts_ref, q_ref, k_hbm, v_hbm,
                  o_ref, *rest, block_size: int,
                  chunk: int, scale: float, mb: int,
                  with_lse: bool = False):
    """Grid (n_seq, kvh): ONE program per (sequence, kv head) that walks
    this sequence's pages with double-buffered manual DMAs from the
    HBM-resident arena.

    A (seq, head, page) grid would be thousands of sequential tiny
    programs per layer (measured 310 ms vs 1.5 ms per 1B-model decode
    step); here pages are an in-kernel ``fori_loop`` with the next page's
    DMA in flight while the current one computes — the reference
    blocked_flash/paged-KV structure.

    q_ref block: [1, 1, rows, dh] (row = g*chunk + j); k_hbm/v_hbm: the
    FULL arena [kvh, NB, bs, dh] left in ANY/HBM memory space; k_buf/
    v_buf: [2, bs, dh] VMEM double buffers. With ``with_lse`` an extra
    [1, 1, rows] f32 output carries each row's logsumexp (the
    partial-attention merge needs it — fused decode's history part).
    """
    if with_lse:
        lse_ref, k_buf, v_buf, sem_k, sem_v = rest
    else:
        k_buf, v_buf, sem_k, sem_v = rest
    s_idx = pl.program_id(0)
    kh = pl.program_id(1)
    rows = q_ref.shape[2]
    start = starts_ref[s_idx]
    ctx = start + counts_ref[s_idx]
    npages = jnp.minimum(lax.div(ctx + block_size - 1,
                                 jnp.int32(block_size)), mb)

    def copy_in(page_i, slot):
        page = pt_ref[s_idx, page_i]
        pltpu.make_async_copy(k_hbm.at[kh, page], k_buf.at[slot],
                              sem_k.at[slot]).start()
        pltpu.make_async_copy(v_hbm.at[kh, page], v_buf.at[slot],
                              sem_v.at[slot]).start()

    @pl.when(npages > 0)
    def _run():
        copy_in(0, 0)
        q = q_ref[0, 0]                                     # [rows, dh]

        def body(b, carry):
            acc, m_prev, l_prev = carry
            slot = lax.rem(b, 2)

            @pl.when(b + 1 < npages)
            def _prefetch():
                copy_in(b + 1, lax.rem(b + 1, 2))

            pltpu.make_async_copy(k_hbm.at[kh, 0], k_buf.at[slot],
                                  sem_k.at[slot]).wait()
            pltpu.make_async_copy(v_hbm.at[kh, 0], v_buf.at[slot],
                                  sem_v.at[slot]).wait()
            k_blk = k_buf[slot]
            v_blk = v_buf[slot]
            s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
            r = lax.broadcasted_iota(jnp.int32, (rows, block_size), 0)
            j = lax.rem(r, chunk)                           # query offset
            qpos = start + j
            kpos = b * block_size + \
                lax.broadcasted_iota(jnp.int32, (rows, block_size), 1)
            s = jnp.where((kpos <= qpos) & (kpos < ctx), s, _NEG_INF)

            blk_max = jnp.max(s, axis=1)
            m_new = jnp.maximum(m_prev, blk_max)
            p = jnp.exp(s - m_new[:, None])
            # float mask arithmetic, NOT a bool broadcast: Mosaic can't
            # insert a minor dim on i1 vectors
            alive = (m_new > _NEG_INF / 2).astype(jnp.float32)
            p = p * alive[:, None]
            corr = jnp.exp(m_prev - m_new) * alive
            acc = acc * corr[:, None] + lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            l = l_prev * corr + jnp.sum(p, axis=1)
            return acc, m_new, l

        acc0 = jnp.zeros((rows, q_ref.shape[3]), jnp.float32)
        m0 = jnp.full((rows,), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((rows,), jnp.float32)
        acc, m, l = lax.fori_loop(0, npages, body, (acc0, m0, l0))
        l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
        if with_lse:
            lse_ref[0, 0] = jnp.where(m > _NEG_INF / 2, m + jnp.log(l),
                                      _NEG_INF)[:, None]

    @pl.when(npages == 0)
    def _empty():
        o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])
        if with_lse:
            lse_ref[0, 0] = jnp.full_like(lse_ref[0, 0], _NEG_INF)


def paged_attention(q: jax.Array, arena_k: jax.Array, arena_v: jax.Array,
                    page_table: jax.Array, starts: jax.Array,
                    counts: jax.Array, *, interpret: bool = False
                    ) -> jax.Array:
    """Pallas paged attention. Same contract as :func:`paged_attention_xla`.

    The page table is a scalar-prefetch operand read INSIDE the kernel to
    drive manual double-buffered DMAs from the HBM arena — no HBM gather,
    no per-page grid step. Dead pages (beyond a sequence's context length)
    are skipped by the dynamic in-kernel loop bound.
    """
    kvh, nbp1, bs, dh = arena_k.shape
    n, c, h, _ = q.shape
    groups = h // kvh
    mb = page_table.shape[1]
    rows = groups * c

    # [n, c, kvh, g, dh] → [n, kvh, g*c, dh] with row index = g*c + j
    qk = q.reshape(n, c, kvh, groups, dh).transpose(0, 2, 3, 1, 4) \
        .reshape(n, kvh, rows, dh)

    grid = (n, kvh)
    kernel = functools.partial(_paged_kernel, block_size=bs, chunk=c,
                               scale=1.0 / math.sqrt(dh), mb=mb)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rows, dh),
                             lambda s, kh, pt, st, ct: (s, kh, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rows, dh),
                lambda s, kh, pt, st, ct: (s, kh, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, bs, dh), arena_k.dtype),
                pltpu.VMEM((2, bs, dh), arena_v.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, kvh, rows, dh), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), starts.astype(jnp.int32),
      counts.astype(jnp.int32), qk, arena_k, arena_v)

    # [n, kvh, g*c, dh] → [n, c, h, dh]
    return out.reshape(n, kvh, groups, c, dh).transpose(0, 3, 1, 2, 4) \
        .reshape(n, c, h, dh)


def paged_attention_with_lse(q: jax.Array, arena_k: jax.Array,
                             arena_v: jax.Array, page_table: jax.Array,
                             starts: jax.Array, counts: jax.Array, *,
                             interpret: bool = False):
    """Pallas paged attention returning (out, lse [n, c, h] fp32) for the
    partial-attention merge. ``counts=0`` gives HISTORY-only semantics
    (keys [0, starts)) — the fused decode loop's arena part, where the
    arena is a read-only input rather than a carried/donated buffer."""
    kvh, nbp1, bs, dh = arena_k.shape
    n, c, h, _ = q.shape
    groups = h // kvh
    mb = page_table.shape[1]
    rows = groups * c

    qk = q.reshape(n, c, kvh, groups, dh).transpose(0, 2, 3, 1, 4) \
        .reshape(n, kvh, rows, dh)

    grid = (n, kvh)
    kernel = functools.partial(_paged_kernel, block_size=bs, chunk=c,
                               scale=1.0 / math.sqrt(dh), mb=mb,
                               with_lse=True)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rows, dh),
                             lambda s, kh, pt, st, ct: (s, kh, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, rows, dh),
                             lambda s, kh, pt, st, ct: (s, kh, 0, 0)),
                pl.BlockSpec((1, 1, rows, 1),
                             lambda s, kh, pt, st, ct: (s, kh, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, bs, dh), arena_k.dtype),
                pltpu.VMEM((2, bs, dh), arena_v.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((n, kvh, rows, dh), q.dtype),
                   jax.ShapeDtypeStruct((n, kvh, rows, 1), jnp.float32)],
        interpret=interpret,
    )(page_table.astype(jnp.int32), starts.astype(jnp.int32),
      counts.astype(jnp.int32), qk, arena_k, arena_v)

    out = out.reshape(n, kvh, groups, c, dh).transpose(0, 3, 1, 2, 4) \
        .reshape(n, c, h, dh)
    lse = lse.reshape(n, kvh, groups, c).transpose(0, 3, 1, 2) \
        .reshape(n, c, h)
    return out, lse


def supported(head_dim: int, block_size: int) -> bool:
    """Shape gate for the Pallas path: the KV block's last two dims
    (block_size, head_dim) must satisfy the (8, 128) tile rule, and the
    kernel only pays off on TPU. Query rows (groups × chunk) need no gate —
    Pallas pads the sublane dim."""
    return head_dim % 128 == 0 and block_size % 8 == 0 and \
        jax.default_backend() == "tpu"
