"""Block quantization kernels (int8 / int4 / fp8) — ZeRO++ & inference.

TPU-native replacement for the reference's CUDA quantizer family
(csrc/quantization/{quantize,dequantize,quant_reduce,quantize_intX}.cu,
csrc/fp_quantizer/) used by ZeRO++ qwZ/qgZ (runtime/zero/stage3.py:1636,
runtime/comm/coalesced_collectives.py) and inference weight quant.

Layout: a flat [n] tensor is viewed as [n/B, B] blocks; each block gets one
fp32 scale (symmetric absmax) or (scale, zero-point) pair (asymmetric
min/max). int4 packs two values per uint8 byte. All shapes static; the XLA
path is a fused reshape→reduce→round (one HBM pass); the Pallas kernel does
the same tile-resident for use inside larger fused kernels.

Error bound (symmetric int8): |x - dq(q(x))| ≤ absmax(block) / 254
per element — tested in tests/test_quantization.py.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 256


def _as_blocks(x: jax.Array, block: int) -> jax.Array:
    n = x.shape[0]
    if n % block:
        raise ValueError(f"length {n} not divisible by block {block} "
                         f"(pad upstream)")
    return x.reshape(n // block, block)


# ---------------------------------------------------------------------------
# XLA path
# ---------------------------------------------------------------------------

def quantize_blocks(x: jax.Array, block: int = DEFAULT_BLOCK, bits: int = 8,
                    symmetric: bool = True
                    ) -> Tuple[jax.Array, jax.Array, Optional[jax.Array]]:
    """flat f32/bf16 [n] → (q, scales [n/B] f32, zero_points or None).

    bits=8: q int8 in [-127, 127] (symmetric) or uint8 with zero-point.
    bits=4: q uint8 [n/2] — two nibbles per byte, values in [-7, 7] + 8.
    """
    xb = _as_blocks(x.astype(jnp.float32), block)
    qmax = 127.0 if bits == 8 else 7.0
    if symmetric:
        absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        scales = absmax / qmax
        safe = jnp.where(scales > 0, scales, 1.0)
        q = jnp.clip(jnp.round(xb / safe), -qmax, qmax)
        zp = None
    else:
        lo = jnp.min(xb, axis=1, keepdims=True)
        hi = jnp.max(xb, axis=1, keepdims=True)
        scales = (hi - lo) / (2 * qmax)
        safe = jnp.where(scales > 0, scales, 1.0)
        zp = lo
        q = jnp.clip(jnp.round((xb - lo) / safe) - qmax, -qmax, qmax)
    if bits == 8:
        packed = q.astype(jnp.int8).reshape(-1)
    elif bits == 4:
        u = (q + 8).astype(jnp.uint8).reshape(-1, 2)
        packed = (u[:, 0] | (u[:, 1] << 4)).astype(jnp.uint8)
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    return packed, scales[:, 0], (zp[:, 0] if zp is not None else None)


def dequantize_blocks(q: jax.Array, scales: jax.Array,
                      zero_points: Optional[jax.Array] = None,
                      block: int = DEFAULT_BLOCK, bits: int = 8,
                      dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_blocks` → flat [n] of ``dtype``."""
    if bits == 8:
        vals = q.astype(jnp.float32).reshape(-1, block)
    elif bits == 4:
        lo = (q & 0xF).astype(jnp.int32) - 8
        hi = (q >> 4).astype(jnp.int32) - 8
        vals = jnp.stack([lo, hi], axis=1).reshape(-1, block) \
            .astype(jnp.float32)
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    out = vals * scales[:, None]
    if zero_points is not None:
        qmax = 127.0 if bits == 8 else 7.0
        out = (vals + qmax) * scales[:, None] + zero_points[:, None]
    return out.reshape(-1).astype(dtype)


def fp8_cast(x: jax.Array, dtype=jnp.float8_e4m3fn) -> jax.Array:
    """FP8 weight cast (reference csrc/fp_quantizer FP6/FP8 path — on TPU
    fp8 is a native dtype; the 'kernel' is a convert XLA fuses)."""
    return x.astype(dtype)


_FP8_E4M3_MAX = 448.0


def quantize_fp8_blocks(x: jax.Array, block: int = DEFAULT_BLOCK):
    """Block-scaled fp8-e4m3 quantization (reference ops/fp_quantizer
    FP_Quantize with q_bits=8, mantissa_bits=3 — the 'FP6-LLM' family).

    Per-block absmax scaling stretches each block onto the ±448 e4m3
    range, so small-magnitude weight blocks keep their mantissa precision
    instead of flushing toward zero. Returns (q fp8 [n], scales fp32
    [n/block])."""
    xb = _as_blocks(x.astype(jnp.float32), block)
    absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.maximum(absmax / _FP8_E4M3_MAX, 1e-12)
    q = (xb / scale).astype(jnp.float8_e4m3fn).reshape(-1)
    return q, scale[:, 0]


def dequantize_fp8_blocks(q: jax.Array, scales: jax.Array,
                          block: int = DEFAULT_BLOCK,
                          dtype=jnp.float32) -> jax.Array:
    xb = _as_blocks(q, block).astype(jnp.float32) * scales[:, None]
    return xb.reshape(-1).astype(dtype)


# ---------------------------------------------------------------------------
# Pallas fused kernel (int8 symmetric — the qwZ/qgZ hot path)
# ---------------------------------------------------------------------------

def _quant_kernel(x_ref, q_ref, s_ref, *, qmax: float):
    x = x_ref[...].astype(jnp.float32)                  # [rows, B]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q_ref[...] = jnp.clip(jnp.round(x / safe), -qmax, qmax).astype(jnp.int8)
    s_ref[...] = scale[:, 0]


def quantize_blocks_pallas(x: jax.Array, block: int = DEFAULT_BLOCK,
                           rows_per_program: int = 64,
                           interpret: bool = False
                           ) -> Tuple[jax.Array, jax.Array]:
    """Fused symmetric-int8 block quantize (one VMEM-resident pass)."""
    xb = _as_blocks(x, block)
    nb = xb.shape[0]
    rp = min(rows_per_program, nb)
    while nb % rp:
        rp -= 1
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=127.0),
        grid=(nb // rp,),
        in_specs=[pl.BlockSpec((rp, block), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((rp, block), lambda i: (i, 0)),
                   pl.BlockSpec((rp,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((nb, block), jnp.int8),
                   jax.ShapeDtypeStruct((nb,), jnp.float32)],
        interpret=interpret,
    )(xb)
    return q.reshape(-1), s
