"""Int8 / fp8-e4m3 / int4 / fp6-e3m2 weight-only quantized serving
(Pallas dequant-in-VMEM matmul).

Reference analogue: the weight-quantized inference linears
(inference/quantization/ + module_inject/module_quantize.py and the
INT8 paths in csrc/quantization/). Quantization is symmetric
per-output-channel (scale = max|w|/127 over the contraction dim) — the
standard near-lossless weight-only recipe.

What this buys on TPU — measured honestly on v5e (1.27B llama, batch
16 decode, per-step time isolated from prefill):
- **Memory capacity**: matmul weights at half the HBM — a chip serves
  a ~2x larger model (the reason the reference ships INT8 inference).
- **Decode-speed parity**: 7.77 ms/step int8 vs 7.85 ms/step bf16.
  XLA's bf16 decode matmuls stream weights at ~320 GB/s on this chip;
  the kernel's int8 stream (~160 GB/s of int8 ≈ 320 bf16-equivalent)
  only reaches that WITH the `dimension_semantics` pipelining hint
  (without it: 9.9 ms/step, 25% slower). The XLA alternative is worse:
  `dot(x, w_int8.astype(bf16))` materializes the dequantized weight
  (0.71x). A future >2x win needs int8 DMA to outpace bf16 — revisit
  per libtpu generation.
- **int4**: quarter the weight HBM; end-to-end serving measured
  slightly FASTER than bf16 on v5e (bench_inference, 1B llama, 8
  mixed prompts, 32 new tokens: padded 870 vs 831 tok/s, ragged 700
  vs 606) — the nibble unpack is free next to the halved weight DMA.
  15-level grid though: validate task quality before shipping int4.
- **fp6-e3m2**: 3/8 the weight HBM with float quality (better than
  int4 on gaussian weights — more levels where weights cluster), but
  the 4-plane unpack + exponent decode costs real VPU time: measured
  ~28% slower than bf16 end-to-end on the same v5e workload (padded
  596 vs 831 tok/s). A CAPACITY point between int4 and int8, not a
  speed one — pick it when int4 quality fails and int8 doesn't fit.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

#: suffix convention: a params dict carrying ``<name>`` as int8 plus
#: ``<name>_scale`` routes matmuls through qmatmul (transformer.linear_2d)
SCALE_SUFFIX = "_scale"


#: e4m3fn max finite value — the fp8 analogue of int8's 127
_E4M3_MAX = 448.0

#: e3m2 max finite value: (4+3)·2^(7-5) = 28
_E3M2_MAX = 28.0


def _fp6_encode(a: jax.Array) -> jax.Array:
    """|w|/scale in [0, 28] → e3m2 bit pattern (5 bits, sign added by the
    caller): e_field (3 bits, bias 3, subnormals at e=0) | mantissa (2).

    All representable magnitudes are (4+m)·2^(e−5) for e≥1 plus the
    subnormal grid m·2^−4 — i.e. multiples of 2^E with a/2^E ∈ [4, 8)
    (E = floor(log2 a) − 2, floored at −4). Round onto that grid, bump
    the exponent when rounding hits 8.
    """
    a = jnp.clip(a.astype(jnp.float32), 0.0, _E3M2_MAX)
    E = jnp.floor(jnp.log2(jnp.maximum(a, 2.0 ** -4))) - 2
    E = jnp.clip(E, -4, 2)
    q = jnp.round(a * 2.0 ** (-E))
    bump = q >= 8
    E = jnp.where(bump, E + 1, E)
    q = jnp.where(bump, 4.0, q)
    q = jnp.where(E > 2, 7.0, q)   # overflow clamp → 28
    E = jnp.minimum(E, 2)
    qi = q.astype(jnp.int32)
    Ei = E.astype(jnp.int32)
    e_field = jnp.where(qi >= 4, Ei + 5, 0)
    m = jnp.where(qi >= 4, qi - 4, qi)
    return (e_field << 2) | m


def _fp6_decode_bits(v: jax.Array) -> jax.Array:
    """6-bit e3m2 pattern (int32) → float32 value."""
    s = (v >> 5) & 1
    e = (v >> 2) & 7
    m = (v & 3).astype(jnp.float32)
    mag = jnp.where(e > 0,
                    (1 << e).astype(jnp.float32) * 0.03125 * (4.0 + m),
                    m * 0.0625)
    return jnp.where(s == 1, -mag, mag)


def _fp6_pack(v6: jax.Array) -> jax.Array:
    """[..., K, N] 6-bit patterns (int32) → packed uint8
    [..., 3, K/4, N] (plane-major split-quarters: byte triple
    (p0[r], p1[r], p2[r]) encodes rows r, K/4+r, K/2+r, 3K/4+r —
    plane-major so a Pallas block keeps (K-rows, N) as the tiled
    (sublane, lane) trailing dims)."""
    k = v6.shape[-2]
    kq = k // 4
    v0 = v6[..., :kq, :]
    v1 = v6[..., kq:2 * kq, :]
    v2 = v6[..., 2 * kq:3 * kq, :]
    v3 = v6[..., 3 * kq:, :]
    r0 = (v0 << 2) | (v1 >> 4)
    r1 = ((v1 & 15) << 4) | (v2 >> 2)
    r2 = ((v2 & 3) << 6) | v3
    return jnp.stack([r0, r1, r2], axis=-3).astype(jnp.uint8)


def _fp6_unpack_bits(packed: jax.Array):
    """packed [..., 3, K/4, N] uint8 → four int32 quarter-planes."""
    p = packed.astype(jnp.int32)
    r0 = p[..., 0, :, :]
    r1 = p[..., 1, :, :]
    r2 = p[..., 2, :, :]
    v0 = r0 >> 2
    v1 = ((r0 & 3) << 4) | (r1 >> 4)
    v2 = ((r1 & 15) << 2) | (r2 >> 6)
    v3 = r2 & 63
    return v0, v1, v2, v3


def unpack_fp6(packed: jax.Array) -> jax.Array:
    """packed uint8 [..., 3, K/4, N] → float32 [..., K, N]."""
    return jnp.concatenate([_fp6_decode_bits(v) for v in
                            _fp6_unpack_bits(packed)], axis=-2)


def quantize_weight(w: jax.Array, mode: str = "int8"
                    ) -> Tuple[jax.Array, jax.Array]:
    """[K, N] float → (quantized, f32 scale [N]); symmetric
    per-output-channel. Works on stacked [L, K, N] too (scale [L, N]).

    ``mode="int8"``: uniform 8-bit grid (scale = max|w|/127).
    ``mode="fp8"``: float8_e4m3fn storage (scale = max|w|/448) — same
    byte width, but the exponent bits spend precision where weights
    cluster near zero; reference analogue: ops/fp_quantizer (FP6-LLM /
    fp8_gemm), here serving-only like the int8 path.
    ``mode="int4"``: uniform 4-bit grid (scale = max|w|/7), TWO values
    packed per uint8 byte → storage [K/2, N]: row r holds w[r] in the
    low nibble and w[K/2 + r] in the high nibble (split-halves layout,
    so the kernel reads one contiguous uint8 tile and two matching x
    column tiles — no in-kernel interleave). Reference analogue: the
    4-bit quantizer kernels under csrc/quantization (qwZ block quant)
    and inference/quantization 4-bit serving.
    ``mode="fp6"``: e3m2 floats (scale = max|w|/28), FOUR values packed
    per THREE bytes → storage [3, K/4, N] uint8 (plane-major
    split-quarters layout, same one-contiguous-tile property).
    Reference analogue: the FP6-LLM path in ops/fp_quantizer
    (csrc/fp_quantizer/fp_quantize.cu).
    """
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    if mode == "fp8":
        scale = jnp.maximum(absmax / _E4M3_MAX, 1e-12)
        q = (w.astype(jnp.float32) / scale[..., None, :]).astype(
            jnp.float8_e4m3fn)
        return q, scale
    if mode == "fp6":
        k = w.shape[-2]
        if k % 4:
            raise ValueError(f"fp6 packing needs K % 4 == 0; got K={k}")
        scale = jnp.maximum(absmax / _E3M2_MAX, 1e-12)
        a = w.astype(jnp.float32) / scale[..., None, :]
        bits = _fp6_encode(jnp.abs(a))
        bits = bits | jnp.where(a < 0, 32, 0)
        return _fp6_pack(bits), scale
    if mode == "int4":
        k = w.shape[-2]
        if k % 2:
            raise ValueError(f"int4 packing needs even K; got K={k}")
        scale = jnp.maximum(absmax / 7.0, 1e-12)
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                     -7, 7).astype(jnp.int32)
        lo = q[..., :k // 2, :] & 0xF
        hi = q[..., k // 2:, :] & 0xF
        return ((hi << 4) | lo).astype(jnp.uint8), scale
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _nibble(v: jax.Array) -> jax.Array:
    """Sign-extend a 4-bit field held in the low bits of an int32."""
    return (jnp.bitwise_xor(v & 0xF, 8) - 8)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """packed uint8 [..., K/2, N] → int32 [..., K, N] (split-halves
    inverse of quantize_weight mode='int4')."""
    p = packed.astype(jnp.int32)
    return jnp.concatenate([_nibble(p), _nibble(p >> 4)], axis=-2)


def dequantize_weight(q: jax.Array, scale: jax.Array) -> jax.Array:
    if q.dtype == jnp.uint8 and q.ndim >= 3 and q.shape[-3] == 3 and \
            q.ndim == scale.ndim + 2:   # fp6 packed [..., 3, K/4, N]
        return unpack_fp6(q) * scale[..., None, :]
    if q.dtype == jnp.uint8:   # int4 packed
        return unpack_int4(q).astype(jnp.float32) * scale[..., None, :]
    return q.astype(jnp.float32) * scale[..., None, :]


def _tile(dim: int) -> int:
    """Largest supported block size dividing ``dim`` (0 = not tileable)."""
    return 512 if dim % 512 == 0 else (256 if dim % 256 == 0 else 0)


def _pad_m(x: jax.Array, m: int, axis: int):
    """Pad the M (rows) axis up to a sublane multiple; returns
    (padded x, padded m, block m). Shared by every kernel wrapper so a
    tiling tweak can't silently diverge between them."""
    mp = max(8, -(-m // 8) * 8)
    bm = mp if mp <= 256 else 256
    if mp % bm:
        mp = -(-mp // bm) * bm
    if mp == m:
        return x, mp, bm
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mp - m)
    return jnp.pad(x, pad), mp, bm


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x_blk = x_ref[...]
    # int8 → bf16 in VMEM; MXU accumulates fp32 (preferred_element_type)
    w_blk = w_ref[...].astype(jnp.bfloat16)
    acc_ref[...] += lax.dot_general(
        x_blk, w_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = (acc_ref[...] * s_ref[0][None, :]).astype(o_ref.dtype)


def _qmm(x: jax.Array, w: jax.Array, scale: jax.Array, bm: int, bn: int,
         bk: int, interpret: bool, out_dtype) -> jax.Array:
    m, k = x.shape
    _, n = w.shape
    nk = k // bk
    s2 = scale.astype(jnp.float32).reshape(1, n)
    kw = {}
    if not interpret:
        # m/n grid dims are embarrassingly parallel; telling Mosaic so
        # improves DMA pipelining (measured 4.57 -> 2.92 ms on the
        # 24-layer decode chain probe)
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        functools.partial(_qmm_kernel, nk=nk),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
        **kw,
    )(x, w, s2)


def _unpack_int4_planes(w_blk):
    """uint8 [bk, bn] → (lo, hi) bf16 planes (rows kk / Kp+kk)."""
    p = w_blk.astype(jnp.int32)
    return (_nibble(p).astype(jnp.bfloat16),
            _nibble(p >> 4).astype(jnp.bfloat16))


def _unpack_fp6_planes(w_blk):
    """uint8 [3, bk, bn] → four bf16 quarter-planes (e3m2 decoded)."""
    return tuple(_fp6_decode_bits(v).astype(jnp.bfloat16)
                 for v in _fp6_unpack_bits(w_blk))


_PACKED = {
    # planes per byte-group, in-kernel unpack, whole-array unpack
    "int4": (2, _unpack_int4_planes, unpack_int4),
    "fp6": (4, _unpack_fp6_planes, unpack_fp6),
}


def _make_packed_kernel(planes: int, unpack, batched: bool):
    """One kernel body serves int4 and fp6, dense and grouped: the x
    column tiles matching each packed plane arrive as separate refs."""
    def kernel(*refs, nk: int):
        x_refs = refs[:planes]
        w_ref, s_ref, o_ref, acc_ref = refs[planes:]
        k = pl.program_id(3 if batched else 2)

        @pl.when(k == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        w_blk = w_ref[0] if batched else w_ref[...]
        for x_ref, plane in zip(x_refs, unpack(w_blk)):
            acc_ref[...] += lax.dot_general(
                x_ref[0] if batched else x_ref[...], plane,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(k == nk - 1)
        def _flush():
            if batched:
                o_ref[0] = (acc_ref[...] *
                            s_ref[0, 0][None, :]).astype(o_ref.dtype)
            else:
                o_ref[...] = (acc_ref[...] *
                              s_ref[0][None, :]).astype(o_ref.dtype)
    return kernel


def _packed_qmm(x, w_q, scale, *, mode: str, interpret: bool, out_dtype,
                batched: bool):
    """Shared wrapper for ALL bit-packed weight matmuls (int4/fp6 ×
    dense/grouped): one home for shape validation, tiling, M padding,
    BlockSpecs and the XLA fallback, so a pipelining or tiling tweak
    cannot silently diverge between formats."""
    planes, unpack, unpack_all = _PACKED[mode]
    if batched:
        g, m, k = x.shape
    else:
        m, k = x.shape
    kp, n = w_q.shape[-2], w_q.shape[-1]
    if planes * kp != k:
        raise ValueError(
            f"qmatmul({mode}): packed rows {kp} != K/{planes} for x "
            f"K={k}")
    bk, bn = _tile(kp), _tile(n)
    out_dtype = out_dtype or x.dtype
    if not bk or not bn:
        logger.warning(
            f"qmatmul{'_batched' if batched else ''}({mode}): "
            f"K/{planes}={kp}/N={n} not tileable; using XLA dequant path")
        if batched:
            w = unpack_all(w_q).astype(jnp.float32) * scale[:, None, :]
            return jnp.einsum("gmk,gkn->gmn", x.astype(jnp.float32),
                              w).astype(out_dtype)
        w = unpack_all(w_q).astype(jnp.float32) * scale[None, :]
        return (x.astype(jnp.float32) @ w).astype(out_dtype)
    xp, mp, bm = _pad_m(x, m, 1 if batched else 0)
    nk = kp // bk
    kern = functools.partial(_make_packed_kernel(planes, unpack, batched),
                             nk=nk)
    kw = {}
    if batched:
        x_specs = [
            pl.BlockSpec((1, bm, bk), lambda gg, i, j, kk, _q=q, _nk=nk:
                         (gg, i, kk + _q * _nk)) for q in range(planes)]
        w_spec = pl.BlockSpec((1, bk, bn),
                              lambda gg, i, j, kk: (gg, kk, j))             if mode == "int4" else             pl.BlockSpec((1, 3, bk, bn),
                         lambda gg, i, j, kk: (gg, 0, kk, j))
        s_arr = scale.astype(jnp.float32).reshape(g, 1, n)
        s_spec = pl.BlockSpec((1, 1, bn), lambda gg, i, j, kk: (gg, 0, j))
        out_spec = pl.BlockSpec((1, bm, bn),
                                lambda gg, i, j, kk: (gg, i, j))
        grid = (g, mp // bm, n // bn, nk)
        out_shape = jax.ShapeDtypeStruct((g, mp, n), out_dtype)
        if not interpret:
            kw["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary"))
    else:
        x_specs = [
            pl.BlockSpec((bm, bk), lambda i, j, kk, _q=q, _nk=nk:
                         (i, kk + _q * _nk)) for q in range(planes)]
        w_spec = pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j))             if mode == "int4" else             pl.BlockSpec((3, bk, bn), lambda i, j, kk: (0, kk, j))
        s_arr = scale.astype(jnp.float32).reshape(1, n)
        s_spec = pl.BlockSpec((1, bn), lambda i, j, kk: (0, j))
        out_spec = pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))
        grid = (mp // bm, n // bn, nk)
        out_shape = jax.ShapeDtypeStruct((mp, n), out_dtype)
        if not interpret:
            kw["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        kern, grid=grid,
        in_specs=x_specs + [w_spec, s_spec],
        out_specs=out_spec, out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret, **kw,
    )(*([xp] * planes), w_q, s_arr)
    if mp == m:
        return out
    return out[:, :m] if batched else out[:m]


def qmatmul(x: jax.Array, w_q: jax.Array, scale: jax.Array,
            out_dtype=None,
            interpret: Optional[bool] = None) -> jax.Array:
    """x [M, K] (bf16/f32) @ quantized w_q with per-channel scale [N].
    w_q: int8/fp8 [K, N], int4-packed uint8 [K/2, N], or fp6-packed
    uint8 [3, K/4, N] (dtype+rank-detected).

    Pads M up to a sublane multiple; falls back to an XLA dequant matmul
    off-TPU or for non-tileable K/N.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    if w_q.dtype == jnp.uint8:   # packed: fp6 [3, K/4, N] or int4 [K/2, N]
        mode = "fp6" if w_q.ndim == 3 else "int4"
        return _packed_qmm(x, w_q, scale, mode=mode, interpret=interpret,
                           out_dtype=out_dtype, batched=False)
    n = w_q.shape[1]
    bk, bn = _tile(k), _tile(n)
    out_dtype = out_dtype or x.dtype
    if not bk or not bn:
        logger.warning(
            f"qmatmul: K={k}/N={n} not tileable; using XLA dequant path")
        w = w_q.astype(jnp.float32) * scale[None, :]
        return (x.astype(jnp.float32) @ w).astype(out_dtype)
    xp, mp, bm = _pad_m(x, m, 0)
    out = _qmm(xp, w_q, scale, bm, bn, bk, interpret, out_dtype)
    return out[:m] if mp != m else out


def qmatmul_tp(x: jax.Array, w_q: jax.Array, scale: jax.Array,
               role: str, out_dtype=None) -> jax.Array:
    """TP-sharded weight-only matmul: the Pallas kernel under a partial
    shard_map over the 'model' axis (reference: module_inject INT8
    serving with mp_size>1 — quantized weights sliced per TP rank).

    role="col" (wq/wk/wv/wi/wg, lm head): w_q [K, N] sharded on N,
    scale [N] sharded with it; each shard runs the kernel on its output
    columns. role="row" (wo down-projections): w_q sharded on K, x
    sharded on its last dim (the previous col-parallel output), psum
    over 'model' after the local matmul — the per-output-channel scale
    commutes with the sum, so applying it per-shard is exact.

    Falls back to the plain (replicated) kernel when: no mesh / model
    axis 1, packed int4/fp6 weights (sharding the packed dim would
    split nibble planes), or a non-divisible shard dim (logged).
    Batch/data axes stay GSPMD-managed (partial-manual shard_map).
    """
    from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
    mesh = get_mesh() if has_mesh() else None
    tp = mesh.shape.get("model", 1) if mesh is not None else 1
    if tp == 1:
        return qmatmul(x, w_q, scale, out_dtype=out_dtype)
    if w_q.dtype == jnp.uint8:     # packed int4/fp6: engine guards this
        logger.warning("qmatmul_tp: packed weights not TP-shardable; "
                       "running replicated")
        return qmatmul(x, w_q, scale, out_dtype=out_dtype)
    k, n = w_q.shape
    shard_dim = n if role == "col" else k
    if shard_dim % tp:
        logger.warning(
            f"qmatmul_tp: {role} dim {shard_dim} not divisible by "
            f"tp={tp}; running replicated")
        return qmatmul(x, w_q, scale, out_dtype=out_dtype)
    out_dtype = out_dtype or x.dtype
    return _qtp_fn(mesh, role, jnp.dtype(out_dtype))(x, w_q, scale)


def _qtp_col_body(xl, wl, sl, out_dtype):
    return qmatmul(xl, wl, sl, out_dtype=out_dtype)


def _qtp_row_body(xl, wl, sl, out_dtype):
    return lax.psum(qmatmul(xl, wl, sl, out_dtype=out_dtype), "model")


@functools.lru_cache(maxsize=64)
def _qtp_fn(mesh, role, out_dtype):
    """Cached jitted shard_map per (mesh, role, out_dtype) — a fresh
    closure per call would defeat the jit cache for eager callers
    (function identity keys the cache; shapes still retrace within one
    entry as usual)."""
    if role == "col":
        in_specs = (P(None, None), P(None, "model"), P("model"))
        out_spec = P(None, "model")
        body = functools.partial(_qtp_col_body, out_dtype=out_dtype)
    else:
        in_specs = (P(None, "model"), P("model", None), P(None))
        out_spec = P(None, None)
        body = functools.partial(_qtp_row_body, out_dtype=out_dtype)
    fn = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_spec, axis_names={"model"},
                       check_vma=False)
    # jit wrapper: partial-manual shard_map needs a jit context (eager
    # calls fail spec validation); under an outer jit this is inlined
    return jax.jit(fn)


def _qmm_batched_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(3)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x_blk = x_ref[0]
    w_blk = w_ref[0].astype(jnp.bfloat16)
    acc_ref[...] += lax.dot_general(
        x_blk, w_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[0] = (acc_ref[...] * s_ref[0, 0][None, :]).astype(o_ref.dtype)


def qmatmul_batched_ep(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                       out_dtype=None) -> jax.Array:
    """EP-sharded grouped weight-only matmul: the batched Pallas kernel
    under a partial shard_map over the 'expert' axis (the reference's
    cutlass grouped moe_gemm runs per EP rank the same way).

    The group dim G is embarrassingly parallel — each expert shard runs
    the kernel on its local experts' weights and capacity buffers, no
    reduction needed. Falls back to the plain (replicated) kernel when
    no mesh / expert axis 1, packed int4/fp6 weights, or G not
    divisible by the expert axis.
    """
    from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
    mesh = get_mesh() if has_mesh() else None
    ep = mesh.shape.get("expert", 1) if mesh is not None else 1
    g = x.shape[0]
    if ep == 1 or w_q.dtype == jnp.uint8 or g % ep:
        if ep > 1:
            logger.warning(
                f"qmatmul_batched_ep: G={g} dtype={w_q.dtype} not "
                f"EP-shardable over expert={ep}; running replicated")
        return qmatmul_batched(x, w_q, scale, out_dtype=out_dtype)
    return _qbe_fn(mesh, jnp.dtype(out_dtype or x.dtype))(x, w_q, scale)


@functools.lru_cache(maxsize=32)
def _qbe_fn(mesh, out_dtype):
    """Cached jitted shard_map for the EP grouped kernel (see _qtp_fn)."""
    spec3 = P("expert", None, None)
    fn = jax.shard_map(
        functools.partial(qmatmul_batched, out_dtype=out_dtype),
        mesh=mesh, in_specs=(spec3, spec3, P("expert", None)),
        out_specs=spec3, axis_names={"expert"}, check_vma=False)
    return jax.jit(fn)


def qmatmul_batched(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                    out_dtype=None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Grouped weight-only matmul: x [G, M, K] @ w_q [G, K, N] (int8 or
    fp8) with per-group per-channel scale [G, N] → [G, M, N].

    The MoE expert FFN path (parallel/moe.py): G is the expert dim of the
    GShard ``ecd,edh->ech`` einsums — the reference's analogue is the
    cutlass grouped moe_gemm (inference/v2/kernels/cutlass_ops/moe_gemm)
    over int8 expert weights. One Pallas grid dim per group keeps each
    expert's weight stream resident in VMEM exactly once per tile pass.

    Falls back to an XLA dequant einsum off-TPU or for non-tileable K/N.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    g, m, k = x.shape
    if w_q.dtype == jnp.uint8:   # packed: fp6 [G,3,K/4,N] or int4 [G,K/2,N]
        mode = "fp6" if w_q.ndim == 4 else "int4"
        return _packed_qmm(x, w_q, scale, mode=mode, interpret=interpret,
                           out_dtype=out_dtype, batched=True)
    n = w_q.shape[2]
    bk, bn = _tile(k), _tile(n)
    out_dtype = out_dtype or x.dtype
    if not bk or not bn:
        logger.warning(
            f"qmatmul_batched: K={k}/N={n} not tileable; using XLA dequant "
            "path (materializes fp32 expert weights — 4x the quantized "
            "HBM footprint)")
        w = w_q.astype(jnp.float32) * scale[:, None, :]
        return jnp.einsum("gmk,gkn->gmn", x.astype(jnp.float32),
                          w).astype(out_dtype)
    xp, mp, bm = _pad_m(x, m, 1)
    nk = k // bk
    s3 = scale.astype(jnp.float32).reshape(g, 1, n)
    kw = {}
    if not interpret:
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_qmm_batched_kernel, nk=nk),
        grid=(g, mp // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((1, bm, bk), lambda gg, i, j, kk: (gg, i, kk)),
            pl.BlockSpec((1, bk, bn), lambda gg, i, j, kk: (gg, kk, j)),
            pl.BlockSpec((1, 1, bn), lambda gg, i, j, kk: (gg, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, bm, bn), lambda gg, i, j, kk: (gg, i, j)),
        out_shape=jax.ShapeDtypeStruct((g, mp, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
        **kw,
    )(xp, w_q, s3)
    return out[:, :m] if mp != m else out


def validate_weight_quant(mode) -> None:
    """Shared early validation for the engines' ``weight_quant`` knob —
    fails before any parameter materialization."""
    if mode is not None and mode not in ("int8", "fp8", "int4", "fp6"):
        raise ValueError(
            f"weight_quant '{mode}' unsupported; expected 'int8', 'fp8', "
            f"'int4' or 'fp6'")


def quantize_param_tree(params, targets=("wq", "wk", "wv", "wo", "wg",
                                         "wi"), mode: str = "int8"):
    """Replace 2-D(+stacked) matmul leaves named in ``targets`` inside
    ``params['layers']`` with (int8, ``<name>_scale``) pairs, quantize an
    untied ``lm_head``, and for tied embeddings add a TRANSPOSED int8
    logits copy ``lm_head_q`` [D, V] (the original embedding table stays
    float for the token lookup; per-step HBM traffic is what matters and
    the logits matmul only ever reads the int8 copy).

    MoE expert weights (wg/wi/wo stacked on the expert dim) quantize to
    per-expert per-channel scales and route through ``qmatmul_batched``
    (the reference's analogue: the int8 grouped moe_gemm under
    inference/v2/kernels/cutlass_ops); the router and the tiny
    shared-expert gate stay float.

    Inference-only: the quantized leaves carry no gradient path.
    """
    validate_weight_quant(mode)
    if "lm_head" + SCALE_SUFFIX in params or "lm_head_q" in params:
        raise ValueError("quantize_param_tree: tree is already quantized")
    out = {k: v for k, v in params.items()}
    layers = {k: v for k, v in params["layers"].items()}
    def quantize_group(group, names):
        g = {k: v for k, v in group.items()}
        for name in names:
            # the scale-leaf check (not dtype) keeps this idempotent:
            # fp8 leaves ARE a floating dtype, and re-quantizing an
            # already-scaled leaf silently destroys the weights
            if name in g and name + SCALE_SUFFIX not in g and \
                    g[name].ndim >= 2 and \
                    jnp.issubdtype(g[name].dtype, jnp.floating) and \
                    g[name].dtype != jnp.float8_e4m3fn:
                q, s = quantize_weight(g[name], mode)
                g[name] = q
                g[name + SCALE_SUFFIX] = s
        return g

    if "moe" in layers:
        moe = quantize_group(layers["moe"], ("wg", "wi", "wo"))
        if "shared" in moe:
            moe["shared"] = quantize_group(moe["shared"],
                                           ("wg", "wi", "wo"))
        layers["moe"] = moe
    for group in ("attn", "mlp"):
        if group in layers:
            layers[group] = quantize_group(layers[group], targets)
    out["layers"] = layers
    if "lm_head" in out:
        q, s = quantize_weight(out["lm_head"], mode)
        out["lm_head"] = q
        out["lm_head" + SCALE_SUFFIX] = s
    else:
        emb = out["embed"]["tokens"]           # [V, D] → logits copy [D, V]
        q, s = quantize_weight(emb.T, mode)
        out["lm_head_q"] = q
        out["lm_head_q" + SCALE_SUFFIX] = s
    return out


def cast_quantized_tree(params, dtype):
    """dtype-cast the float leaves of a (pre-)quantized tree WITHOUT
    touching the quantization artifacts: ``_scale`` leaves must stay f32
    (bf16 scales shift every channel by up to 2^-9), fp8 weights are a
    floating dtype whose cast would silently undo the memory win, and
    packed int planes are integers anyway."""
    def rec(d):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = rec(v)
                continue
            keep = (k.endswith(SCALE_SUFFIX) or k == "lm_head_q"
                    or v.dtype == jnp.float8_e4m3fn
                    or not jnp.issubdtype(v.dtype, jnp.floating))
            out[k] = v if keep else v.astype(dtype)
        return out
    return rec(params)
