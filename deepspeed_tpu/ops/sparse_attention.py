"""Block-sparse attention (static patterns, trace-time block skipping).

Reference: ``deepspeed/ops/sparse_attention`` (triton block-sparse matmul/
softmax, csrc/sparse_attention/utils.cpp) — BERT-era sparse transformer
patterns ('fixed' local+strided, BigBird local+global+random). The
TPU-native re-design: the sparsity pattern is a STATIC numpy block mask,
so the q-block loop is unrolled at trace time and only the allowed key
blocks are ever gathered — skipped blocks cost zero FLOPs and zero HBM
traffic, and every surviving op is a dense einsum XLA tiles onto the MXU
(the TPU answer to triton's blocksparse matmul). ``jax.checkpoint`` per
q-block keeps backward memory at one block row of scores.

For plain sliding-window (Mistral SWA) use ``ops.flash_attention``'s
``window=`` argument instead — that path skips blocks inside one fused
Pallas kernel. This module is for arbitrary patterns (strided/global/
random) that don't reduce to a contiguous window.
"""

import math
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Patterns (block masks: bool [num_q_blocks, num_k_blocks])
# ---------------------------------------------------------------------------

def local_pattern(t: int, block: int, num_local: int = 4) -> np.ndarray:
    """Each q block sees itself and the previous ``num_local - 1`` blocks
    (blockwise sliding window)."""
    n = t // block
    qi = np.arange(n)[:, None]
    ki = np.arange(n)[None, :]
    return (ki <= qi) & (ki > qi - num_local)


def fixed_pattern(t: int, block: int, num_local: int = 4,
                  stride: int = 4) -> np.ndarray:
    """Sparse-transformer 'fixed' pattern (reference ops/sparse_attention/
    sparsity_config FixedSparsityConfig): local window + every
    ``stride``-th block as a global summary column."""
    mask = local_pattern(t, block, num_local)
    n = t // block
    ki = np.arange(n)
    glob = (ki % stride) == (stride - 1)
    mask |= glob[None, :] & (ki[None, :] <= np.arange(n)[:, None])
    return mask


def bigbird_pattern(t: int, block: int, num_local: int = 3,
                    num_global: int = 1, num_random: int = 2,
                    seed: int = 0) -> np.ndarray:
    """BigBird (reference BigBirdSparsityConfig): local window + first
    ``num_global`` blocks visible to everyone + ``num_random`` random
    blocks per q row (drawn from its causal past)."""
    n = t // block
    mask = local_pattern(t, block, num_local)
    mask[:, :num_global] = True
    rng = np.random.default_rng(seed)
    for qi in range(n):
        past = np.arange(qi + 1)
        picks = rng.choice(past, size=min(num_random, len(past)),
                           replace=False)
        mask[qi, picks] = True
    return np.tril(np.ones((n, n), bool)) & mask


# ---------------------------------------------------------------------------
# Kernel (trace-time gather of allowed key blocks)
# ---------------------------------------------------------------------------

def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           block_mask: np.ndarray,
                           block: int = 128,
                           causal: bool = True,
                           q_offset: int = 0) -> jax.Array:
    """q [B,T,H,Dh], k/v [B,T,KvH,Dh], block_mask bool [T/block, T/block]
    → [B,T,H,Dh].  Softmax runs over the gathered blocks only; the
    per-element causal mask is still applied inside surviving diagonal
    blocks."""
    b, tq, h, dh = q.shape
    _, tk, kvh, _ = k.shape
    if tq % block or tk % block:
        raise ValueError(f"T ({tq}/{tk}) must divide block {block}")
    nq, nk = tq // block, tk // block
    block_mask = np.asarray(block_mask, bool)
    if block_mask.shape != (nq, nk):
        raise ValueError(f"block_mask shape {block_mask.shape} != "
                         f"({nq}, {nk})")
    g = h // kvh
    scale = 1.0 / math.sqrt(dh)
    qg = q.reshape(b, tq, kvh, g, dh)

    @partial(jax.checkpoint, static_argnums=(3, 4))
    def row(qc, kc, vc, q_start, sel):
        kpos = jnp.concatenate(
            [jnp.arange(ki * block, (ki + 1) * block, dtype=jnp.int32)
             for ki in sel])
        s = jnp.einsum("btkgd,bskd->bkgts", qc, kc,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_start + jnp.arange(block)
            live = qpos[:, None] >= kpos[None, :]
            s = jnp.where(live[None, None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1).astype(vc.dtype)
        return jnp.einsum("bkgts,bskd->btkgd", p, vc)

    outs = []
    for qi in range(nq):
        sel = [ki for ki in range(nk) if block_mask[qi, ki]
               and (not causal or ki * block <= qi * block + q_offset
                    + block - 1)]
        if not sel:
            raise ValueError(f"q block {qi} attends to no key block — "
                             f"pattern leaves rows without any key "
                             f"(softmax undefined); include the diagonal")
        qc = jax.lax.slice_in_dim(qg, qi * block, (qi + 1) * block, axis=1)
        kc = jnp.concatenate(
            [jax.lax.slice_in_dim(k, ki * block, (ki + 1) * block, axis=1)
             for ki in sel], axis=1)
        vc = jnp.concatenate(
            [jax.lax.slice_in_dim(v, ki * block, (ki + 1) * block, axis=1)
             for ki in sel], axis=1)
        outs.append(row(qc, kc, vc, qi * block + q_offset, tuple(sel)))
    return jnp.concatenate(outs, axis=1).reshape(b, tq, h, dh)


def sparsity(block_mask: np.ndarray, causal: bool = True) -> float:
    """Fraction of (causal) blocks actually computed — the FLOP ratio vs
    dense attention."""
    m = np.asarray(block_mask, bool)
    if causal:
        tril = np.tril(np.ones_like(m))
        return float((m & tril).sum() / tril.sum())
    return float(m.mean())
