"""Flash attention — Pallas TPU kernel.

The TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/inference/csrc/softmax.cu + the blocked_flash bindings
under deepspeed/inference/v2/kernels/ragged_ops/). Blockwise online-softmax
attention: the [T, T] score matrix is never materialized in HBM — each
(query-block, kv-block) tile lives only in VMEM — so backward needs no
saved probabilities, just the per-row logsumexp (the same residual layout
flash-attention-2 uses).

Layout: heads are folded into the grid's leading axis ([B*H, T, D]); GQA
maps query-head index -> kv-head index inside the BlockSpec index maps, so
K/V are never repeated in memory. fp32 accumulation on the MXU
(preferred_element_type), bf16 inputs.

Two kernel generations, auto-dispatched on local sequence length:
- resident (tk*d*itemsize ≤ 2 MiB, i.e. up to 8K at d=128 bf16): whole
  K/V per program, causal fori_loop bound skips dead blocks and their
  fetches — fastest.
- XL: (bh, nq, nk) grid with kv innermost, online-softmax state in VMEM
  scratch — no sequence ceiling (128K+ local seq; the Ulysses-128K
  config needs 16K+ per chip at SP=8).

Falls back to the XLA reference implementation (models.transformer.
dot_product_attention) off-TPU or for shapes the kernel doesn't cover.
"""

import functools
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _mask_scores(s, q_start, k_start, causal: bool,
                 window) -> "jax.Array":
    """Apply the causal and/or sliding-window visibility mask to one
    [BQ, BK] score tile (the ONE home for the mask inequalities — used by
    every fwd/bwd kernel generation)."""
    if not causal and window is None:
        return s
    block_q, block_k = s.shape
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    ok = (qpos >= kpos) if causal else \
        jnp.full_like(qpos, True, dtype=jnp.bool_)
    if window is not None:
        ok = jnp.logical_and(ok, kpos > qpos - window)
    return jnp.where(ok, s, _NEG_INF)


# swept on v5e (1.27B llama, seq 2048): 512/512 → 51.3% MFU vs 47.9% at
# 256/256 and 50.9% at 1024/512 — bigger q tiles amortize the softmax
# bookkeeping until VMEM pressure bites
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, causal: bool, block_k: int, q_offset: int,
                window: Optional[int]):
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    seq_k = k_ref.shape[1]
    d = q_ref.shape[2]

    # operands stay bf16 — the MXU accumulates in fp32 via
    # preferred_element_type; an eager .astype(f32) would force 8x-slower
    # fp32 matmuls (measured 12 vs 90+ TF/s on v5e)
    q = q_ref[0]                                           # [BQ, D]
    q_start = qi * block_q + q_offset
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kb = seq_k // block_k
    if causal:
        # only blocks that intersect the causal triangle
        num_kb_dyn = lax.min(
            jnp.int32(num_kb),
            lax.div(q_start + block_q + block_k - 1, jnp.int32(block_k)))
    else:
        num_kb_dyn = jnp.int32(num_kb)
    if window is not None:
        # sliding window (Mistral SWA): key kp visible to query qp iff
        # qp - window < kp <= qp — blocks left of the window are SKIPPED,
        # so FLOPs scale with window, not T²
        kb_start = lax.max(
            jnp.int32(0),
            lax.div(q_start - jnp.int32(window) + 1, jnp.int32(block_k)))
    else:
        kb_start = jnp.int32(0)

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, kb * block_k, causal, window)
        blk_max = jnp.max(s, axis=1)                        # [BQ]
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m[:, None])
        # rows with no live key yet: new_m == -inf -> p must be 0
        alive = new_m > _NEG_INF / 2
        p = jnp.where(alive[:, None], p, 0.0)
        corr = jnp.where(alive, jnp.exp(m - new_m), 0.0)
        acc = acc * corr[:, None] + lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l = l * corr + jnp.sum(p, axis=1)
        return acc, new_m, l

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = lax.fori_loop(kb_start, num_kb_dyn, body, (acc0, m0, l0))

    safe_l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
    # lse layout [BH, 1, TQ]: full row resident per bh, each qi program
    # writes its slice (satisfies the (8,128) tile rule via dim equality)
    lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = jnp.where(
        m > _NEG_INF / 2, m + jnp.log(safe_l), _NEG_INF)


def _fwd(q, k, v, scale, causal, q_offset, block_q, block_k, window,
         interpret):
    if not _resident_ok(q.shape[1], k.shape[1], q.shape[2],
                        q.dtype.itemsize):
        return _fwd_xl(q, k, v, scale, causal, q_offset, block_q, block_k,
                       window, interpret)
    bh, tq, d = q.shape
    bkv, tk, _ = k.shape
    g = bh // bkv
    grid = (bh, tq // block_q)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, q_offset=q_offset,
                          window=window),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, g=g: (lax.div(b, g), 0, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, g=g: (lax.div(b, g), 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, tq), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# XL forward kernel — KV-blocked grid for long sequences.
#
# The resident kernel above keeps whole K/V per program (fastest at tk
# ≤ ~8K: the causal fori_loop bound skips dead blocks AND their fetches).
# Past that the (1, tk, d) BlockSpec overflows VMEM, so this variant runs
# a (bh, nq, nk) grid with the kv dimension innermost and carries the
# online-softmax state (acc, m, l) in VMEM scratch across kv steps —
# the standard FA2 TPU structure (compare jax.experimental.pallas.ops.
# tpu.flash_attention; re-derived here). Causally-dead (i, j) programs
# skip compute via pl.when, and their K/V index maps are CLAMPED onto
# the nearest live block: Pallas only issues a copy when an operand's
# mapped block index changes between consecutive grid steps, so the
# dead tail (causal) / dead head (sliding window) of each kv row costs
# no DMA either — ~2x less attention HBM traffic at long causal seqs.
# ---------------------------------------------------------------------------


def _xl_kv_index(g, block_q, block_k, q_offset, causal, window, num_kb):
    """K/V BlockSpec index map for the (b, i, j) XL grids (kv innermost).

    Dead (i, j) steps map onto the nearest live kv block, so consecutive
    dead steps re-reference an already-resident block and their copies
    are elided. The clamp is allowed to be conservative (at worst one
    extra block fetched); compute is independently gated by ``pl.when``
    in the kernel, so correctness never depends on it."""
    def idx(b, i, j):
        jj = j
        if causal:
            # last live block: j*bk <= q_start + bq - 1
            jmax = lax.div(i * block_q + q_offset + block_q - 1, block_k)
            jj = lax.min(jj, jmax)
        if window is not None:
            # first live block: j*bk + bk - 1 > q_start - window
            jmin = lax.max(
                0, lax.div(i * block_q + q_offset - window + 1, block_k))
            jj = lax.max(jj, lax.min(jmin, num_kb - 1))
        return (lax.div(b, g), jj, 0)
    return idx


def _xl_q_index(block_q, block_k, q_offset, causal, window, num_qb,
                lse_like: bool = False):
    """Q-side BlockSpec index map for the (b, jk, iq) dkv grid (q
    innermost): clamp dead head (causal) / dead tail (window) steps of
    each q row onto the nearest live q block (same DMA-elision argument
    as `_xl_kv_index`)."""
    def idx(b, jk, iq):
        ii = iq
        if causal:
            # first live q block: iq*bq + q_offset + bq - 1 >= jk*bk
            imin = lax.max(0, lax.div(jk * block_k - q_offset, block_q))
            ii = lax.max(ii, lax.min(imin, num_qb - 1))
        if window is not None:
            # last live q block: iq*bq + q_offset - window < jk*bk + bk - 1
            imax = lax.div(jk * block_k + block_k - 2 + window - q_offset,
                           block_q)
            ii = lax.min(ii, lax.max(imax, 0))
        return (b, 0, ii) if lse_like else (b, ii, 0)
    return idx

def _fwd_kernel_xl(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                   l_ref, *, scale: float, causal: bool, q_offset: int,
                   window: Optional[int], num_kb: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    q_start = i * block_q + q_offset
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = jnp.bool_(True)
    if causal:   # block intersects the causal triangle
        live = jnp.logical_and(live, k_start <= q_start + block_q - 1)
    if window is not None:   # block not entirely left of the window
        live = jnp.logical_and(live,
                               k_start + block_k - 1 > q_start - window)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, k_start, causal, window)
        m = m_ref[...]
        blk_max = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, blk_max)
        new_m_col = new_m[:, None]
        p = jnp.exp(s - new_m_col)
        # Mosaic can't minor-dim-reshape i1 vectors — compare the already
        # 2-D f32 column instead of reshaping a 1-D bool
        p = jnp.where(new_m_col > _NEG_INF / 2, p, 0.0)
        alive = new_m > _NEG_INF / 2
        corr = jnp.where(alive, jnp.exp(m - new_m), 0.0)
        acc_ref[...] = acc_ref[...] * corr[:, None] + lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1)
        m_ref[...] = new_m

    @pl.when(j == num_kb - 1)
    def _flush():
        l = l_ref[...]
        m = m_ref[...]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_ref[...] / safe_l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :] = jnp.where(
            m > _NEG_INF / 2, m + jnp.log(safe_l), _NEG_INF)


def _fwd_xl(q, k, v, scale, causal, q_offset, block_q, block_k, window,
            interpret):
    bh, tq, d = q.shape
    bkv, tk, _ = k.shape
    g = bh // bkv
    num_kb = tk // block_k
    grid = (bh, tq // block_q, num_kb)
    kv_idx = _xl_kv_index(g, block_q, block_k, q_offset, causal, window,
                          num_kb)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_xl, scale=scale, causal=causal,
                          q_offset=q_offset, window=window, num_kb=num_kb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels (flash-attention-2 style: recompute p from q,k + lse)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale: float, causal: bool, block_k: int,
                   q_offset: int, window: Optional[int]):
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    seq_k = k_ref.shape[1]
    d = q_ref.shape[2]

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
    delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
    q_start = qi * block_q + q_offset
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kb = seq_k // block_k
    if causal:
        num_kb_dyn = lax.min(
            jnp.int32(num_kb),
            lax.div(q_start + block_q + block_k - 1, jnp.int32(block_k)))
    else:
        num_kb_dyn = jnp.int32(num_kb)
    if window is not None:
        kb_start = lax.max(
            jnp.int32(0),
            lax.div(q_start - jnp.int32(window) + 1, jnp.int32(block_k)))
    else:
        kb_start = jnp.int32(0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, kb * block_k, causal, window)
        p = jnp.exp(s - lse[:, None])
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k_blk.dtype)
        dq = dq + lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dq

    dq = lax.fori_loop(kb_start, num_kb_dyn, body,
                       jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale: float, causal: bool,
                    block_q: int, q_offset: int, window: Optional[int]):
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    seq_q = q_ref.shape[1]
    d = k_ref.shape[2]

    k_blk = k_ref[0]                                       # [BK, D]
    v_blk = v_ref[0]
    k_start = ki * block_k
    kpos = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    num_qb = seq_q // block_q
    if causal:
        # first q block whose END reaches this k block's start
        first_qb = lax.max(
            jnp.int32(0),
            lax.div(k_start - q_offset - block_q + 1 + block_q - 1,
                    jnp.int32(block_q)))
    else:
        first_qb = jnp.int32(0)
    if window is not None:
        # queries beyond k_end-1 + window - 1 can't see this k block
        num_qb_dyn = lax.min(
            jnp.int32(num_qb),
            lax.div(k_start + block_k - 1 + jnp.int32(window) - 1
                    - q_offset, jnp.int32(block_q)) + 1)
    else:
        num_qb_dyn = jnp.int32(num_qb)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, qb * block_q + q_offset, k_start, causal,
                         window)
        p = jnp.exp(s - lse[:, None])
        dv = dv + lax.dot_general(p.astype(do.dtype), do,
                                  (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q_blk.dtype)
        dk = dk + lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(first_qb, num_qb_dyn, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, scale, causal, q_offset, block_q, block_k,
         window, interpret):
    if not _resident_ok(q.shape[1], k.shape[1], q.shape[2],
                        q.dtype.itemsize):
        return _bwd_xl(q, k, v, out, lse, do, scale, causal, q_offset,
                       block_q, block_k, window, interpret)
    bh, tq, d = q.shape
    bkv, tk, _ = k.shape
    g = bh // bkv
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]                      # [BH, 1, TQ]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, q_offset=q_offset,
                          window=window),
        grid=(bh, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, g=g: (lax.div(b, g), 0, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, g=g: (lax.div(b, g), 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, tq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv per q-head, summed over the GQA group afterwards
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, q_offset=q_offset,
                          window=window),
        grid=(bh, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, g=g: (lax.div(b, g), i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, g=g: (lax.div(b, g), i, 0)),
            pl.BlockSpec((1, tq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if g > 1:
        dk = dk_h.reshape(bkv, g, tk, d).sum(axis=1)
        dv = dv_h.reshape(bkv, g, tk, d).sum(axis=1)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# XL backward kernels — KV/Q-blocked grids mirroring _fwd_kernel_xl
# ---------------------------------------------------------------------------

def _bwd_dq_kernel_xl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_acc_ref, *, scale: float, causal: bool,
                      q_offset: int, window: Optional[int], num_kb: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    q_start = i * block_q + q_offset
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = jnp.bool_(True)
    if causal:
        live = jnp.logical_and(live, k_start <= q_start + block_q - 1)
    if window is not None:
        live = jnp.logical_and(live,
                               k_start + block_k - 1 > q_start - window)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, k_start, causal, window)
        p = jnp.exp(s - lse[:, None])
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k_blk.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_kb - 1)
    def _flush():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_xl(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                       scale: float, causal: bool, q_offset: int,
                       window: Optional[int], num_qb: int):
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    k_start = jk * block_k
    q_start = iq * block_q + q_offset

    @pl.when(iq == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    live = jnp.bool_(True)
    if causal:   # some query in the block reaches this k block
        live = jnp.logical_and(live, q_start + block_q - 1 >= k_start)
    if window is not None:
        live = jnp.logical_and(live,
                               k_start + block_k - 1 > q_start - window)

    @pl.when(live)
    def _compute():
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        q_blk = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, k_start, causal, window)
        p = jnp.exp(s - lse[:, None])
        dv_acc_ref[...] = dv_acc_ref[...] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q_blk.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == num_qb - 1)
    def _flush():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd_xl(q, k, v, out, lse, do, scale, causal, q_offset, block_q,
            block_k, window, interpret):
    bh, tq, d = q.shape
    bkv, tk, _ = k.shape
    g = bh // bkv
    num_kb = tk // block_k
    num_qb = tq // block_q
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]                      # [BH, 1, TQ]

    kv_idx = _xl_kv_index(g, block_q, block_k, q_offset, causal, window,
                          num_kb)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_xl, scale=scale, causal=causal,
                          q_offset=q_offset, window=window, num_kb=num_kb),
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    q_idx = _xl_q_index(block_q, block_k, q_offset, causal, window, num_qb)
    lse_idx = _xl_q_index(block_q, block_k, q_offset, causal, window,
                          num_qb, lse_like=True)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_xl, scale=scale, causal=causal,
                          q_offset=q_offset, window=window, num_qb=num_qb),
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, block_k, d),
                         lambda b, jk, iq, g=g: (lax.div(b, g), jk, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, jk, iq, g=g: (lax.div(b, g), jk, 0)),
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, 1, block_q), lse_idx),
            pl.BlockSpec((1, 1, block_q), lse_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, jk, iq: (b, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, jk, iq: (b, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tk, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if g > 1:
        dk = dk_h.reshape(bkv, g, tk, d).sum(axis=1)
        dv = dv_h.reshape(bkv, g, tk, d).sum(axis=1)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, causal, q_offset, block_q, block_k, window, interpret,
           bwd_block_q, bwd_block_k):
    out, _ = _fwd(q, k, v, 1.0 / math.sqrt(q.shape[-1]), causal, q_offset,
                  block_q, block_k, window, interpret)
    return out


def _flash_fwd(q, k, v, causal, q_offset, block_q, block_k, window,
               interpret, bwd_block_q, bwd_block_k):
    out, lse = _fwd(q, k, v, 1.0 / math.sqrt(q.shape[-1]), causal, q_offset,
                    block_q, block_k, window, interpret)
    # name the custom_vjp residuals so remat policies can SAVE them: with
    # plain 'save_attn_out' (post-projection value) the backward re-runs
    # this whole forward kernel just to rebuild (out, lse) — a full extra
    # attention pass per layer. 'save_attn_kernel' saves these two instead
    # (same bytes: out is B·T·d like the projected value; lse is ~1% more)
    # and the backward recomputes only the cheap wo projection.
    out = checkpoint_name(out, "attn_kernel_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, q_offset, block_q, block_k, window, interpret,
               bwd_block_q, bwd_block_k, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, g,
                      1.0 / math.sqrt(q.shape[-1]), causal, q_offset,
                      bwd_block_q or block_q, bwd_block_k or block_k,
                      window, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


#: per-tensor VMEM budget for the full-K/V-resident BlockSpecs. A core has
#: ~16 MiB and Pallas DOUBLE-BUFFERS revisited blocks, so the dq kernel's
#: K+V residency costs ~4x this bound in stack VMEM (measured: 16.75 MiB
#: at tk=16K/d=128 under a 4 MiB bound → compile OOM). 2 MiB keeps the
#: fast resident kernels through tk=8K at d=128; beyond that the XL
#: (KV-blocked-grid) kernels take over — no sequence ceiling.
_VMEM_PER_TENSOR = 2 * 1024 * 1024


def _resident_ok(tq, tk, d, itemsize=2) -> bool:
    """Whole-K/V-per-program kernels fit VMEM (the fast path: the causal
    fori_loop bound skips dead blocks AND their fetches)."""
    return max(tq, tk) * d * itemsize <= _VMEM_PER_TENSOR


def _supported(tq, tk, d, block_q, block_k) -> bool:
    return (tq % block_q == 0 and tk % block_k == 0 and
            tq >= block_q and tk >= block_k and d <= 256)


def _pick_blocks(tq, tk, d, itemsize, block_q=None, block_k=None):
    """Block selection shared by every public wrapper: explicit args, the
    DSTPU_FLASH_BQ/BK env knobs, per-generation defaults (XL grids want
    1024/1024 — measured 44.8%% vs 36.0%% MFU at 512/512, seq 16K v5e),
    then step-down until the shape divides (e.g. tq=768 runs at 256 —
    far faster than the XLA fallback)."""
    import os
    xl = not _resident_ok(tq, tk, d, itemsize)
    default_bq = 1024 if xl else DEFAULT_BLOCK_Q
    default_bk = 1024 if xl else DEFAULT_BLOCK_K
    bq = block_q or int(os.environ.get("DSTPU_FLASH_BQ", 0)) or \
        min(default_bq, tq)
    bk = block_k or int(os.environ.get("DSTPU_FLASH_BK", 0)) or \
        min(default_bk, tk)
    bq, bk = min(bq, tq), min(bk, tk)
    while bq > 128 and (tq % bq or not _supported(tq, tk, d, bq, bk)):
        bq //= 2
    while bk > 128 and (tk % bk or not _supported(tq, tk, d, bq, bk)):
        bk //= 2
    return bq, bk


def _pick_bwd_blocks(tq, tk, d, itemsize, fwd_bq, fwd_bk):
    """Backward kernels carry more VMEM state (fp32 dq/dk/dv accumulators +
    the extra do/delta operands), so their sweet spot differs from the
    forward's — e.g. fwd 2048×1024 is the 16K winner but the dq kernel
    stack-OOMs past bq 1024. Defaults to the forward blocks; override via
    DSTPU_FLASH_BWD_BQ/BK."""
    import os
    bq = int(os.environ.get("DSTPU_FLASH_BWD_BQ", 0)) or fwd_bq
    bk = int(os.environ.get("DSTPU_FLASH_BWD_BK", 0)) or fwd_bk
    bq, bk = min(bq, tq), min(bk, tk)
    while bq > 128 and (tq % bq or not _supported(tq, tk, d, bq, bk)):
        bq //= 2
    while bk > 128 and (tk % bk or not _supported(tq, tk, d, bq, bk)):
        bk //= 2
    return bq, bk


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    q_offset: int = 0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    window: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in ``attn_fn``: q [B,T,H,D], k/v [B,T,KvH,D] → [B,T,H,D].

    Uses the Pallas kernel on TPU (or interpret mode elsewhere when forced
    via ``interpret=True``); falls back to the XLA reference path for
    unsupported shapes. ``window``: causal sliding window (Mistral SWA) —
    out-of-window key BLOCKS are skipped, so long-seq FLOPs scale with
    T·window instead of T².
    """
    b, tq, h, d = q.shape
    _, tk, kvh, _ = k.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq, bk = _pick_blocks(tq, tk, d, q.dtype.itemsize, block_q, block_k)
    if not _supported(tq, tk, d, bq, bk) or h % kvh:
        from deepspeed_tpu.models.transformer import dot_product_attention
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"flash_attention: shape (tq={tq}, tk={tk}, d={d}, h={h}, "
            f"kvh={kvh}) outside kernel support; using the XLA reference "
            f"path (slower — check block/tile divisibility)")
        return dot_product_attention(q, k, v, causal=causal,
                                     q_offset=q_offset, window=window)

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kvh, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kvh, tk, d)
    bwd_bq, bwd_bk = _pick_bwd_blocks(tq, tk, d, q.dtype.itemsize, bq, bk)
    out = _flash(qf, kf, vf, causal, q_offset, bq, bk, window, interpret,
                 bwd_bq, bwd_bk)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = True,
                             interpret: Optional[bool] = None):
    """Inference-only flash forward returning (out, lse [B,T,H]) for the
    paged-history merge (ops/paged_attention.merge_attention). No
    custom_vjp — serving never differentiates through it. Falls back to
    the XLA lse-returning reference off-TPU/unsupported shapes."""
    b, tq, h, d = q.shape
    _, tk, kvh, _ = k.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq, bk = _pick_blocks(tq, tk, d, q.dtype.itemsize)
    if not _supported(tq, tk, d, bq, bk) or h % kvh:
        # NOTE: the lse fallback requires kvh | h (GQA group reshape) —
        # it raises a clear error otherwise rather than mis-grouping
        from deepspeed_tpu.ops.paged_attention import \
            causal_attention_with_lse
        return causal_attention_with_lse(q, k, v)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kvh, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kvh, tk, d)
    out, lse = _fwd(qf, kf, vf, 1.0 / math.sqrt(d), causal, 0, bq, bk,
                    None, interpret)
    out = out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, tq).transpose(0, 2, 1)
    return out, lse


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            causal: bool = True,
                            q_offset: int = 0,
                            **kw) -> jax.Array:
    """Mesh-aware flash attention for use inside the jitted train step.

    A bare ``pallas_call`` has no SPMD partitioning rule — under automatic
    sharding XLA would replicate q/k/v onto every chip. This wrapper
    shard_maps the kernel over the batch axes ('data','expert') and, when
    head counts divide, the head axes ('model' for TP and 'seq' for
    Ulysses — sharding heads over 'seq' after a sequence-sharded input IS
    the Ulysses all-to-all, reference sequence/layer.py:331, emitted here
    by the shard_map in_specs resharding). Falls back to the XLA attention
    when the local shapes don't meet the kernel's constraints.
    """
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import ZERO_AXES, get_mesh, has_mesh

    if not has_mesh():
        return flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                               **kw)
    mesh = get_mesh()
    b, tq, h, d = q.shape
    kvh = k.shape[2]

    batch_axes = tuple(a for a in ZERO_AXES
                       if mesh.shape[a] > 1 and b % mesh.shape[a] == 0)
    bdiv = 1
    for a in batch_axes:
        bdiv *= mesh.shape[a]
    head_axes = tuple(a for a in ("model", "seq") if mesh.shape[a] > 1)
    hdiv = 1
    for a in head_axes:
        hdiv *= mesh.shape[a]
    # GQA grouping is only correct when q AND kv heads shard identically.
    # Indivisible counts first try the uneven-head treatment (static head
    # padding / minimal KV replication, exact grads — parallel/ulysses.
    # _even_heads, the reference uneven_heads_all2all analogue) so the
    # full head split survives; only exotic shapes degrade.
    orig_h = h
    if head_axes and (h % hdiv or kvh % hdiv):
        from deepspeed_tpu.parallel.ulysses import _even_heads
        evened = _even_heads(q, k, v, hdiv)
        if evened is not None:
            q, k, v, orig_h = evened
            h, kvh = q.shape[2], k.shape[2]
        else:
            head_axes = tuple(a for a in ("model",)
                              if mesh.shape[a] > 1)
            hdiv = mesh.shape["model"] if head_axes else 1
            if head_axes and (h % hdiv or kvh % hdiv):
                head_axes, hdiv = (), 1
    if b % max(bdiv, 1):
        batch_axes, bdiv = (), 1

    manual = set(batch_axes) | set(head_axes)
    if not manual:
        return flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                               **kw)

    bspec = batch_axes if len(batch_axes) > 1 else \
        (batch_axes[0] if batch_axes else None)
    hspec = head_axes if len(head_axes) > 1 else \
        (head_axes[0] if head_axes else None)
    spec = P(bspec, None, hspec, None)

    local = partial(flash_attention, causal=causal, q_offset=q_offset, **kw)
    # check_vma=False: pallas_call outputs carry no varying-axes metadata;
    # the kernel is embarrassingly parallel over the manual axes anyway
    fn = jax.shard_map(lambda a, b_, c: local(a, b_, c),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, axis_names=manual, check_vma=False)
    out = fn(q, k, v)
    if out.shape[2] != orig_h:
        out = out[:, :, :orig_h, :]   # drop padded query heads
    return out
