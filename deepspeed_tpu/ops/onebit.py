"""1-bit Adam / 1-bit LAMB / 0/1 Adam — communication-compressed optimizers.

Reference: ``runtime/fp16/onebit/adam.py:14`` (OnebitAdam), ``lamb.py:16``
(OnebitLamb), ``zoadam.py`` (0/1 Adam), over the compressed backends
(runtime/comm/nccl.py:52). The algorithm: a **warmup** phase
(``freeze_step`` steps) runs exact Adam with full-precision gradient
averaging while the variance estimate stabilizes; after the freeze the
variance is FROZEN and each worker updates its momentum with its LOCAL
gradient, then exchanges only the SIGN bits of the momentum through the
error-feedback 1-bit allreduce (comm/compressed.py) — 32× less traffic
per step, the blogs' up-to-26× comm reduction.

1-bit LAMB adds layerwise adaptation: during warmup the exact LAMB trust
ratio ||w||/||update|| is applied per parameter leaf and its EMA
recorded; in the compressed phase the update is scaled by the FROZEN
per-leaf coefficient (reference lamb.py freezes ``scaling_coeff`` the
same way — fresh trust ratios can't be computed without exact global
statistics).

TPU design: one explicit ``shard_map`` step over 'data' (quantized/
compressed collectives can't be expressed as GSPMD annotations — same
stance as runtime/zero/zeropp.py). Params and m/v stay REPLICATED (the
reference requires ZeRO stage 0 with 1-bit optimizers too); the error-
feedback buffers are per-device state carried as [world, ...] arrays
sharded over 'data'. Restrictions (validated): zero stage 0, bf16/fp32,
no offload/pipeline, no gradient clipping in the compressed phase (the
exact global norm is never materialized — reference has the same
limitation).
"""

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                           init_error_buffers, padded_size)
from deepspeed_tpu.runtime.zero.offload import FlatLayout
from deepspeed_tpu.utils.logging import log_dist

ONEBIT_NAMES = ("onebitadam", "onebit_adam", "zerooneadam",
                "onebitlamb", "onebit_lamb")


def validate_onebit(engine) -> None:
    cfg = engine.config
    if cfg.zero_optimization.stage != 0:
        raise ValueError("1-bit Adam requires ZeRO stage 0 (reference "
                         "onebit/adam.py restriction: momentum comm "
                         "replaces the grad allreduce)")
    for ax in ("model", "seq", "pipe", "expert", "data_inner"):
        if engine.mesh.shape[ax] != 1:
            raise ValueError(f"1-bit Adam runs over the 'data' axis only; "
                             f"mesh axis '{ax}' = {engine.mesh.shape[ax]}")
    if engine.fp16_enabled:
        raise ValueError("1-bit Adam here requires bf16/fp32 (fp16 "
                         "overflow handling needs exact grads)")
    if engine.offload_enabled:
        raise ValueError("1-bit Adam and offload_optimizer are exclusive")
    if engine.model.pipeline_loss_fn is not None:
        raise ValueError("1-bit Adam does not compose with pipeline")


def _is_zeroone(opt_type: str) -> bool:
    return "zeroone" in opt_type.lower().replace("-", "").replace("_", "")


def init_onebit_state(engine) -> None:
    """Replicated flat master/m/v + per-device error-feedback buffers."""
    mesh = engine.mesh
    world = mesh.shape["data"]
    layout = FlatLayout(engine._abstract_params)
    engine._onebit_layout = layout
    total = layout.total
    padded = padded_size(total, world)
    engine._onebit_padded = padded
    zeroone = _is_zeroone(engine.config.optimizer.type)

    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("data"))
    flat_params = jax.jit(
        lambda p: layout.flatten_device(p, jnp.float32),
        out_shardings=rep)(engine.params)
    engine.opt_state = {
        "master": flat_params,
        "m": jax.device_put(jnp.zeros((total,), jnp.float32), rep),
        "v": jax.device_put(jnp.zeros((total,), jnp.float32), rep),
        "werr": jax.device_put(jnp.zeros((world, padded), jnp.float32),
                               dp),
        "serr": jax.device_put(
            jnp.zeros((world, padded // world), jnp.float32), dp),
        "step": jax.device_put(jnp.zeros((), jnp.int32), rep),
        # per-leaf LAMB trust-ratio EMA (frozen after warmup); carried
        # by the adam variants too so the state treedef is uniform
        "coeff": jax.device_put(
            jnp.ones((len(layout.sizes),), jnp.float32), rep),
        # 0/1 Adam extras (zoadam.py state): the momentum accumulator u
        # (local updates applied between syncs), accumulated lr, and the
        # adaptive variance/local-step interval policy scalars. The u
        # buffer is param-sized so only 0/1 Adam allocates it.
        "u": jax.device_put(
            jnp.zeros((total if zeroone else 0,), jnp.float32), rep),
        "lrs": jax.device_put(jnp.zeros((), jnp.float32), rep),
        "var_interval": jax.device_put(jnp.ones((), jnp.int32), rep),
        "var_counter": jax.device_put(jnp.zeros((), jnp.int32), rep),
        "local_interval": jax.device_put(jnp.ones((), jnp.int32), rep),
        "local_counter": jax.device_put(jnp.zeros((), jnp.int32), rep),
        # telemetry: how many exact (fp32 pmean) vs 1-bit collectives the
        # schedule actually issued — the comm-savings invariant under test
        "exact_comms": jax.device_put(jnp.zeros((), jnp.int32), rep),
        "onebit_comms": jax.device_put(jnp.zeros((), jnp.int32), rep),
    }
    engine._state_shardings = jax.tree.map(
        lambda x: x.sharding, engine.opt_state)
    log_dist(f"{'0/1' if zeroone else '1-bit'} Adam: "
             f"{total / 1e6:.1f}M params, dp={world}, "
             f"compressed collectives per the interval policy")


def build_onebit_step(engine) -> None:
    cfg = engine.config
    mesh = engine.mesh
    world = mesh.shape["data"]
    layout = engine._onebit_layout
    total = layout.total
    padded = engine._onebit_padded
    compute_dtype = engine.compute_dtype
    gas = int(cfg.gradient_accumulation_steps)
    lr_schedule = engine.lr_schedule
    loss_fn = engine.model.loss_fn

    p = dict(cfg.optimizer.params or {})
    betas = p.get("betas", (0.9, 0.999))
    b1, b2 = float(betas[0]), float(betas[1])
    eps = float(p.get("eps", 1e-8))
    wd = float(p.get("weight_decay", 0.0))
    freeze_step = int(p.get("freeze_step", 100))
    is_lamb = "lamb" in cfg.optimizer.type.lower()
    is_zeroone = _is_zeroone(cfg.optimizer.type)
    # 0/1 Adam policy knobs (reference zoadam.py defaults)
    var_freeze_step = int(p.get("var_freeze_step", 100000))
    var_update_scaler = int(p.get("var_update_scaler", 16))
    local_step_scaler = int(p.get("local_step_scaler", 32768))
    local_step_clipper = int(p.get("local_step_clipper", 16))
    # LAMB trust-ratio clip + EMA factor (reference lamb.py max_coeff /
    # min_coeff / coeff_beta)
    coeff_max = float(p.get("max_coeff", 10.0))
    coeff_min = float(p.get("min_coeff", 0.01))
    coeff_beta = float(p.get("coeff_beta", 0.9))
    n_seg = len(layout.sizes)
    seg_ids = jnp.asarray(np.repeat(np.arange(n_seg), layout.sizes),
                          jnp.int32)

    def seg_trust(master, upd):
        """Per-leaf LAMB trust ratio ||w||/||upd||, clipped; zero-norm
        leaves (zero-initialized biases at step 1) get the reference's
        neutral 1.0 (lamb.py: lamb_coeff=1 when either norm is 0) — the
        clip floor would otherwise freeze them 100x down."""
        wn = jnp.sqrt(jax.ops.segment_sum(master * master, seg_ids,
                                          num_segments=n_seg))
        un = jnp.sqrt(jax.ops.segment_sum(upd * upd, seg_ids,
                                          num_segments=n_seg))
        trust = jnp.clip(wn / jnp.maximum(un, 1e-12), coeff_min, coeff_max)
        return jnp.where((wn == 0) | (un == 0), 1.0, trust)

    def body(params, opt, batch, step, rng):
        def micro(carry, mb):
            acc, r = carry
            r, sub = jax.random.split(r)

            def lf(pp):
                out = loss_fn(pp, mb, sub)
                return out[0] if isinstance(out, tuple) else out

            loss, grads = jax.value_and_grad(lf)(params)
            return (acc + layout.flatten_device(grads, jnp.float32), r), \
                loss

        acc0 = jnp.zeros((total,), jnp.float32)
        (g_local, _), losses = lax.scan(micro, (acc0, rng), batch)
        g_local = g_local * (1.0 / gas)

        master, m, v = opt["master"], opt["m"], opt["v"]
        werr, serr = opt["werr"][0], opt["serr"][0]
        t_new = opt["step"] + 1

        def warmup(_):
            g = lax.pmean(g_local, "data")
            m1 = b1 * m + (1 - b1) * g
            v1 = b2 * v + (1 - b2) * g * g
            return m1, v1, werr, serr

        def compressed(_):
            # local momentum then 1-bit error-feedback allreduce of it
            ml = b1 * m + (1 - b1) * g_local
            ml_pad = jnp.concatenate(
                [ml, jnp.zeros((padded - total,), jnp.float32)])
            m_avg, w2, s2 = compressed_allreduce(ml_pad, werr, serr,
                                                 "data")
            return m_avg[:total], v, w2, s2       # variance FROZEN

        m1, v1, w2, s2 = lax.cond(t_new <= freeze_step, warmup,
                                  compressed, None)
        bc1 = 1 - b1 ** t_new.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(
            t_new, freeze_step).astype(jnp.float32)
        lr = lr_schedule(step)
        upd = (m1 / bc1) / (jnp.sqrt(v1 / bc2) + eps)
        if wd:
            upd = upd + wd * master
        coeff = opt["coeff"]
        if is_lamb:
            # warmup: exact per-leaf trust ratio, EMA recorded; after the
            # freeze the EMA is FROZEN and reused (reference lamb.py
            # scaling_coeff freeze)
            in_warmup = t_new <= freeze_step
            trust_now = seg_trust(master, upd)
            trust = jnp.where(in_warmup, trust_now, coeff)
            coeff = jnp.where(
                in_warmup,
                coeff_beta * coeff + (1 - coeff_beta) * trust_now, coeff)
            upd = upd * trust[seg_ids]
        master1 = master - lr * upd
        new_flat = master1.astype(compute_dtype)
        loss = lax.pmean(jnp.mean(losses), "data")
        mnorm = jnp.sqrt(jnp.sum(jnp.square(m1)))
        new_opt = dict(opt, master=master1, m=m1, v=v1,
                       werr=w2[None], serr=s2[None], step=t_new,
                       coeff=coeff)
        return new_flat, new_opt, loss, mnorm, lr

    def body_zeroone(params, opt, batch, step, rng):
        """0/1 Adam (reference zoadam.py:14, arXiv:2202.06009).

        Phase 1 (step <= var_freeze_step) — adaptive variance updates:
        on steps divisible by ``var_interval`` the gradient is averaged
        EXACTLY and both moments update; on all other steps only the
        momentum updates, from the 1-bit error-feedback-compressed
        gradient. ``var_interval`` doubles every ``var_update_scaler``
        variance updates, so exact collectives become exponentially rare.

        Phase 2 (after the freeze) — local steps: momentum updates from
        the LOCAL gradient and the worker takes the step with NO
        communication, accumulating applied updates in ``u``; every
        ``local_interval`` steps the local drift is undone, the
        accumulated momentum is 1-bit-allreduced, and params/momentum are
        reset from the global average (zoadam.py:246-266).
        ``local_interval`` doubles every ``local_step_scaler`` steps,
        clipped at ``local_step_clipper``."""
        def micro(carry, mb):
            acc, r = carry
            r, sub = jax.random.split(r)

            def lf(pp):
                out = loss_fn(pp, mb, sub)
                return out[0] if isinstance(out, tuple) else out

            loss, grads = jax.value_and_grad(lf)(params)
            return (acc + layout.flatten_device(grads, jnp.float32), r), \
                loss

        acc0 = jnp.zeros((total,), jnp.float32)
        (g_local, _), losses = lax.scan(micro, (acc0, rng), batch)
        g_local = g_local * (1.0 / gas)

        master, m, v, u = opt["master"], opt["m"], opt["v"], opt["u"]
        t_new = opt["step"] + 1
        lr = lr_schedule(step)
        # phase-boundary error-buffer reset (zoadam.py
        # reinitial_error_buffer: the errors switch metric from gradient
        # to accumulated momentum)
        at_boundary = t_new == (var_freeze_step + 1)
        werr = jnp.where(at_boundary, 0.0, opt["werr"][0])
        serr = jnp.where(at_boundary, 0.0, opt["serr"][0])
        pad_z = jnp.zeros((padded - total,), jnp.float32)

        def phase1(_):
            var_step = (t_new % opt["var_interval"]) == 0

            def exact(_):
                g = lax.pmean(g_local, "data")
                m1 = b1 * m + (1 - b1) * g
                v1 = b2 * v + (1 - b2) * g * g
                return (m1, v1, werr, serr,
                        opt["exact_comms"] + 1, opt["onebit_comms"])

            def onebit(_):
                g_avg, w2, s2 = compressed_allreduce(
                    jnp.concatenate([g_local, pad_z]), werr, serr, "data")
                m1 = b1 * m + (1 - b1) * g_avg[:total]
                return (m1, v, w2, s2,
                        opt["exact_comms"], opt["onebit_comms"] + 1)

            m1, v1, w2, s2, ec, oc = lax.cond(var_step, exact, onebit,
                                              None)
            upd = m1 / (jnp.sqrt(v1) + eps)
            if wd:
                upd = upd + wd * master
            master1 = master - lr * upd
            vc = jnp.where(var_step, opt["var_counter"] + 1,
                           opt["var_counter"])
            dbl = vc >= var_update_scaler
            vi = jnp.where(dbl, opt["var_interval"] * 2,
                           opt["var_interval"])
            vc = jnp.where(dbl, 0, vc)
            return (master1, m1, v1, u, opt["lrs"], w2, s2, vi, vc,
                    opt["local_interval"], opt["local_counter"], ec, oc)

        def phase2(_):
            # local momentum + local step, zero communication
            m1 = b1 * m + (1 - b1) * g_local
            denom = jnp.sqrt(v) + eps
            upd = m1 / denom
            if wd:
                upd = upd + wd * master
            master1 = master - lr * upd
            u1 = u - lr * upd
            lrs1 = opt["lrs"] + lr
            sync = (t_new % opt["local_interval"]) == 0

            def do_sync(_):
                # undo local drift, average the accumulated momentum
                # (u scaled back to momentum units), re-apply globally
                undone = master1 - u1
                buf = u1 * denom
                buf_avg, w2, s2 = compressed_allreduce(
                    jnp.concatenate([buf, pad_z]), werr, serr, "data")
                buf_avg = buf_avg[:total]
                m2 = -buf_avg / jnp.maximum(lrs1, 1e-20)
                p2 = undone + buf_avg / denom
                return (p2, m2, jnp.zeros_like(u1),
                        jnp.zeros_like(lrs1), w2, s2,
                        opt["onebit_comms"] + 1)

            def no_sync(_):
                return (master1, m1, u1, lrs1, werr, serr,
                        opt["onebit_comms"])

            p2, m2, u2, lrs2, w2, s2, oc = lax.cond(sync, do_sync,
                                                    no_sync, None)
            lc = opt["local_counter"] + 1
            dbl = lc >= local_step_scaler
            li = jnp.where(
                dbl, jnp.minimum(local_step_clipper,
                                 opt["local_interval"] * 2),
                opt["local_interval"])
            lc = jnp.where(dbl, 0, lc)
            return (p2, m2, v, u2, lrs2, w2, s2, opt["var_interval"],
                    opt["var_counter"], li, lc, opt["exact_comms"], oc)

        (master1, m1, v1, u1, lrs1, w2, s2, vi, vc, li, lc, ec, oc) = \
            lax.cond(t_new > var_freeze_step, phase2, phase1, None)
        new_flat = master1.astype(compute_dtype)
        loss = lax.pmean(jnp.mean(losses), "data")
        mnorm = jnp.sqrt(jnp.sum(jnp.square(m1)))
        new_opt = dict(opt, master=master1, m=m1, v=v1, u=u1, lrs=lrs1,
                       werr=w2[None], serr=s2[None], step=t_new,
                       var_interval=vi, var_counter=vc,
                       local_interval=li, local_counter=lc,
                       exact_comms=ec, onebit_comms=oc)
        return new_flat, new_opt, loss, mnorm, lr

    param_specs = jax.tree.map(lambda _: P(), engine.params)
    opt_specs = {"master": P(), "m": P(), "v": P(),
                 "werr": P("data"), "serr": P("data"), "step": P(),
                 "coeff": P(), "u": P(), "lrs": P(),
                 "var_interval": P(), "var_counter": P(),
                 "local_interval": P(), "local_counter": P(),
                 "exact_comms": P(), "onebit_comms": P()}
    step_body = body_zeroone if is_zeroone else body

    def fused_step(params, opt_state, scaler, batch, step, rng):
        batch_specs = jax.tree.map(
            lambda x: P(None, "data", *([None] * (np.ndim(x) - 2))),
            batch)
        new_flat, new_opt, loss, mnorm, lr = shard_map(
            step_body, mesh=mesh,
            in_specs=(param_specs, opt_specs, batch_specs, P(), P()),
            out_specs=(P(), opt_specs, P(), P(), P()),
            check_vma=False,
        )(params, opt_state, batch, step, rng)
        new_params = layout.unflatten_device(
            new_flat, [compute_dtype if jnp.issubdtype(d, jnp.floating)
                       else d for d in layout.dtypes])
        new_params = lax.with_sharding_constraint(
            new_params, engine._param_shardings)
        metrics = {"loss": loss, "lr": lr, "grad_norm": mnorm,
                   "loss_scale": scaler.scale,
                   "overflow": jnp.zeros((), jnp.int32)}
        return new_params, new_opt, scaler, metrics

    engine._fused_step = jax.jit(fused_step, donate_argnums=(0, 1))
    engine._grad_step = None
    engine._acc_add = None
    engine._update_step = None
    engine._rng = jax.random.PRNGKey(cfg.seed + 1)
