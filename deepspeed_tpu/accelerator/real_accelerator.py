"""Accelerator singleton resolution.

Reference: accelerator/real_accelerator.py:51 (``get_accelerator`` honors
the DS_ACCELERATOR env override, else probes vendor runtimes). Here the
probe is jax's backend discovery; override with ``DSTPU_ACCELERATOR``
('tpu' | 'cpu').
"""

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import (CPU_Accelerator,
                                                       TPU_Accelerator)
from deepspeed_tpu.utils.logging import logger

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is not None:
        return _ACCELERATOR
    name = os.environ.get("DSTPU_ACCELERATOR")
    if name is None:
        import jax
        name = "tpu" if jax.default_backend() == "tpu" else "cpu"
    if name not in ("tpu", "cpu"):
        raise ValueError(
            f"DSTPU_ACCELERATOR={name!r} invalid; expected 'tpu' or 'cpu'")
    _ACCELERATOR = TPU_Accelerator() if name == "tpu" else CPU_Accelerator()
    logger.info(f"accelerator: {name}")
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel
