"""Accelerator abstraction — the L0 seam of the framework.

TPU-native re-design of the reference's ``DeepSpeedAccelerator`` ABC
(accelerator/abstract_accelerator.py:10, ~70 abstract methods). Large parts
of that surface exist only because torch exposes mutable global device
state (streams, events, per-device allocators, graph capture). Under
jax/XLA those concepts are either functional (RNG = explicit PRNGKey),
compiler-owned (streams/graphs ≈ jit), or queryable but not settable
(devices are process-global). The ABC below keeps the reference's seams
that still mean something on TPU:

- device identity/count/sync            (reference :33–60)
- RNG seeding → functional PRNGKey      (reference :62–89)
- memory statistics                      (reference :114–165)
- dtype capability probes                (reference :167–178)
- pinned/host memory                     (reference :258–268)
- op-builder dispatch                    (reference :274–286)
- communication_backend_name             (reference :201–203)

Dropped as N/A (documented, not stubbed): Stream/Event (XLA async dispatch
+ donation replace manual streams), graph capture/replay (jit), set_device
(jax owns placement via shardings).
"""

import abc
from typing import Any, Dict, Optional, Sequence


class DeepSpeedAccelerator(abc.ABC):
    _name: str

    # ------------------------------------------------------------ device API
    @abc.abstractmethod
    def is_available(self) -> bool:
        """True if this accelerator's platform has at least one device."""

    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        """'tpu' or 'tpu:3' style name (reference :33)."""

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None):
        """The jax.Device object (reference returns a torch device ctx)."""

    @abc.abstractmethod
    def device_count(self) -> int:
        """Local (this-process) addressable device count."""

    @abc.abstractmethod
    def global_device_count(self) -> int:
        """All devices across the pod (multi-host)."""

    @abc.abstractmethod
    def current_device(self) -> int:
        """Index of the default device."""

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Drain outstanding async work on the device (reference :54)."""

    # --------------------------------------------------------------- RNG API
    @abc.abstractmethod
    def manual_seed(self, seed: int) -> None:
        """Set the process seed; subsequent default_generator() keys derive
        from it. Functional analogue of torch.manual_seed (reference :62)."""

    @abc.abstractmethod
    def initial_seed(self) -> int: ...

    @abc.abstractmethod
    def default_generator(self, device_index: int = 0):
        """A fresh jax PRNGKey folded in with the device index. Each call
        advances the process stream (stateful seam over functional RNG)."""

    # ------------------------------------------------------------ memory API
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]: ...

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get(
            "peak_bytes_in_use", self.memory_allocated(device_index)))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None: ...

    # ------------------------------------------------------------- dtype API
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    @abc.abstractmethod
    def supported_dtypes(self) -> Sequence[Any]: ...

    # ----------------------------------------------------------- comm/builder
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        """'ici' on TPU (XLA collectives over ICI/DCN), 'host' on CPU —
        the reference returns 'nccl'/'ccl'/'hccl' here (:201)."""

    @abc.abstractmethod
    def create_op_builder(self, class_name: str):
        """Instantiate a NativeOpBuilder by op name (reference :274)."""

    @abc.abstractmethod
    def get_op_builder(self, class_name: str):
        """Return the builder class/factory without instantiating."""

    # ------------------------------------------------------------ host memory
    @abc.abstractmethod
    def pin_memory(self, array, align_bytes: int = 1):
        """Return a host buffer suitable for async DMA. On TPU-VM, host
        RAM is directly DMA-visible; numpy arrays need only alignment
        (reference :258 pins CUDA host memory)."""

    @abc.abstractmethod
    def is_pinned(self, array) -> bool: ...

    # -------------------------------------------------------------- utilities
    def on_accelerator(self, array) -> bool:
        """True if the jax array lives on this accelerator's platform."""
        try:
            shards = array.devices() if hasattr(array, "devices") else set()
            return any(d.platform == self._name for d in shards)
        except Exception:
            return False

    def range_push(self, msg: str) -> None:
        """Profiler range marker (reference nvtx :221). Routed to
        jax.profiler traces when active; cheap no-op otherwise."""
        import jax.profiler as _p
        tc = getattr(self, "_trace_ctxs", None)
        if tc is None:
            tc = self._trace_ctxs = []
        ctx = _p.TraceAnnotation(msg)
        ctx.__enter__()
        tc.append(ctx)

    def range_pop(self) -> None:
        tc = getattr(self, "_trace_ctxs", None)
        if tc:
            tc.pop().__exit__(None, None, None)
