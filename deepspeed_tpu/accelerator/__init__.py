"""L0 accelerator abstraction (reference: accelerator/ package)."""

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator  # noqa: F401
from deepspeed_tpu.accelerator.real_accelerator import (get_accelerator,  # noqa: F401
                                                        set_accelerator)
from deepspeed_tpu.accelerator.tpu_accelerator import (CPU_Accelerator,  # noqa: F401
                                                       TPU_Accelerator)
