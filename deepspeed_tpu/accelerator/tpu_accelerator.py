"""Concrete accelerators: TPU (jax/XLA) and CPU (virtual-device testing).

Reference analogue: accelerator/cuda_accelerator.py (387 LoC) and
cpu_accelerator.py. One implementation serves both platforms here because
jax abstracts the device API; only capability probes and the comm backend
name differ.
"""

import os
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator

#: buffers registered by pin_memory (ndarrays accept neither attributes nor
#: hashing, so membership is id-keyed out-of-band; weakref callbacks clear
#: entries so pinning never leaks)
import weakref
_PINNED: Dict[int, "weakref.ref"] = {}


def _pin(arr: np.ndarray) -> None:
    key = id(arr)
    _PINNED[key] = weakref.ref(arr, lambda _r, k=key: _PINNED.pop(k, None))

#: op name → (sources) registry for create_op_builder; mirrors the
#: reference's one-builder-file-per-op layout (op_builder/__init__.py)
_NATIVE_OPS = {
    "host_adam": ["host_adam.cpp"],
    "async_io": ["async_io.cpp"],
}


class _JaxAccelerator(DeepSpeedAccelerator):
    """Shared jax-backed implementation."""

    def __init__(self, platform: str):
        self._name = platform
        self._seed = 42

    # ------------------------------------------------------------ device API
    def is_available(self) -> bool:
        try:
            return len(jax.devices(self._name)) > 0
        except RuntimeError:
            return False

    def _devices(self):
        return jax.local_devices(backend=self._name)

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = self._devices()
        return devs[device_index or 0]

    def device_count(self) -> int:
        return len(self._devices())

    def global_device_count(self) -> int:
        return jax.device_count(backend=self._name)

    def current_device(self) -> int:
        return 0

    def synchronize(self, device_index: Optional[int] = None) -> None:
        # block on a token put to the device — drains its async queue
        tok = jax.device_put(jnp.zeros((), jnp.int32),
                             self.device(device_index))
        jax.block_until_ready(tok)

    # --------------------------------------------------------------- RNG API
    def manual_seed(self, seed: int) -> None:
        self._seed = int(seed)
        self._stream = 0

    def initial_seed(self) -> int:
        return self._seed

    def default_generator(self, device_index: int = 0):
        stream = getattr(self, "_stream", 0)
        self._stream = stream + 1
        key = jax.random.PRNGKey(self._seed)
        return jax.random.fold_in(jax.random.fold_in(key, device_index),
                                  stream)

    # ------------------------------------------------------------ memory API
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, Any]:
        dev = self.device(device_index)
        try:
            return dict(dev.memory_stats() or {})
        except Exception:
            return {}

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        # XLA exposes peak stats read-only; track a high-water offset instead
        stats = self.memory_stats(device_index)
        self._peak_offset = stats.get("peak_bytes_in_use", 0)

    # ------------------------------------------------------------- dtype API
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self) -> Sequence[Any]:
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8,
                jnp.float8_e4m3fn, jnp.float8_e5m2]

    # ----------------------------------------------------------- comm/builder
    def communication_backend_name(self) -> str:
        return "ici" if self._name == "tpu" else "host"

    def get_op_builder(self, class_name: str):
        from deepspeed_tpu.ops.op_builder import NativeOpBuilder
        if class_name not in _NATIVE_OPS:
            raise KeyError(f"unknown native op '{class_name}'; "
                           f"known: {sorted(_NATIVE_OPS)}")
        sources = _NATIVE_OPS[class_name]
        return lambda: NativeOpBuilder(class_name, sources=sources)

    def create_op_builder(self, class_name: str):
        return self.get_op_builder(class_name)()

    # ------------------------------------------------------------ host memory
    def pin_memory(self, array, align_bytes: int = 64):
        """Return `array` backed by an align_bytes-aligned host buffer
        (O_DIRECT NVMe I/O needs 512/4096-byte alignment)."""
        arr = np.asarray(array)
        if not (arr.ctypes.data % align_bytes == 0 and arr.flags.c_contiguous):
            raw = np.empty(arr.nbytes + align_bytes, dtype=np.uint8)
            off = (-raw.ctypes.data) % align_bytes
            out = raw[off:off + arr.nbytes].view(arr.dtype).reshape(arr.shape)
            out[...] = arr
            arr = out
        _pin(arr)
        return arr

    def is_pinned(self, array) -> bool:
        ref = _PINNED.get(id(array))
        return ref is not None and ref() is array


class TPU_Accelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("tpu")


class CPU_Accelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("cpu")

    def memory_stats(self, device_index=None):
        stats = super().memory_stats(device_index)
        if not stats:
            try:
                import psutil
                vm = psutil.virtual_memory()
                stats = {"bytes_in_use": vm.used, "bytes_limit": vm.total,
                         "peak_bytes_in_use": vm.used}
            except Exception:
                stats = {}
        return stats
