"""Mistral family presets (reference: inference/v2/model_implementations/
mistral/ — Llama-family decoder with GQA and sliding-window attention
(v0.1: window 4096); HF-loadable via models/hf_loader.py)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def mistral_config(size: str = "7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=256),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   num_kv_heads=8, intermediate_size=14336,
                   sliding_window=4096),
    }
    base = dict(vocab_size=32000, max_seq_len=8192, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", rope_theta=10000.0,
                use_bias=False, tie_embeddings=False, norm_eps=1e-5)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
