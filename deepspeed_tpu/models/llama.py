"""Llama-3 family presets (BASELINE.md targets: ZeRO-3 Llama-3 8B,
ZeRO-Infinity Llama-3 70B, Ulysses Llama-3 8B @ 128K)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def llama3_config(size: str = "8b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                     intermediate_size=128, vocab_size=512, max_seq_len=256),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     num_kv_heads=8, intermediate_size=4096),
        # TPU-native head sizing: dh=128 (one VREG lane tile) — halves the
        # attention score traffic vs dh=64 at identical FLOPs/params
        "1b":  dict(hidden_size=2048, num_layers=16, num_heads=16,
                    num_kv_heads=8, intermediate_size=8192),
        "8b":  dict(hidden_size=4096, num_layers=32, num_heads=32,
                    num_kv_heads=8, intermediate_size=14336),
        "70b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                    num_kv_heads=8, intermediate_size=28672),
    }
    base = dict(vocab_size=128256, max_seq_len=8192, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", rope_theta=500000.0,
                use_bias=False, tie_embeddings=False, norm_eps=1e-5)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
