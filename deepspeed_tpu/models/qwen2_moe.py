"""Qwen2-MoE family presets (reference: inference/v2 model zoo lists
qwen_v2_moe). Distinctives vs Mixtral: a SHARED expert (dense MLP on
every token) scaled by a sigmoid gate, qwen2-style qkv biases, and
norm_topk_prob=False (raw softmax routing weights)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def qwen2_moe_config(size: str = "a2.7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=96,
                     shared_expert_size=128, num_experts=4,
                     num_experts_per_tok=2, vocab_size=512,
                     max_seq_len=256),
        # Qwen1.5-MoE-A2.7B
        "a2.7b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                      num_kv_heads=16, intermediate_size=1408,
                      shared_expert_size=5632, num_experts=60,
                      num_experts_per_tok=4, vocab_size=151936,
                      max_seq_len=8192),
    }
    base = dict(norm="rmsnorm", activation="silu_glu", pos_emb="rope",
                rope_theta=1e6, use_bias=True, tie_embeddings=False,
                norm_topk_prob=False, shared_expert_gate=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
