"""GPT-Neo family presets (reference: the GPT-Neo injection policy in
module_inject/containers/gptneo.py).

Architecture quirks vs GPT-2: separate (not fused) q/k/v Linears with NO
bias but a biased out_proj (``attn_out_bias``); alternating global/
local-256 attention layers (``layer_window_pattern=(0, 256)``); and NO
1/sqrt(d) attention scaling — the HF loader folds a sqrt(head_dim)
factor into wq so the in-repo scaled kernels reproduce the unscaled
math exactly (models/hf_loader.py:_load_gptneo).
"""

from deepspeed_tpu.models.transformer import DecoderConfig


def gptneo_config(size: str = "1.3b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128, layer_window_pattern=(0, 8)),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16),
        "2.7b": dict(hidden_size=2560, num_layers=32, num_heads=20),
    }
    base = dict(vocab_size=50257, max_seq_len=2048, norm="layernorm",
                activation="gelu", pos_emb="learned", use_bias=True,
                attn_bias=False, attn_out_bias=True, tie_embeddings=True,
                layer_window_pattern=(0, 256))
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
