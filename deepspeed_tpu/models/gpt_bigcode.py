"""GPT-BigCode family presets (SantaCoder/StarCoder; reference:
module_inject supports the bigcode arch via AutoTP). Distinctives:
GPT-2-style learned positions + LayerNorm + tanh-GELU, but with
multi-query attention (1 kv head) and nn.Linear weights (the HF
checkpoint stores [out, in], unlike GPT-2's Conv1D)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def gpt_bigcode_config(size: str = "1b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=1, vocab_size=512, max_seq_len=128),
        # santacoder
        "1b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                   num_kv_heads=1, vocab_size=49280),
        # starcoderbase / starcoder
        "15b": dict(hidden_size=6144, num_layers=40, num_heads=48,
                    num_kv_heads=1, vocab_size=49152, max_seq_len=8192),
    }
    base = dict(vocab_size=49152, max_seq_len=2048, norm="layernorm",
                activation="gelu", pos_emb="learned", use_bias=True,
                tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
