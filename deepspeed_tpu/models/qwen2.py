"""Qwen2 family presets (reference: inference/v2/model_implementations/
qwen_v2/ — Llama-family decoder with qkv biases; HF-loadable via
models/hf_loader.py which maps the q/k/v bias tensors)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def qwen2_config(size: str = "7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=256),
        "0.5b": dict(hidden_size=896, num_layers=24, num_heads=14,
                     num_kv_heads=2, intermediate_size=4864,
                     tie_embeddings=True),
        "7b": dict(hidden_size=3584, num_layers=28, num_heads=28,
                   num_kv_heads=4, intermediate_size=18944),
        "72b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                    num_kv_heads=8, intermediate_size=29568),
    }
    base = dict(vocab_size=152064, max_seq_len=32768, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", rope_theta=1000000.0,
                use_bias=True, tie_embeddings=False, norm_eps=1e-6)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
