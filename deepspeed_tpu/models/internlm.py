"""InternLM family presets (reference: module_inject/containers
InternLMLayerPolicy / DS_InternLMContainer).

Llama math (RMSNorm, RoPE, SwiGLU) with ``"bias": true`` — all four
attention projections carry biases (o_proj included, unlike Qwen2).
Exports exactly as ``model_type: llama`` with ``attention_bias: true``
(the LlamaConfig slot covering o_proj bias), so trained InternLM
checkpoints round-trip through transformers without loss.
"""

from deepspeed_tpu.models.transformer import DecoderConfig


def internlm_config(size: str = "7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=128, vocab_size=512,
                     max_seq_len=256),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   intermediate_size=11008),
        "20b": dict(hidden_size=5120, num_layers=60, num_heads=40,
                    intermediate_size=13824),
    }
    base = dict(vocab_size=103168, max_seq_len=2048, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", norm_eps=1e-6,
                use_bias=False, attn_bias=True, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
