"""GPT-2 family presets (the first-milestone model per BASELINE.md:
'ZeRO-2 GPT-2 125M')."""

from deepspeed_tpu.models.transformer import DecoderConfig


def gpt2_config(size: str = "125m", **overrides) -> DecoderConfig:
    presets = {
        "tiny":  dict(hidden_size=64, num_layers=2, num_heads=4,
                      vocab_size=512, max_seq_len=128),
        "125m":  dict(hidden_size=768, num_layers=12, num_heads=12),
        "350m":  dict(hidden_size=1024, num_layers=24, num_heads=16),
        "760m":  dict(hidden_size=1536, num_layers=24, num_heads=16),
        "1.3b":  dict(hidden_size=2048, num_layers=24, num_heads=32),
        "2.7b":  dict(hidden_size=2560, num_layers=32, num_heads=32),
        "6.7b":  dict(hidden_size=4096, num_layers=32, num_heads=32),
        "13b":   dict(hidden_size=5120, num_layers=40, num_heads=40),
    }
    base = dict(vocab_size=50304, max_seq_len=1024, norm="layernorm",
                activation="gelu", pos_emb="learned", use_bias=True,
                tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
