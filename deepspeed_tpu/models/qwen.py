"""Qwen (v1) family presets (reference: inference/v2/model_implementations/
qwen/ — QwenInferenceModel / QwenTransformerContainer).

Llama math (RMSNorm, rotate-half RoPE, SwiGLU) with the GPT-2-style HF
layout: fused biased ``attn.c_attn`` (contiguous q|k|v thirds — NOT
per-head interleaved), bias-less ``attn.c_proj``/MLP, and the MLP naming
quirk the reference container maps explicitly: ``mlp.w1`` is the UP
projection and ``mlp.w2`` the GATE (container.py:57–58), with the HF
config's ``intermediate_size`` being 2x the per-projection width
(model.py:72 ``intermediate_dim = intermediate_size // 2``). Always MHA:
``n_heads_kv = hidden_size // kv_channels`` (model.py:75).

The reference ignores Qwen-v1's optional dynamic-NTK / logn attention
scaling (model.py positional_embedding_config is plain RotateHalfConfig);
so do we — within the trained ``seq_length`` both are identity.

Qwen-v1 checkpoints LOAD from their native layout
(``models/hf_loader.py:_load_qwen``); export emits the qwen2 layout,
which expresses the same math losslessly (q/k/v biases, bias-less
o_proj, untied head) and reloads in transformers without remote code.
"""

from deepspeed_tpu.models.transformer import DecoderConfig


def qwen_config(size: str = "7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=128, vocab_size=512,
                     max_seq_len=256),
        "1.8b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                     intermediate_size=5504),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   intermediate_size=11008),
        "14b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    intermediate_size=13696),
    }
    base = dict(vocab_size=151936, max_seq_len=8192, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", rope_theta=10000.0,
                norm_eps=1e-6, use_bias=True, attn_out_bias=False,
                tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
