"""HuggingFace checkpoint interop (safetensors ↔ transformer pytree).

TPU-native equivalent of the reference's checkpoint engines + injection
policies (inference/v2/checkpoint/huggingface_engine.py streaming loader,
module_inject/auto_tp.py:193 layer-name policy walk). Instead of mutating
torch modules layer-by-layer, we map HF tensor names into the functional
pytree layout (layers stacked on a leading [L] axis for ``lax.scan``) and
let `transformer.partition_specs` supply the TP/FSDP sharding rules — the
AutoTP analogue is rule-driven sharding of the loaded pytree, applied by
the engine via `jax.device_put` at initialize().

Supported families: Llama/Mistral (silu_glu, RMSNorm, rope), Mixtral
(MoE experts w1/w2/w3), Qwen2 (adds qkv biases). HF stores Linear weights
as [out, in]; our einsum layout is [in, out], hence the transposes.
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import DecoderConfig
from deepspeed_tpu.utils.logging import logger

Params = Any


# ---------------------------------------------------------------------------
# config mapping
# ---------------------------------------------------------------------------

_FAMILIES = ("llama", "mistral", "mixtral", "qwen2")


def config_from_hf(hf: Dict[str, Any]) -> DecoderConfig:
    """HF config.json dict → DecoderConfig."""
    mt = hf.get("model_type", "llama")
    if mt not in _FAMILIES:
        raise ValueError(f"unsupported model_type '{mt}'; "
                         f"supported: {_FAMILIES}")
    kw = dict(
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        intermediate_size=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_seq_len=hf.get("max_position_embeddings", 4096),
        norm="rmsnorm",
        activation="silu_glu",
        pos_emb="rope",
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
        use_bias=(mt == "qwen2"),   # qwen2: qkv bias only; handled in map
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    if mt == "mixtral":
        kw.update(num_experts=hf["num_local_experts"],
                  num_experts_per_tok=hf.get("num_experts_per_tok", 2))
    return DecoderConfig(**kw)


def config_to_hf(cfg: DecoderConfig) -> Dict[str, Any]:
    hf = {
        "model_type": "mixtral" if cfg.num_experts else "llama",
        "architectures": ["MixtralForCausalLM" if cfg.num_experts
                          else "LlamaForCausalLM"],
        "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.kv_heads,
        "intermediate_size": cfg.ffn_size,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "float32",
    }
    if cfg.num_experts:
        hf["num_local_experts"] = cfg.num_experts
        hf["num_experts_per_tok"] = cfg.num_experts_per_tok
    return hf


# ---------------------------------------------------------------------------
# tensor-name mapping
# ---------------------------------------------------------------------------

def _reader(model_dir: str):
    """Yield a get(name)->np.ndarray over all safetensors shards (streamed:
    tensors load lazily, one at a time — the 70B-scale requirement of the
    reference's HuggingFaceCheckpointEngine)."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as fh:
            weight_map = json.load(fh)["weight_map"]
        handles: Dict[str, Any] = {}

        def get(name: str) -> np.ndarray:
            shard = weight_map[name]
            if shard not in handles:
                handles[shard] = safe_open(
                    os.path.join(model_dir, shard), framework="np")
            return handles[shard].get_tensor(name)

        return get, set(weight_map)
    single = os.path.join(model_dir, "model.safetensors")
    handle = safe_open(single, framework="np")
    names = set(handle.keys())
    return handle.get_tensor, names


def load_hf_checkpoint(model_dir: str, dtype=np.float32
                       ) -> Tuple[DecoderConfig, Params]:
    """Load an HF Llama/Mistral/Mixtral/Qwen2 checkpoint directory into
    (DecoderConfig, params pytree)."""
    with open(os.path.join(model_dir, "config.json")) as fh:
        hf_cfg = json.load(fh)
    cfg = config_from_hf(hf_cfg)
    get, names = _reader(model_dir)
    L = cfg.num_layers

    def T(name):
        return np.ascontiguousarray(get(name).astype(dtype).T)

    def stackT(fmt):
        return np.stack([T(fmt.format(i)) for i in range(L)])

    def stack(fmt):
        return np.stack([get(fmt.format(i)).astype(dtype)
                         for i in range(L)])

    p = "model.layers.{}."
    attn = {
        "wq": stackT(p + "self_attn.q_proj.weight"),
        "wk": stackT(p + "self_attn.k_proj.weight"),
        "wv": stackT(p + "self_attn.v_proj.weight"),
        "wo": stackT(p + "self_attn.o_proj.weight"),
    }
    if p.format(0) + "self_attn.q_proj.bias" in names:   # qwen2
        attn["bq"] = stack(p + "self_attn.q_proj.bias")
        attn["bk"] = stack(p + "self_attn.k_proj.bias")
        attn["bv"] = stack(p + "self_attn.v_proj.bias")
        attn["bo"] = np.zeros((L, cfg.hidden_size), dtype)

    layers: Dict[str, Any] = {
        "attn": attn,
        "ln1": {"scale": stack(p + "input_layernorm.weight")},
        "ln2": {"scale": stack(p + "post_attention_layernorm.weight")},
    }
    if cfg.num_experts:
        E = cfg.num_experts
        ep = p + "block_sparse_moe.experts.{}."

        def estackT(suffix):
            return np.stack([
                np.stack([T(ep.format(i, e) + suffix) for e in range(E)])
                for i in range(L)])
        layers["moe"] = {
            "router": stackT(p + "block_sparse_moe.gate.weight"),
            "wg": estackT("w1.weight"),       # mixtral w1 = gate
            "wo": estackT("w2.weight"),       # w2 = down
            "wi": estackT("w3.weight"),       # w3 = up
        }
    else:
        layers["mlp"] = {
            "wg": stackT(p + "mlp.gate_proj.weight"),
            "wi": stackT(p + "mlp.up_proj.weight"),
            "wo": stackT(p + "mlp.down_proj.weight"),
        }

    params: Params = {
        "embed": {"tokens": get("model.embed_tokens.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {"scale": get("model.norm.weight").astype(dtype)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = T("lm_head.weight")
    logger.info(f"loaded HF checkpoint from {model_dir}: "
                f"{cfg.num_params() / 1e6:.1f}M params, "
                f"{hf_cfg.get('model_type')}")
    return cfg, params


def export_hf_checkpoint(cfg: DecoderConfig, params: Params,
                         out_dir: str) -> None:
    """Write the pytree back as an HF-layout safetensors checkpoint
    (single shard) + config.json — the reverse mapping, so models trained
    here load in transformers."""
    import jax
    from safetensors.numpy import save_file
    if cfg.parallel_block:
        raise NotImplementedError(
            "export_hf_checkpoint maps the llama-family layout only; "
            "parallel-residual models (falcon/gptneox presets) need their "
            "own key mapping — not implemented yet")

    os.makedirs(out_dir, exist_ok=True)
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params)
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host["embed"]["tokens"],
        "model.norm.weight": host["final_norm"]["scale"],
    }
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.ascontiguousarray(host["lm_head"].T)
    lyr = host["layers"]
    p = "model.layers.{}."
    for i in range(cfg.num_layers):
        a = lyr["attn"]
        out[p.format(i) + "self_attn.q_proj.weight"] = \
            np.ascontiguousarray(a["wq"][i].T)
        out[p.format(i) + "self_attn.k_proj.weight"] = \
            np.ascontiguousarray(a["wk"][i].T)
        out[p.format(i) + "self_attn.v_proj.weight"] = \
            np.ascontiguousarray(a["wv"][i].T)
        out[p.format(i) + "self_attn.o_proj.weight"] = \
            np.ascontiguousarray(a["wo"][i].T)
        out[p.format(i) + "input_layernorm.weight"] = lyr["ln1"]["scale"][i]
        out[p.format(i) + "post_attention_layernorm.weight"] = \
            lyr["ln2"]["scale"][i]
        if cfg.num_experts:
            moe = lyr["moe"]
            out[p.format(i) + "block_sparse_moe.gate.weight"] = \
                np.ascontiguousarray(moe["router"][i].T)
            for e in range(cfg.num_experts):
                ep = p.format(i) + f"block_sparse_moe.experts.{e}."
                out[ep + "w1.weight"] = np.ascontiguousarray(moe["wg"][i, e].T)
                out[ep + "w2.weight"] = np.ascontiguousarray(moe["wo"][i, e].T)
                out[ep + "w3.weight"] = np.ascontiguousarray(moe["wi"][i, e].T)
        else:
            m = lyr["mlp"]
            out[p.format(i) + "mlp.gate_proj.weight"] = \
                np.ascontiguousarray(m["wg"][i].T)
            out[p.format(i) + "mlp.up_proj.weight"] = \
                np.ascontiguousarray(m["wi"][i].T)
            out[p.format(i) + "mlp.down_proj.weight"] = \
                np.ascontiguousarray(m["wo"][i].T)
    save_file(out, os.path.join(out_dir, "model.safetensors"),
              metadata={"format": "pt"})
    with open(os.path.join(out_dir, "config.json"), "w") as fh:
        json.dump(config_to_hf(cfg), fh, indent=2)
