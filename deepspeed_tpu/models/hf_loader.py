"""HuggingFace checkpoint interop (safetensors ↔ transformer pytree).

TPU-native equivalent of the reference's checkpoint engines + injection
policies (inference/v2/checkpoint/huggingface_engine.py streaming loader,
module_inject/auto_tp.py:193 layer-name policy walk). Instead of mutating
torch modules layer-by-layer, we map HF tensor names into the functional
pytree layout (layers stacked on a leading [L] axis for ``lax.scan``) and
let `transformer.partition_specs` supply the TP/FSDP sharding rules — the
AutoTP analogue is rule-driven sharding of the loaded pytree, applied by
the engine via `jax.device_put` at initialize().

Supported families: Llama/Mistral (silu_glu, RMSNorm, rope), Mixtral
(MoE experts w1/w2/w3), Qwen2 (adds qkv biases). HF stores Linear weights
as [out, in]; our einsum layout is [in, out], hence the transposes.
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import DecoderConfig
from deepspeed_tpu.utils.logging import logger

Params = Any


# ---------------------------------------------------------------------------
# config mapping
# ---------------------------------------------------------------------------

_FAMILIES = ("llama", "mistral", "mixtral", "qwen", "qwen2", "qwen2_moe",
              "gpt_neox", "gemma", "gpt2", "opt", "bloom", "falcon",
              "phi", "phi3", "gpt_bigcode", "gptj", "bert", "distilbert",
              "gpt_neo", "internlm")


def _map_hf_act(act: str) -> str:
    """HF activation_function → DecoderConfig.activation. HF 'gelu' is
    the exact erf form; 'gelu_new'/'gelu_fast'/'gelu_pytorch_tanh' are
    the tanh approximation this repo calls plain 'gelu'."""
    table = {"gelu": "gelu_exact", "gelu_new": "gelu", "gelu_fast": "gelu",
             "gelu_pytorch_tanh": "gelu", "relu": "relu"}
    if act not in table:
        raise ValueError(f"unsupported HF activation_function '{act}'")
    return table[act]


def config_from_hf(hf: Dict[str, Any]) -> DecoderConfig:
    """HF config.json dict → DecoderConfig."""
    mt = hf.get("model_type", "llama")
    if mt not in _FAMILIES:
        raise ValueError(f"unsupported model_type '{mt}'; "
                         f"supported: {_FAMILIES}")
    if mt == "bert":
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 512),
            norm="layernorm",
            activation=_map_hf_act(hf.get("hidden_act", "gelu")),
            pos_emb="learned",
            norm_eps=float(hf.get("layer_norm_eps", 1e-12)),
            use_bias=True, tie_embeddings=True,
            causal=False, prenorm=False, embed_norm=True,
            type_vocab_size=int(hf.get("type_vocab_size", 2)),
            mlm_head=True)
    if mt == "distilbert":
        return DecoderConfig(
            hidden_size=hf["dim"],
            num_layers=hf["n_layers"],
            num_heads=hf["n_heads"],
            intermediate_size=hf["hidden_dim"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 512),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation", "gelu")),
            pos_emb="learned",
            norm_eps=1e-12,
            use_bias=True, tie_embeddings=True,
            causal=False, prenorm=False, embed_norm=True,
            type_vocab_size=0, mlm_head=True)
    if mt == "gpt_neox":
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=_map_hf_act(hf.get("hidden_act", "gelu")),
            pos_emb="rope",
            rope_theta=float(hf.get("rotary_emb_base", 10000.0)),
            rotary_pct=float(hf.get("rotary_pct", 0.25)),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            use_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            parallel_block=bool(hf.get("use_parallel_residual", True)),
            parallel_block_norms=2)
    if mt == "gptj":
        dh = hf["n_embd"] // hf["n_head"]
        return DecoderConfig(
            hidden_size=hf["n_embd"],
            num_layers=hf["n_layer"],
            num_heads=hf["n_head"],
            intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("n_positions", 2048),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation_function",
                                          "gelu_new")),
            pos_emb="rope",
            rotary_pct=float(hf.get("rotary_dim") or dh) / dh,
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=True, attn_bias=False,
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            lm_head_bias=True,
            parallel_block=True, parallel_block_norms=1)
    if mt == "qwen":
        # Qwen v1 (reference: inference/v2/model_implementations/qwen/
        # model.py) — llama math, fused biased c_attn, always MHA with
        # head_dim = kv_channels; HF intermediate_size is 2x the real
        # per-projection FFN width (model.py:72)
        if not hf.get("no_bias", True):
            # no_bias=false puts biases on c_proj/w1/w2 too; we have no
            # slots for those — loading would silently drop them
            raise ValueError("qwen v1 checkpoints with no_bias=false are "
                             "not supported (c_proj/mlp biases)")
        dh = int(hf.get("kv_channels", 128))
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"] // 2,
            vocab_size=hf["vocab_size"],
            max_seq_len=int(hf.get("seq_length", 8192)),
            norm="rmsnorm", activation="silu_glu", pos_emb="rope",
            rope_theta=float(hf.get("rotary_emb_base", 10000.0)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-6)),
            use_bias=True, attn_out_bias=False, tie_embeddings=False,
            head_dim_override=(
                dh if dh * hf["num_attention_heads"] != hf["hidden_size"]
                else None))
    if mt == "internlm":
        # llama math with "bias": true on all four attention projections
        # (reference: module_inject/containers InternLMLayerPolicy); the
        # generic llama-layout loader picks up the bias tensors
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads"),
            intermediate_size=hf["intermediate_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="rmsnorm", activation="silu_glu", pos_emb="rope",
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
            use_bias=False, attn_bias=bool(hf.get("bias", True)),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)))
    if mt == "gpt_neo":
        window = int(hf.get("window_size", 256))
        at = hf.get("attention_types") or \
            [[["global", "local"], hf["num_layers"] // 2]]
        kinds = []
        for types, count in at:
            kinds.extend(list(types) * int(count))
        pattern = tuple(0 if k == "global" else window for k in kinds)
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_layers"],
            num_heads=hf["num_heads"],
            intermediate_size=hf.get("intermediate_size")
            or 4 * hf["hidden_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation_function",
                                          "gelu_new")),
            pos_emb="learned",
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=True, attn_bias=False, attn_out_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            layer_window_pattern=pattern)
    if mt == "gpt2":
        return DecoderConfig(
            hidden_size=hf["n_embd"],
            num_layers=hf["n_layer"],
            num_heads=hf["n_head"],
            intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("n_positions", 1024),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation_function",
                                          "gelu_new")),
            pos_emb="learned",
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
    if mt == "gpt_bigcode":
        H = hf["n_head"]
        return DecoderConfig(
            hidden_size=hf["n_embd"],
            num_layers=hf["n_layer"],
            num_heads=H,
            num_kv_heads=1 if hf.get("multi_query", True) else H,
            intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("n_positions", 1024),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation_function",
                                          "gelu_pytorch_tanh")),
            pos_emb="learned",
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
    if mt == "opt":
        if not hf.get("do_layer_norm_before", True):
            raise ValueError("OPT post-norm variants (do_layer_norm_before="
                             "False, e.g. opt-350m) are not supported")
        if hf.get("word_embed_proj_dim", hf["hidden_size"]) != hf["hidden_size"]:
            raise ValueError("OPT word_embed_proj_dim != hidden_size "
                             "(opt-350m projection) is not supported")
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["ffn_dim"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation_function", "relu")),
            pos_emb="learned", use_bias=bool(hf.get("enable_bias", True)),
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
    if mt == "bloom":
        d = hf.get("hidden_size") or hf["n_embed"]
        return DecoderConfig(
            hidden_size=d,
            num_layers=hf["n_layer"],
            num_heads=hf["n_head"],
            intermediate_size=4 * d,
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("seq_length", 2048),
            norm="layernorm", activation="gelu", pos_emb="alibi",
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=True, embed_norm=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
    if mt == "falcon":
        new_arch = bool(hf.get("new_decoder_architecture", False))
        H = hf["num_attention_heads"]
        if new_arch:
            kv = hf.get("num_kv_heads") or H
            norms = hf.get("num_ln_in_parallel_attn") or 2
        else:
            kv = 1 if hf.get("multi_query", True) else H
            norms = 1
        if not hf.get("parallel_attn", True):
            raise ValueError("falcon parallel_attn=False (falcon-rw) "
                             "layout is not supported")
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=H, num_kv_heads=kv,
            intermediate_size=hf.get("ffn_hidden_size") or 4 * hf["hidden_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=_map_hf_act(hf.get("activation", "gelu")),
            pos_emb="alibi" if hf.get("alibi") else "rope",
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            use_bias=bool(hf.get("bias", False)), norm_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            parallel_block=True, parallel_block_norms=norms)
    if mt == "phi":
        if hf.get("qk_layernorm"):
            raise ValueError("phi qk_layernorm=True is not supported")
        return DecoderConfig(
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads")
            or hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            vocab_size=hf["vocab_size"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm", activation="gelu", pos_emb="rope",
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rotary_pct=float(hf.get("partial_rotary_factor", 0.5)),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            use_bias=True,
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            lm_head_bias=True,
            parallel_block=True, parallel_block_norms=1)
    kw = dict(
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        intermediate_size=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_seq_len=hf.get("max_position_embeddings", 4096),
        norm="rmsnorm",
        activation="silu_glu",
        pos_emb="rope",
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
        # qwen2: qkv bias only (use_bias); llama attention_bias=true (the
        # InternLM round-trip layout): biases on all four attention
        # projections via attn_bias — NOT use_bias, so the config
        # re-exports through the same llama+attention_bias branch
        use_bias=(mt in ("qwen2", "qwen2_moe")),
        attn_bias=True if bool(hf.get("attention_bias", False)) else None,
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    # HF semantics differ per family: Mistral applies sliding_window
    # whenever set; Qwen2 gates it behind use_sliding_window=False BY
    # DEFAULT
    if mt == "phi3":
        if hf.get("rope_scaling"):
            raise ValueError("phi3 rope_scaling (longrope) is not "
                             "supported; use the base-context variant")
        kw["rotary_pct"] = float(hf.get("partial_rotary_factor", 1.0))
    use_swa_default = mt not in ("qwen2", "qwen2_moe")
    if hf.get("sliding_window") and hf.get("use_sliding_window",
                                           use_swa_default):
        kw["sliding_window"] = int(hf["sliding_window"])
    if mt == "mixtral":
        kw.update(num_experts=hf["num_local_experts"],
                  num_experts_per_tok=hf.get("num_experts_per_tok", 2))
    if mt == "qwen2_moe":
        if hf.get("decoder_sparse_step", 1) != 1 or \
                hf.get("mlp_only_layers"):
            raise ValueError(
                "qwen2_moe with interleaved dense layers "
                "(decoder_sparse_step != 1 / mlp_only_layers) is not "
                "supported — the stacked-layer scan needs uniform blocks")
        kw.update(
            num_experts=hf["num_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 4),
            norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
            # experts use moe_intermediate_size; the config's dense
            # intermediate_size only applies to mlp_only layers (none)
            intermediate_size=hf["moe_intermediate_size"],
            shared_expert_size=hf["shared_expert_intermediate_size"],
            shared_expert_gate=True)
    if mt == "gemma":
        # gemma stores RMSNorm as (1 + w) — folded into `scale` at load —
        # plus GeGLU, sqrt(d)-scaled embeddings and a decoupled head_dim
        # (GemmaConfig's DEFAULT is 256, NOT hidden//heads)
        kw.update(activation="gelu_glu", scale_embeddings=True,
                  head_dim_override=int(hf.get("head_dim", 256)),
                  tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
        if hf.get("final_logit_softcapping"):
            kw["logit_softcap"] = float(hf["final_logit_softcapping"])
    return DecoderConfig(**kw)


def _is_gemma_layout(cfg: DecoderConfig) -> bool:
    return cfg.activation == "gelu_glu" and cfg.scale_embeddings


def _no_exotics(cfg: DecoderConfig) -> bool:
    """Features NO classic (gpt2/bigcode/opt/bloom/falcon/phi/neox) HF layout has
    a slot for — a config carrying any of them must NOT match those
    branches, or the export silently drops the feature."""
    return (not cfg.num_experts and cfg.head_dim_override is None
            and not cfg.scale_embeddings and not cfg.logit_softcap
            and cfg.sliding_window is None and not cfg.is_glu
            and cfg.layer_window_pattern is None
            and cfg.attn_out_bias is None)


def _is_neox_layout(cfg: DecoderConfig) -> bool:
    """NeoX/Pythia family marker (covers use_parallel_residual False too:
    sequential NeoX still has the layernorm+bias+gelu+rope layout that the
    llama mapping can't express). GQA is excluded — NeoX has no kv-head
    grouping, so a biased GQA falcon must NOT route here (its kv rows
    cannot be re-interleaved into the [H, 3, dh] fused layout)."""
    return (cfg.norm == "layernorm" and cfg.pos_emb == "rope"
            and cfg.use_bias and cfg.activation in ("gelu", "gelu_exact")
            and cfg.has_ln2   # 1-norm parallel models (phi) are NOT neox
            and cfg.kv_heads == cfg.num_heads
            and _no_exotics(cfg) and not cfg.embed_norm
            and not cfg.lm_head_bias)


def config_to_hf(cfg: DecoderConfig) -> Dict[str, Any]:
    def act_name(exact_name="gelu", tanh_name="gelu_new"):
        """HF 'gelu' is exact erf; tanh-approx models must export the
        tanh spelling or transformers reloads with the wrong act."""
        if cfg.activation == "relu":
            return "relu"
        return exact_name if cfg.activation == "gelu_exact" else tanh_name

    if not cfg.causal or not cfg.prenorm:
        # encoder layouts (BERT/DistilBERT): both flags flip together
        if cfg.causal or cfg.prenorm or cfg.pos_emb != "learned" \
                or cfg.norm != "layernorm" or not cfg.mlm_head \
                or not _no_exotics(cfg) or not cfg.embed_norm:
            raise ValueError(
                "config_to_hf: no HF layout for this encoder config "
                f"(causal={cfg.causal} prenorm={cfg.prenorm} "
                f"pos_emb={cfg.pos_emb}); supported encoder exports: "
                "bert (type_vocab_size>0), distilbert")
        if cfg.type_vocab_size:
            return {
                "model_type": "bert",
                "architectures": ["BertForMaskedLM"],
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.num_layers,
                "num_attention_heads": cfg.num_heads,
                "intermediate_size": cfg.ffn_size,
                "vocab_size": cfg.vocab_size,
                "max_position_embeddings": cfg.max_seq_len,
                "type_vocab_size": cfg.type_vocab_size,
                "layer_norm_eps": cfg.norm_eps,
                "hidden_act": act_name(),
                "tie_word_embeddings": True,
                "torch_dtype": "float32",
            }
        return {
            "model_type": "distilbert",
            "architectures": ["DistilBertForMaskedLM"],
            "dim": cfg.hidden_size,
            "n_layers": cfg.num_layers,
            "n_heads": cfg.num_heads,
            "hidden_dim": cfg.ffn_size,
            "vocab_size": cfg.vocab_size,
            "max_position_embeddings": cfg.max_seq_len,
            "activation": act_name(),
            "sinusoidal_pos_embds": False,
            "tie_weights_": True,
            "torch_dtype": "float32",
        }
    if _is_neox_layout(cfg):
        return {
            "model_type": "gpt_neox",
            "architectures": ["GPTNeoXForCausalLM"],
            "hidden_size": cfg.hidden_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "intermediate_size": cfg.ffn_size,
            "vocab_size": cfg.vocab_size,
            "max_position_embeddings": cfg.max_seq_len,
            "rotary_emb_base": cfg.rope_theta,
            "rotary_pct": cfg.rotary_pct,
            "layer_norm_eps": cfg.norm_eps,
            "use_parallel_residual": cfg.parallel_block,
            "tie_word_embeddings": cfg.tie_embeddings,
            "hidden_act": act_name(),
            "torch_dtype": "float32",
        }
    base = {
        "vocab_size": cfg.vocab_size,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "float32",
    }
    if cfg.layer_window_pattern is not None:
        # GPT-Neo: the only layout with per-layer window alternation
        nz = {w for w in cfg.layer_window_pattern if w}
        if (len(nz) > 1 or cfg.norm != "layernorm"
                or cfg.pos_emb != "learned" or not cfg.use_bias
                or cfg.qkv_bias or not cfg.out_bias
                or cfg.parallel_block or cfg.num_experts):
            raise ValueError(
                "config_to_hf: layer_window_pattern only exports as "
                "gpt_neo (layernorm, learned pos, bias-less qkv + biased "
                "out, one distinct local window size); got "
                f"pattern={cfg.layer_window_pattern}")
        kinds = ["global" if w == 0 else "local"
                 for w in cfg.window_per_layer()]
        return {**base, "model_type": "gpt_neo",
                "architectures": ["GPTNeoForCausalLM"],
                "hidden_size": cfg.hidden_size,
                "num_layers": cfg.num_layers,
                "num_heads": cfg.num_heads,
                "intermediate_size": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "window_size": next(iter(nz), 256),
                "attention_types": [[[k], 1] for k in kinds],
                "layer_norm_epsilon": cfg.norm_eps,
                "activation_function": act_name()}
    untied_bias = cfg.lm_head_bias and not cfg.tie_embeddings
    if (cfg.norm == "layernorm" and cfg.pos_emb == "learned"
            and cfg.use_bias and not cfg.parallel_block
            and _no_exotics(cfg) and not cfg.embed_norm
            and not untied_bias   # no lm_head.bias slot in these layouts
            and cfg.kv_heads in (1, cfg.num_heads)):
        if cfg.kv_heads == 1 and cfg.num_heads > 1:   # MQA → bigcode
            return {**base, "model_type": "gpt_bigcode",
                    "architectures": ["GPTBigCodeForCausalLM"],
                    "n_embd": cfg.hidden_size, "n_layer": cfg.num_layers,
                    "n_head": cfg.num_heads,
                    "n_positions": cfg.max_seq_len,
                    "n_inner": cfg.ffn_size, "multi_query": True,
                    "layer_norm_epsilon": cfg.norm_eps,
                    "activation_function":
                        act_name("gelu", "gelu_pytorch_tanh")}
        if cfg.activation == "relu":   # OPT lineage
            return {**base, "model_type": "opt",
                    "architectures": ["OPTForCausalLM"],
                    "hidden_size": cfg.hidden_size,
                    "num_hidden_layers": cfg.num_layers,
                    "num_attention_heads": cfg.num_heads,
                    "ffn_dim": cfg.ffn_size,
                    "max_position_embeddings": cfg.max_seq_len,
                    "word_embed_proj_dim": cfg.hidden_size,
                    "do_layer_norm_before": True, "enable_bias": True,
                    "activation_function": "relu"}
        return {**base, "model_type": "gpt2",
                "architectures": ["GPT2LMHeadModel"],
                "n_embd": cfg.hidden_size, "n_layer": cfg.num_layers,
                "n_head": cfg.num_heads, "n_positions": cfg.max_seq_len,
                "n_ctx": cfg.max_seq_len, "n_inner": cfg.ffn_size,
                "layer_norm_epsilon": cfg.norm_eps,
                "activation_function": act_name()}
    if (cfg.pos_emb == "alibi" and cfg.embed_norm and cfg.use_bias
            and cfg.norm == "layernorm" and not cfg.parallel_block
            and _no_exotics(cfg) and not untied_bias):   # BLOOM
        return {**base, "model_type": "bloom",
                "architectures": ["BloomForCausalLM"],
                "hidden_size": cfg.hidden_size, "n_layer": cfg.num_layers,
                "n_head": cfg.num_heads,
                "layer_norm_epsilon": cfg.norm_eps, "seq_length":
                cfg.max_seq_len}
    if (cfg.parallel_block and cfg.norm == "layernorm"
            and not cfg.lm_head_bias and _no_exotics(cfg)
            and not cfg.embed_norm and cfg.rotary_pct == 1.0
            and (not cfg.use_bias or cfg.has_ln2)):
        # Falcon: pick the fused-qkv generation that can express the
        # head layout — old MQA only fits kv=1 + one shared norm. Biased
        # ONE-norm parallel models fall through to the phi branch below
        # (separate biased projections — the same math, an expressible
        # layout); biased 2-norm GQA exports as falcon "bias": true.
        new_arch = cfg.kv_heads > 1 or cfg.parallel_block_norms == 2
        hf = {**base, "model_type": "falcon",
              "architectures": ["FalconForCausalLM"],
              "hidden_size": cfg.hidden_size,
              "num_hidden_layers": cfg.num_layers,
              "num_attention_heads": cfg.num_heads,
              "ffn_hidden_size": cfg.ffn_size,
              "max_position_embeddings": cfg.max_seq_len,
              "layer_norm_epsilon": cfg.norm_eps,
              "rope_theta": cfg.rope_theta,
              "alibi": cfg.pos_emb == "alibi", "bias": cfg.use_bias,
              "activation": act_name("gelu", "gelu_pytorch_tanh"),
              "parallel_attn": True,
              "new_decoder_architecture": new_arch,
              "multi_query": cfg.kv_heads == 1}
        if new_arch:
            hf["num_kv_heads"] = cfg.kv_heads
            hf["num_ln_in_parallel_attn"] = cfg.parallel_block_norms
        return hf
    if (cfg.parallel_block and not cfg.has_ln2 and cfg.use_bias
            and not cfg.qkv_bias and cfg.pos_emb == "rope"
            and cfg.lm_head_bias and not cfg.tie_embeddings
            and cfg.kv_heads == cfg.num_heads
            # GPTJConfig has NO rope-base slot: a non-default theta must
            # fall through to the no-layout error, not silently reload
            # in transformers with the hardcoded 10000
            and cfg.rope_theta == 10000.0
            and _no_exotics(cfg) and not cfg.embed_norm):   # GPT-J
        return {**base, "model_type": "gptj",
                "architectures": ["GPTJForCausalLM"],
                "n_embd": cfg.hidden_size, "n_layer": cfg.num_layers,
                "n_head": cfg.num_heads, "n_positions": cfg.max_seq_len,
                "n_inner": cfg.ffn_size,
                "rotary_dim": cfg.rope_dim,
                "layer_norm_epsilon": cfg.norm_eps,
                "activation_function": act_name()}
    if (cfg.parallel_block and not cfg.has_ln2 and cfg.use_bias
            and cfg.qkv_bias
            and cfg.pos_emb == "rope" and _no_exotics(cfg)
            and not cfg.embed_norm):   # Phi
        return {**base, "model_type": "phi",
                "architectures": ["PhiForCausalLM"],
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.num_layers,
                "num_attention_heads": cfg.num_heads,
                "num_key_value_heads": cfg.kv_heads,
                "intermediate_size": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "partial_rotary_factor": cfg.rotary_pct,
                "layer_norm_eps": cfg.norm_eps,
                "rope_theta": cfg.rope_theta,
                "hidden_act": act_name(),
                "qk_layernorm": False}
    if not (cfg.norm == "rmsnorm" and cfg.pos_emb == "rope"
            and cfg.is_glu and not cfg.parallel_block
            and not cfg.embed_norm and not untied_bias
            and cfg.rotary_pct == 1.0):
        # the llama-family layouts are sequential-residual, full-rotary,
        # bias-less-head — a config outside every branch must RAISE, not
        # write a silently-wrong checkpoint
        raise ValueError(
            f"config_to_hf: no HF layout for norm={cfg.norm} "
            f"pos_emb={cfg.pos_emb} activation={cfg.activation} "
            f"parallel_block={cfg.parallel_block}; supported exports: "
            f"llama/mistral/mixtral/qwen2-like, gemma, gpt_neox, gpt2, "
            f"gpt_bigcode, opt, bloom, falcon, phi")
    if _is_gemma_layout(cfg):
        mt = "gemma"
        arch = ["GemmaForCausalLM"]
    elif cfg.num_experts and cfg.shared_expert_size:
        mt, arch = "qwen2_moe", ["Qwen2MoeForCausalLM"]
    elif cfg.num_experts:
        mt, arch = "mixtral", ["MixtralForCausalLM"]
    elif cfg.qkv_bias and cfg.out_bias and not cfg.use_bias \
            and cfg.sliding_window is None:
        # InternLM shape: biases on all four attention projections but
        # nowhere else — LlamaConfig expresses it exactly via
        # attention_bias=true (o_proj bias INCLUDED, unlike qwen2)
        mt, arch = "llama", ["LlamaForCausalLM"]
    elif cfg.use_bias:
        # qkv biases exist only in the qwen2 layout of this family;
        # exporting as llama/mistral would silently drop them
        mt, arch = "qwen2", ["Qwen2ForCausalLM"]
    elif cfg.sliding_window is not None:
        # LlamaConfig has no sliding-window support — exporting SWA as
        # 'llama' would silently reload full-causal in transformers
        mt, arch = "mistral", ["MistralForCausalLM"]
    else:
        mt, arch = "llama", ["LlamaForCausalLM"]
    hf = {
        "model_type": mt,
        "architectures": arch,
        "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.kv_heads,
        "intermediate_size": cfg.ffn_size,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "float32",
    }
    if mt == "llama" and cfg.qkv_bias:
        hf["attention_bias"] = True   # InternLM round-trip
    if cfg.sliding_window is not None:
        hf["sliding_window"] = cfg.sliding_window
        if mt == "qwen2":
            hf["use_sliding_window"] = True   # qwen2 defaults it OFF
    if _is_gemma_layout(cfg):
        # always explicit: GemmaConfig's DEFAULT head_dim is 256, not
        # hidden//heads — an omitted key reloads with the wrong shape
        hf["head_dim"] = cfg.head_dim
        hf["hidden_act"] = "gelu_pytorch_tanh"
        hf["hidden_activation"] = "gelu_pytorch_tanh"
        if cfg.logit_softcap:
            hf["final_logit_softcapping"] = cfg.logit_softcap
    elif cfg.head_dim_override is not None:
        hf["head_dim"] = cfg.head_dim_override
    if cfg.num_experts and cfg.shared_expert_size:   # qwen2_moe
        hf.update(num_experts=cfg.num_experts,
                  num_experts_per_tok=cfg.num_experts_per_tok,
                  moe_intermediate_size=cfg.ffn_size,
                  intermediate_size=cfg.ffn_size,
                  shared_expert_intermediate_size=cfg.shared_expert_size,
                  norm_topk_prob=cfg.norm_topk_prob,
                  decoder_sparse_step=1, mlp_only_layers=[])
    elif cfg.num_experts:
        hf["num_local_experts"] = cfg.num_experts
        hf["num_experts_per_tok"] = cfg.num_experts_per_tok
    return hf


# ---------------------------------------------------------------------------
# tensor-name mapping
# ---------------------------------------------------------------------------

def _reader(model_dir: str):
    """Yield a get(name)->np.ndarray over all safetensors shards (streamed:
    tensors load lazily, one at a time — the 70B-scale requirement of the
    reference's HuggingFaceCheckpointEngine)."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as fh:
            weight_map = json.load(fh)["weight_map"]
        handles: Dict[str, Any] = {}

        def get(name: str) -> np.ndarray:
            shard = weight_map[name]
            if shard not in handles:
                handles[shard] = safe_open(
                    os.path.join(model_dir, shard), framework="np")
            return handles[shard].get_tensor(name)

        return get, set(weight_map)
    single = os.path.join(model_dir, "model.safetensors")
    handle = safe_open(single, framework="np")
    names = set(handle.keys())
    return handle.get_tensor, names


def load_hf_checkpoint(model_dir: str, dtype=np.float32
                       ) -> Tuple[DecoderConfig, Params]:
    """Load an HF Llama/Mistral/Mixtral/Qwen2 checkpoint directory into
    (DecoderConfig, params pytree)."""
    with open(os.path.join(model_dir, "config.json")) as fh:
        hf_cfg = json.load(fh)
    cfg = config_from_hf(hf_cfg)
    get, names = _reader(model_dir)
    params = params_from_state(cfg, hf_cfg, get, names, dtype)
    logger.info(f"loaded HF checkpoint from {model_dir}: "
                f"{cfg.num_params() / 1e6:.1f}M params, "
                f"{hf_cfg.get('model_type')}")
    return cfg, params


def params_from_state(cfg: DecoderConfig, hf_cfg: Dict[str, Any], get, names,
                      dtype=np.float32) -> Params:
    """Map HF-convention tensor names → params pytree, source-agnostic.

    ``get(name) -> np.ndarray`` and ``names`` may come from safetensors
    shards (`load_hf_checkpoint`) or from a torch state dict (the
    DeepSpeed-checkpoint importer, `checkpoint/ds_import.py`) — the name
    conventions are identical because the reference engine checkpoints the
    wrapped HF module's own state_dict (reference runtime/engine.py:3621).
    """
    L = cfg.num_layers
    mt = hf_cfg.get("model_type")
    if mt == "bert":
        return _load_bert(cfg, get, names, dtype)
    if mt == "distilbert":
        return _load_distilbert(cfg, get, names, dtype)
    if mt == "gpt_neox":
        return _load_neox(cfg, get, dtype)
    if mt == "gpt_neo":
        return _load_gptneo(cfg, get, names, dtype)
    if mt == "qwen":
        return _load_qwen(cfg, get, names, dtype)
    if mt == "gpt2":
        return _load_gpt2(cfg, get, names, dtype)
    if mt == "gpt_bigcode":
        return _load_bigcode(cfg, get, names, dtype)
    if mt == "opt":
        return _load_opt(cfg, get, names, dtype)
    if mt == "bloom":
        return _load_bloom(cfg, get, names, dtype)
    if mt == "falcon":
        return _load_falcon(cfg, hf_cfg, get, names, dtype)
    if mt == "phi":
        return _load_phi(cfg, get, dtype)
    if mt == "phi3":
        return _load_phi3(cfg, get, names, dtype)
    if mt == "gptj":
        return _load_gptj(cfg, get, dtype)

    def T(name):
        return np.ascontiguousarray(get(name).astype(dtype).T)

    def stackT(fmt):
        return np.stack([T(fmt.format(i)) for i in range(L)])

    def stack(fmt):
        return np.stack([get(fmt.format(i)).astype(dtype)
                         for i in range(L)])

    p = "model.layers.{}."
    attn = {
        "wq": stackT(p + "self_attn.q_proj.weight"),
        "wk": stackT(p + "self_attn.k_proj.weight"),
        "wv": stackT(p + "self_attn.v_proj.weight"),
        "wo": stackT(p + "self_attn.o_proj.weight"),
    }
    if p.format(0) + "self_attn.q_proj.bias" in names:   # qwen2/internlm
        attn["bq"] = stack(p + "self_attn.q_proj.bias")
        attn["bk"] = stack(p + "self_attn.k_proj.bias")
        attn["bv"] = stack(p + "self_attn.v_proj.bias")
        # internlm ("bias": true) also biases o_proj; qwen2 does not
        attn["bo"] = stack(p + "self_attn.o_proj.bias") \
            if p.format(0) + "self_attn.o_proj.bias" in names \
            else np.zeros((L, cfg.hidden_size), dtype)

    layers: Dict[str, Any] = {
        "attn": attn,
        "ln1": {"scale": stack(p + "input_layernorm.weight")},
        "ln2": {"scale": stack(p + "post_attention_layernorm.weight")},
    }
    if cfg.num_experts:
        E = cfg.num_experts
        is_qwen_moe = hf_cfg.get("model_type") == "qwen2_moe"
        ep = p + ("mlp.experts.{}." if is_qwen_moe
                  else "block_sparse_moe.experts.{}.")

        def estackT(suffix):
            return np.stack([
                np.stack([T(ep.format(i, e) + suffix) for e in range(E)])
                for i in range(L)])
        if is_qwen_moe:
            layers["moe"] = {
                "router": stackT(p + "mlp.gate.weight"),
                "wg": estackT("gate_proj.weight"),
                "wi": estackT("up_proj.weight"),
                "wo": estackT("down_proj.weight"),
                "shared": {
                    "wg": stackT(p + "mlp.shared_expert.gate_proj.weight"),
                    "wi": stackT(p + "mlp.shared_expert.up_proj.weight"),
                    "wo": stackT(p + "mlp.shared_expert.down_proj.weight"),
                    "gate": stackT(p + "mlp.shared_expert_gate.weight"),
                },
            }
        else:
            layers["moe"] = {
                "router": stackT(p + "block_sparse_moe.gate.weight"),
                "wg": estackT("w1.weight"),       # mixtral w1 = gate
                "wo": estackT("w2.weight"),       # w2 = down
                "wi": estackT("w3.weight"),       # w3 = up
            }
    else:
        layers["mlp"] = {
            "wg": stackT(p + "mlp.gate_proj.weight"),
            "wi": stackT(p + "mlp.up_proj.weight"),
            "wo": stackT(p + "mlp.down_proj.weight"),
        }

    params: Params = {
        "embed": {"tokens": get("model.embed_tokens.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {"scale": get("model.norm.weight").astype(dtype)},
    }
    if hf_cfg.get("model_type") == "gemma":
        # HF gemma RMSNorm computes x̂·(1+w); our _norm computes x̂·scale
        for ln in (layers["ln1"], layers["ln2"], params["final_norm"]):
            ln["scale"] = ln["scale"] + 1.0
    if not cfg.tie_embeddings:
        params["lm_head"] = T("lm_head.weight")
    return params


def _load_neox(cfg: DecoderConfig, get, dtype) -> Params:
    """GPT-NeoX/Pythia layout: fused query_key_value with PER-HEAD
    interleaving ([heads, 3, dh] on the out dim), separate input/
    post_attention norms, biases everywhere."""
    L, H, dh, D = (cfg.num_layers, cfg.num_heads, cfg.head_dim,
                   cfg.hidden_size)
    p = "gpt_neox.layers.{}."

    def split_qkv_w(i):
        w = get(p.format(i) + "attention.query_key_value.weight")
        w = w.astype(dtype).reshape(H, 3, dh, D)
        # → our [in, out] einsum layout, out = head-major × dh
        return tuple(np.ascontiguousarray(
            w[:, j].reshape(H * dh, D).T) for j in range(3))

    def split_qkv_b(i):
        b = get(p.format(i) + "attention.query_key_value.bias")
        b = b.astype(dtype).reshape(H, 3, dh)
        return tuple(b[:, j].reshape(-1) for j in range(3))

    qw, kw, vw = zip(*(split_qkv_w(i) for i in range(L)))
    qb, kb, vb = zip(*(split_qkv_b(i) for i in range(L)))

    def stack(fmt):
        return np.stack([get(fmt.format(i)).astype(dtype)
                         for i in range(L)])

    def stackT(fmt):
        return np.stack([np.ascontiguousarray(
            get(fmt.format(i)).astype(dtype).T) for i in range(L)])

    layers = {
        "attn": {
            "wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
            "wo": stackT(p + "attention.dense.weight"),
            "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
            "bo": stack(p + "attention.dense.bias"),
        },
        "ln1": {"scale": stack(p + "input_layernorm.weight"),
                "bias": stack(p + "input_layernorm.bias")},
        "ln2": {"scale": stack(p + "post_attention_layernorm.weight"),
                "bias": stack(p + "post_attention_layernorm.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.dense_h_to_4h.weight"),
            "bi": stack(p + "mlp.dense_h_to_4h.bias"),
            "wo": stackT(p + "mlp.dense_4h_to_h.weight"),
            "bo": stack(p + "mlp.dense_4h_to_h.bias"),
        },
    }
    params: Params = {
        "embed": {"tokens": get("gpt_neox.embed_in.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("gpt_neox.final_layer_norm.weight").astype(dtype),
            "bias": get("gpt_neox.final_layer_norm.bias").astype(dtype)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = np.ascontiguousarray(
            get("embed_out.weight").astype(dtype).T)
    return params


def _load_gptneo(cfg: DecoderConfig, get, names, dtype) -> Params:
    """GPT-Neo layout (reference: module_inject/containers/gptneo.py):
    separate bias-less q/k/v Linears + biased out_proj, GPT-2-style
    ln/mlp naming but nn.Linear ([out, in]) weights. GPT-Neo computes
    attention WITHOUT the 1/sqrt(dh) scale; we fold sqrt(dh) into wq at
    load so the in-repo scaled kernels match exactly (exported back out
    by _export_gptneo)."""
    import math as _math
    L = cfg.num_layers
    stack, stackT = _stack_helpers(get, L, dtype)
    p = "transformer.h.{}."
    scale = np.asarray(_math.sqrt(cfg.head_dim), dtype)
    layers = {
        "attn": {
            "wq": stackT(p + "attn.attention.q_proj.weight") * scale,
            "wk": stackT(p + "attn.attention.k_proj.weight"),
            "wv": stackT(p + "attn.attention.v_proj.weight"),
            "wo": stackT(p + "attn.attention.out_proj.weight"),
            "bo": stack(p + "attn.attention.out_proj.bias"),
        },
        "ln1": {"scale": stack(p + "ln_1.weight"),
                "bias": stack(p + "ln_1.bias")},
        "ln2": {"scale": stack(p + "ln_2.weight"),
                "bias": stack(p + "ln_2.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.c_fc.weight"),
            "bi": stack(p + "mlp.c_fc.bias"),
            "wo": stackT(p + "mlp.c_proj.weight"),
            "bo": stack(p + "mlp.c_proj.bias"),
        },
    }
    params: Params = {
        "embed": {
            "tokens": get("transformer.wte.weight").astype(dtype),
            "pos": get("transformer.wpe.weight").astype(dtype),
        },
        "layers": layers,
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dtype),
            "bias": get("transformer.ln_f.bias").astype(dtype)},
    }
    return _attach_untied_head(params, cfg, get, names, dtype)


def _load_bert(cfg: DecoderConfig, get, names, dtype) -> Params:
    """BERT encoder layout (reference: module_inject/containers/bert.py).

    Post-LN mapping: HF ``attention.output.LayerNorm`` → our ``ln1``
    (applied after the attention residual), ``output.LayerNorm`` →
    ``ln2``. Works for both ``BertForMaskedLM`` (``bert.``-prefixed +
    ``cls.predictions`` head) and a bare ``BertModel`` checkpoint."""
    L = cfg.num_layers
    pre = "bert." if "bert.embeddings.word_embeddings.weight" in names \
        else ""
    stack, stackT = _stack_helpers(get, L, dtype)
    p = pre + "encoder.layer.{}."
    layers = {
        "attn": {
            "wq": stackT(p + "attention.self.query.weight"),
            "wk": stackT(p + "attention.self.key.weight"),
            "wv": stackT(p + "attention.self.value.weight"),
            "wo": stackT(p + "attention.output.dense.weight"),
            "bq": stack(p + "attention.self.query.bias"),
            "bk": stack(p + "attention.self.key.bias"),
            "bv": stack(p + "attention.self.value.bias"),
            "bo": stack(p + "attention.output.dense.bias"),
        },
        "ln1": {"scale": stack(p + "attention.output.LayerNorm.weight"),
                "bias": stack(p + "attention.output.LayerNorm.bias")},
        "ln2": {"scale": stack(p + "output.LayerNorm.weight"),
                "bias": stack(p + "output.LayerNorm.bias")},
        "mlp": {
            "wi": stackT(p + "intermediate.dense.weight"),
            "bi": stack(p + "intermediate.dense.bias"),
            "wo": stackT(p + "output.dense.weight"),
            "bo": stack(p + "output.dense.bias"),
        },
    }
    e = pre + "embeddings."
    params: Params = {
        "embed": {
            "tokens": get(e + "word_embeddings.weight").astype(dtype),
            "pos": get(e + "position_embeddings.weight").astype(dtype),
            "token_type":
                get(e + "token_type_embeddings.weight").astype(dtype),
        },
        "embed_norm": {"scale": get(e + "LayerNorm.weight").astype(dtype),
                       "bias": get(e + "LayerNorm.bias").astype(dtype)},
        "layers": layers,
    }
    if "cls.predictions.transform.dense.weight" in names:
        t = "cls.predictions.transform."
        params["mlm_head"] = {
            "dense": np.ascontiguousarray(
                get(t + "dense.weight").astype(dtype).T),
            "dense_bias": get(t + "dense.bias").astype(dtype),
            "ln": {"scale": get(t + "LayerNorm.weight").astype(dtype),
                   "bias": get(t + "LayerNorm.bias").astype(dtype)},
            "vocab_bias": get("cls.predictions.bias").astype(dtype),
        }
    return params


def _load_distilbert(cfg: DecoderConfig, get, names, dtype) -> Params:
    """DistilBERT layout (reference: module_inject/containers/
    distil_bert.py): BERT math without token types; the MLM head tensors
    are top-level ``vocab_transform``/``vocab_layer_norm``/
    ``vocab_projector`` (projector weight tied to the embeddings)."""
    L = cfg.num_layers
    pre = "distilbert." \
        if "distilbert.embeddings.word_embeddings.weight" in names else ""
    stack, stackT = _stack_helpers(get, L, dtype)
    p = pre + "transformer.layer.{}."
    layers = {
        "attn": {
            "wq": stackT(p + "attention.q_lin.weight"),
            "wk": stackT(p + "attention.k_lin.weight"),
            "wv": stackT(p + "attention.v_lin.weight"),
            "wo": stackT(p + "attention.out_lin.weight"),
            "bq": stack(p + "attention.q_lin.bias"),
            "bk": stack(p + "attention.k_lin.bias"),
            "bv": stack(p + "attention.v_lin.bias"),
            "bo": stack(p + "attention.out_lin.bias"),
        },
        "ln1": {"scale": stack(p + "sa_layer_norm.weight"),
                "bias": stack(p + "sa_layer_norm.bias")},
        "ln2": {"scale": stack(p + "output_layer_norm.weight"),
                "bias": stack(p + "output_layer_norm.bias")},
        "mlp": {
            "wi": stackT(p + "ffn.lin1.weight"),
            "bi": stack(p + "ffn.lin1.bias"),
            "wo": stackT(p + "ffn.lin2.weight"),
            "bo": stack(p + "ffn.lin2.bias"),
        },
    }
    e = pre + "embeddings."
    params: Params = {
        "embed": {
            "tokens": get(e + "word_embeddings.weight").astype(dtype),
            "pos": get(e + "position_embeddings.weight").astype(dtype),
        },
        "embed_norm": {"scale": get(e + "LayerNorm.weight").astype(dtype),
                       "bias": get(e + "LayerNorm.bias").astype(dtype)},
        "layers": layers,
    }
    if "vocab_transform.weight" in names:
        params["mlm_head"] = {
            "dense": np.ascontiguousarray(
                get("vocab_transform.weight").astype(dtype).T),
            "dense_bias": get("vocab_transform.bias").astype(dtype),
            "ln": {"scale": get("vocab_layer_norm.weight").astype(dtype),
                   "bias": get("vocab_layer_norm.bias").astype(dtype)},
            "vocab_bias": get("vocab_projector.bias").astype(dtype),
        }
    return params


def _attach_untied_head(params: Params, cfg: DecoderConfig, get, names,
                        dtype) -> Params:
    """Untied fine-tunes of normally-tied families (GPT-2/BLOOM/Falcon)
    carry an explicit lm_head.weight; a config/params mismatch here would
    crash later in lm_logits with a bare KeyError."""
    if cfg.tie_embeddings:
        return params
    if "lm_head.weight" not in names:
        raise ValueError("checkpoint says tie_word_embeddings=False but "
                         "has no lm_head.weight tensor")
    params["lm_head"] = np.ascontiguousarray(
        get("lm_head.weight").astype(dtype).T)
    return params


def _stack_helpers(get, L, dtype):
    """(stack, stackT) over per-layer tensor names."""
    def stack(fmt):
        return np.stack([get(fmt.format(i)).astype(dtype)
                         for i in range(L)])

    def stackT(fmt):
        return np.stack([np.ascontiguousarray(
            get(fmt.format(i)).astype(dtype).T) for i in range(L)])
    return stack, stackT


def _load_qwen(cfg: DecoderConfig, get, names, dtype) -> Params:
    """Qwen v1 layout (reference: inference/v2/model_implementations/
    qwen/container.py:54–61): nn.Linear fused ``attn.c_attn`` — contiguous
    q|k|v thirds on the out dim, WITH bias — over RMSNorm ``ln_1``/``ln_2``
    (weight only); ``mlp.w1`` is the UP projection and ``mlp.w2`` the GATE
    (the reference maps w1→up_params, w2→gate_params); ``c_proj`` tensors
    are bias-less; untied ``lm_head``."""
    L = cfg.num_layers
    p = "transformer.h.{}."
    stack, stackT = _stack_helpers(get, L, dtype)

    qw, kw_, vw = (np.ascontiguousarray(a) for a in np.split(
        stackT(p + "attn.c_attn.weight"), 3, axis=2))
    qb, kb, vb = (np.ascontiguousarray(a) for a in np.split(
        stack(p + "attn.c_attn.bias"), 3, axis=1))
    layers = {
        "attn": {"wq": qw, "wk": kw_, "wv": vw,
                 "wo": stackT(p + "attn.c_proj.weight"),
                 "bq": qb, "bk": kb, "bv": vb},
        "ln1": {"scale": stack(p + "ln_1.weight")},
        "ln2": {"scale": stack(p + "ln_2.weight")},
        "mlp": {"wi": stackT(p + "mlp.w1.weight"),    # w1 = up
                "wg": stackT(p + "mlp.w2.weight"),    # w2 = gate
                "wo": stackT(p + "mlp.c_proj.weight")},
    }
    return _attach_untied_head({
        "embed": {"tokens": get("transformer.wte.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dtype)},
    }, cfg, get, names, dtype)


def _load_gpt2(cfg: DecoderConfig, get, names, dtype) -> Params:
    """GPT-2 layout: Conv1D weights already [in, out]; fused c_attn with
    COLUMN-CONCATENATED q|k|v (not head-interleaved), learned positions."""
    L, D = cfg.num_layers, cfg.hidden_size
    p = "transformer.h.{}."
    stack, _ = _stack_helpers(get, L, dtype)

    def split_cols(fmt, axis):
        full = np.stack([get(fmt.format(i)).astype(dtype)
                         for i in range(L)])
        return np.split(full, 3, axis=axis)

    qw, kw_, vw = split_cols(p + "attn.c_attn.weight", axis=2)
    qb, kb, vb = split_cols(p + "attn.c_attn.bias", axis=1)
    layers = {
        "attn": {
            "wq": np.ascontiguousarray(qw), "wk": np.ascontiguousarray(kw_),
            "wv": np.ascontiguousarray(vw),
            "wo": stack(p + "attn.c_proj.weight"),
            "bq": np.ascontiguousarray(qb), "bk": np.ascontiguousarray(kb),
            "bv": np.ascontiguousarray(vb),
            "bo": stack(p + "attn.c_proj.bias"),
        },
        "ln1": {"scale": stack(p + "ln_1.weight"),
                "bias": stack(p + "ln_1.bias")},
        "ln2": {"scale": stack(p + "ln_2.weight"),
                "bias": stack(p + "ln_2.bias")},
        "mlp": {
            "wi": stack(p + "mlp.c_fc.weight"),
            "bi": stack(p + "mlp.c_fc.bias"),
            "wo": stack(p + "mlp.c_proj.weight"),
            "bo": stack(p + "mlp.c_proj.bias"),
        },
    }
    return _attach_untied_head({
        "embed": {"tokens": get("transformer.wte.weight").astype(dtype),
                  "pos": get("transformer.wpe.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dtype),
            "bias": get("transformer.ln_f.bias").astype(dtype)},
    }, cfg, get, names, dtype)


def _load_bigcode(cfg: DecoderConfig, get, names, dtype) -> Params:
    """GPT-BigCode (SantaCoder/StarCoder) layout: GPT-2 names but
    nn.Linear ([out, in]) weights. Fused c_attn packing differs by
    variant: MQA = q | 1-head k | 1-head v concatenated on the out dim;
    MHA (multi_query=False) = NeoX-style HEAD-INTERLEAVED [H, 3, dh]."""
    L, D, H, dh = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                   cfg.head_dim)
    kvd = cfg.kv_heads * dh
    p = "transformer.h.{}."
    stack, stackT = _stack_helpers(get, L, dtype)

    def split_attn(i):
        w = np.ascontiguousarray(
            get(p.format(i) + "attn.c_attn.weight").astype(dtype).T)
        b = get(p.format(i) + "attn.c_attn.bias").astype(dtype)
        if cfg.kv_heads == 1:   # MQA concat
            return ((w[:, :D], w[:, D:D + kvd], w[:, D + kvd:]),
                    (b[:D], b[D:D + kvd], b[D + kvd:]))
        wi = w.reshape(D, H, 3, dh)
        bi = b.reshape(H, 3, dh)
        return (tuple(np.ascontiguousarray(wi[:, :, j].reshape(D, H * dh))
                      for j in range(3)),
                tuple(bi[:, j].reshape(-1) for j in range(3)))

    ws, bs = zip(*(split_attn(i) for i in range(L)))
    layers = {
        "attn": {
            "wq": np.stack([w[0] for w in ws]),
            "wk": np.stack([w[1] for w in ws]),
            "wv": np.stack([w[2] for w in ws]),
            "wo": stackT(p + "attn.c_proj.weight"),
            "bq": np.stack([b[0] for b in bs]),
            "bk": np.stack([b[1] for b in bs]),
            "bv": np.stack([b[2] for b in bs]),
            "bo": stack(p + "attn.c_proj.bias"),
        },
        "ln1": {"scale": stack(p + "ln_1.weight"),
                "bias": stack(p + "ln_1.bias")},
        "ln2": {"scale": stack(p + "ln_2.weight"),
                "bias": stack(p + "ln_2.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.c_fc.weight"),
            "bi": stack(p + "mlp.c_fc.bias"),
            "wo": stackT(p + "mlp.c_proj.weight"),
            "bo": stack(p + "mlp.c_proj.bias"),
        },
    }
    return _attach_untied_head({
        "embed": {"tokens": get("transformer.wte.weight").astype(dtype),
                  "pos": get("transformer.wpe.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dtype),
            "bias": get("transformer.ln_f.bias").astype(dtype)},
    }, cfg, get, names, dtype)


def _load_opt(cfg: DecoderConfig, get, names, dtype) -> Params:
    """OPT layout: separate q/k/v/out projections with biases, ReLU MLP,
    learned positions with the +2 row offset (embed_positions stores
    max_position_embeddings + 2 rows; dense sequences index position+2,
    so the table is loaded with the first two rows dropped)."""
    L = cfg.num_layers
    p = "model.decoder.layers.{}."
    stack, stackT = _stack_helpers(get, L, dtype)
    layers = {
        "attn": {
            "wq": stackT(p + "self_attn.q_proj.weight"),
            "wk": stackT(p + "self_attn.k_proj.weight"),
            "wv": stackT(p + "self_attn.v_proj.weight"),
            "wo": stackT(p + "self_attn.out_proj.weight"),
            "bq": stack(p + "self_attn.q_proj.bias"),
            "bk": stack(p + "self_attn.k_proj.bias"),
            "bv": stack(p + "self_attn.v_proj.bias"),
            "bo": stack(p + "self_attn.out_proj.bias"),
        },
        "ln1": {"scale": stack(p + "self_attn_layer_norm.weight"),
                "bias": stack(p + "self_attn_layer_norm.bias")},
        "ln2": {"scale": stack(p + "final_layer_norm.weight"),
                "bias": stack(p + "final_layer_norm.bias")},
        "mlp": {
            "wi": stackT(p + "fc1.weight"), "bi": stack(p + "fc1.bias"),
            "wo": stackT(p + "fc2.weight"), "bo": stack(p + "fc2.bias"),
        },
    }
    params: Params = {
        "embed": {
            "tokens": get("model.decoder.embed_tokens.weight").astype(dtype),
            "pos": get("model.decoder.embed_positions.weight"
                       ).astype(dtype)[2:],
        },
        "layers": layers,
        "final_norm": {
            "scale": get("model.decoder.final_layer_norm.weight").astype(dtype),
            "bias": get("model.decoder.final_layer_norm.bias").astype(dtype)},
    }
    return _attach_untied_head(params, cfg, get, names, dtype)


def _load_bloom(cfg: DecoderConfig, get, names, dtype) -> Params:
    """BLOOM layout: NeoX-style HEAD-INTERLEAVED fused query_key_value
    ([H, 3, dh] on the out dim), word-embeddings LayerNorm, ALiBi (no
    positional parameters)."""
    L, H, dh = cfg.num_layers, cfg.num_heads, cfg.head_dim
    p = "transformer.h.{}."
    stack, stackT = _stack_helpers(get, L, dtype)

    def split_qkv(i):
        w = get(p.format(i) + "self_attention.query_key_value.weight")
        w = w.astype(dtype).reshape(H, 3, dh, cfg.hidden_size)
        b = get(p.format(i) + "self_attention.query_key_value.bias")
        b = b.astype(dtype).reshape(H, 3, dh)
        return ([np.ascontiguousarray(w[:, j].reshape(H * dh, -1).T)
                 for j in range(3)],
                [b[:, j].reshape(-1) for j in range(3)])

    ws, bs = zip(*(split_qkv(i) for i in range(L)))
    layers = {
        "attn": {
            "wq": np.stack([w[0] for w in ws]),
            "wk": np.stack([w[1] for w in ws]),
            "wv": np.stack([w[2] for w in ws]),
            "wo": stackT(p + "self_attention.dense.weight"),
            "bq": np.stack([b[0] for b in bs]),
            "bk": np.stack([b[1] for b in bs]),
            "bv": np.stack([b[2] for b in bs]),
            "bo": stack(p + "self_attention.dense.bias"),
        },
        "ln1": {"scale": stack(p + "input_layernorm.weight"),
                "bias": stack(p + "input_layernorm.bias")},
        "ln2": {"scale": stack(p + "post_attention_layernorm.weight"),
                "bias": stack(p + "post_attention_layernorm.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.dense_h_to_4h.weight"),
            "bi": stack(p + "mlp.dense_h_to_4h.bias"),
            "wo": stackT(p + "mlp.dense_4h_to_h.weight"),
            "bo": stack(p + "mlp.dense_4h_to_h.bias"),
        },
    }
    return _attach_untied_head({
        "embed": {"tokens":
                  get("transformer.word_embeddings.weight").astype(dtype)},
        "embed_norm": {
            "scale": get("transformer.word_embeddings_layernorm.weight"
                         ).astype(dtype),
            "bias": get("transformer.word_embeddings_layernorm.bias"
                        ).astype(dtype)},
        "layers": layers,
        "final_norm": {"scale": get("transformer.ln_f.weight").astype(dtype),
                       "bias": get("transformer.ln_f.bias").astype(dtype)},
    }, cfg, get, names, dtype)


def _load_falcon(cfg: DecoderConfig, hf_cfg, get, names, dtype) -> Params:
    """Falcon layout: bias-less linears (unless config "bias": true) with
    biased LayerNorms, fused query_key_value whose packing differs by
    generation — MQA (7B: H query heads then one k then one v),
    new_decoder_architecture (40B: per-kv-group [g queries, k, v]
    interleave), or NeoX-style [H, 3, dh] when multi_query=False."""
    L, H, KV, dh, D = (cfg.num_layers, cfg.num_heads, cfg.kv_heads,
                       cfg.head_dim, cfg.hidden_size)
    new_arch = bool(hf_cfg.get("new_decoder_architecture", False))
    p = "transformer.h.{}."
    stack, stackT = _stack_helpers(get, L, dtype)

    def split_fused(m, trailing):
        """Un-pack one fused qkv tensor of shape [fused_out, *trailing]
        into (q, k, v) rows following the generation's packing."""
        if new_arch:
            g = H // KV
            m = m.reshape(KV, g + 2, dh, *trailing)
            return (m[:, :g].reshape(H * dh, *trailing),
                    m[:, g].reshape(KV * dh, *trailing),
                    m[:, g + 1].reshape(KV * dh, *trailing))
        if KV == 1:
            m = m.reshape(H + 2, dh, *trailing)
            return (m[:H].reshape(H * dh, *trailing),
                    m[H].reshape(dh, *trailing),
                    m[H + 1].reshape(dh, *trailing))
        m = m.reshape(H, 3, dh, *trailing)
        return tuple(m[:, j].reshape(H * dh, *trailing) for j in range(3))

    def split_qkv(i):
        w = get(p.format(i) + "self_attention.query_key_value.weight"
                ).astype(dtype)
        return tuple(np.ascontiguousarray(m.T)
                     for m in split_fused(w, (D,)))

    qw, kw_, vw = zip(*(split_qkv(i) for i in range(L)))
    layers = {
        "attn": {
            "wq": np.stack(qw), "wk": np.stack(kw_), "wv": np.stack(vw),
            "wo": stackT(p + "self_attention.dense.weight"),
        },
        "mlp": {
            "wi": stackT(p + "mlp.dense_h_to_4h.weight"),
            "wo": stackT(p + "mlp.dense_4h_to_h.weight"),
        },
    }
    if cfg.use_bias:   # falcon-rw-style "bias": true checkpoints
        def split_qkv_b(i):
            b = get(p.format(i) + "self_attention.query_key_value.bias"
                    ).astype(dtype)
            return split_fused(b, ())

        qb, kb, vb = zip(*(split_qkv_b(i) for i in range(L)))
        layers["attn"].update(
            bq=np.stack(qb), bk=np.stack(kb), bv=np.stack(vb),
            bo=stack(p + "self_attention.dense.bias"))
        layers["mlp"].update(
            bi=stack(p + "mlp.dense_h_to_4h.bias"),
            bo=stack(p + "mlp.dense_4h_to_h.bias"))
    if cfg.parallel_block_norms == 2:
        layers["ln1"] = {"scale": stack(p + "ln_attn.weight"),
                         "bias": stack(p + "ln_attn.bias")}
        layers["ln2"] = {"scale": stack(p + "ln_mlp.weight"),
                         "bias": stack(p + "ln_mlp.bias")}
    else:
        layers["ln1"] = {"scale": stack(p + "input_layernorm.weight"),
                         "bias": stack(p + "input_layernorm.bias")}
    return _attach_untied_head({
        "embed": {"tokens":
                  get("transformer.word_embeddings.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {"scale": get("transformer.ln_f.weight").astype(dtype),
                       "bias": get("transformer.ln_f.bias").astype(dtype)},
    }, cfg, get, names, dtype)


def _load_phi3(cfg: DecoderConfig, get, names, dtype) -> Params:
    """Phi-3 layout: llama-family math with FUSED qkv_proj ([q|k|v] on
    the out dim) and FUSED gate_up_proj ([gate|up]); no biases."""
    L, D = cfg.num_layers, cfg.hidden_size
    qd = cfg.q_dim
    kvd = cfg.kv_heads * cfg.head_dim
    h = cfg.ffn_size
    p = "model.layers.{}."
    stack, stackT = _stack_helpers(get, L, dtype)

    def split_qkv(i):
        # transposed VIEW; np.stack below makes the one contiguous copy
        w = get(p.format(i) + "self_attn.qkv_proj.weight").astype(dtype).T
        return w[:, :qd], w[:, qd:qd + kvd], w[:, qd + kvd:]

    def split_gate_up(i):
        w = get(p.format(i) + "mlp.gate_up_proj.weight").astype(dtype).T
        return w[:, :h], w[:, h:]

    qw, kw_, vw = zip(*(split_qkv(i) for i in range(L)))
    gw, uw = zip(*(split_gate_up(i) for i in range(L)))
    layers = {
        "attn": {
            "wq": np.stack(qw), "wk": np.stack(kw_), "wv": np.stack(vw),
            "wo": stackT(p + "self_attn.o_proj.weight"),
        },
        "ln1": {"scale": stack(p + "input_layernorm.weight")},
        "ln2": {"scale": stack(p + "post_attention_layernorm.weight")},
        "mlp": {
            "wg": np.stack(gw), "wi": np.stack(uw),
            "wo": stackT(p + "mlp.down_proj.weight"),
        },
    }
    params: Params = {
        "embed": {"tokens": get("model.embed_tokens.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {"scale": get("model.norm.weight").astype(dtype)},
    }
    return _attach_untied_head(params, cfg, get, names, dtype)


def _gptj_rope_perm(cfg: DecoderConfig, inverse: bool = False) -> np.ndarray:
    """Per-head column permutation folding GPT-J's INTERLEAVED rotary
    pairing (HF rotate_every_two: pair (2j, 2j+1) gets frequency j) into
    this repo's rotate-half convention (pair (j, j+rot/2) gets frequency
    j): new position j takes original 2j, new j+rot/2 takes 2j+1, tail
    dims pass through. Both conventions then compute identical attention
    scores because q and k share the permutation. Same trick as the
    Meta→HF llama weight conversion, in the other direction."""
    dh, rot = cfg.head_dim, cfg.rope_dim
    perm = np.concatenate([np.arange(0, rot, 2), np.arange(1, rot, 2),
                           np.arange(rot, dh)])
    if inverse:
        perm = np.argsort(perm)
    full = np.concatenate([perm + h * dh for h in range(cfg.num_heads)])
    return full


def _load_gptj(cfg: DecoderConfig, get, dtype) -> Params:
    """GPT-J layout: parallel residual with ONE shared ln_1, bias-less
    q/k/v/out_proj, biased fc_in/fc_out, interleaved partial rotary
    (folded into the q/k permutation above), untied lm_head WITH bias."""
    L = cfg.num_layers
    p = "transformer.h.{}."
    stack, stackT = _stack_helpers(get, L, dtype)
    perm = _gptj_rope_perm(cfg)
    layers = {
        "attn": {
            "wq": stackT(p + "attn.q_proj.weight")[:, :, perm],
            "wk": stackT(p + "attn.k_proj.weight")[:, :, perm],
            "wv": stackT(p + "attn.v_proj.weight"),
            "wo": stackT(p + "attn.out_proj.weight"),
        },
        "ln1": {"scale": stack(p + "ln_1.weight"),
                "bias": stack(p + "ln_1.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.fc_in.weight"),
            "bi": stack(p + "mlp.fc_in.bias"),
            "wo": stackT(p + "mlp.fc_out.weight"),
            "bo": stack(p + "mlp.fc_out.bias"),
        },
    }
    return {
        "embed": {"tokens": get("transformer.wte.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dtype),
            "bias": get("transformer.ln_f.bias").astype(dtype)},
        "lm_head": np.ascontiguousarray(get("lm_head.weight").astype(dtype).T),
        "lm_head_bias": get("lm_head.bias").astype(dtype),
    }


def _load_phi(cfg: DecoderConfig, get, dtype) -> Params:
    """Phi layout: parallel residual with ONE shared input layernorm,
    separate biased q/k/v/dense projections, partial rotary, untied
    lm_head WITH bias."""
    L = cfg.num_layers
    p = "model.layers.{}."
    stack, stackT = _stack_helpers(get, L, dtype)
    layers = {
        "attn": {
            "wq": stackT(p + "self_attn.q_proj.weight"),
            "wk": stackT(p + "self_attn.k_proj.weight"),
            "wv": stackT(p + "self_attn.v_proj.weight"),
            "wo": stackT(p + "self_attn.dense.weight"),
            "bq": stack(p + "self_attn.q_proj.bias"),
            "bk": stack(p + "self_attn.k_proj.bias"),
            "bv": stack(p + "self_attn.v_proj.bias"),
            "bo": stack(p + "self_attn.dense.bias"),
        },
        "ln1": {"scale": stack(p + "input_layernorm.weight"),
                "bias": stack(p + "input_layernorm.bias")},
        "mlp": {
            "wi": stackT(p + "mlp.fc1.weight"), "bi": stack(p + "mlp.fc1.bias"),
            "wo": stackT(p + "mlp.fc2.weight"), "bo": stack(p + "mlp.fc2.bias"),
        },
    }
    return {
        "embed": {"tokens": get("model.embed_tokens.weight").astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": get("model.final_layernorm.weight").astype(dtype),
            "bias": get("model.final_layernorm.bias").astype(dtype)},
        "lm_head": np.ascontiguousarray(get("lm_head.weight").astype(dtype).T),
        "lm_head_bias": get("lm_head.bias").astype(dtype),
    }


def export_hf_checkpoint(cfg: DecoderConfig, params: Params,
                         out_dir: str) -> None:
    """Write the pytree back as an HF-layout safetensors checkpoint
    (single shard) + config.json — the reverse mapping, so models trained
    here load in transformers."""
    import jax
    # also key on the params tree: the moe.use_residual config knob folds
    # moe_residual into an internal copy of the model config, so the
    # caller's cfg may still say False while the tree carries the branch
    if cfg.moe_residual or (isinstance(params.get("layers"), dict)
                            and "residual" in params["layers"].get(
                                "moe", {})):
        raise ValueError(
            "export_hf_checkpoint: Residual-MoE (moe_residual) is a "
            "DeepSpeed training feature with no HF layout slot for the "
            "dense branch / coefficient — no transformers architecture "
            "can load it")
    if not cfg.causal or not cfg.prenorm:
        return _export_encoder(cfg, config_to_hf(cfg), params, out_dir)
    if _is_neox_layout(cfg):
        return _export_neox(cfg, params, out_dir)
    cfg_hf = config_to_hf(cfg)   # raises on unsupported layouts
    if cfg_hf["model_type"] in ("gpt2", "opt", "bloom", "falcon", "phi",
                                "gpt_bigcode", "gptj", "gpt_neo"):
        return _export_classic(cfg, cfg_hf, params, out_dir)

    os.makedirs(out_dir, exist_ok=True)
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params)
    if cfg_hf["model_type"] == "gemma":   # reverse the (1+w) fold
        host["final_norm"]["scale"] = host["final_norm"]["scale"] - 1.0
        for ln in ("ln1", "ln2"):
            host["layers"][ln]["scale"] = host["layers"][ln]["scale"] - 1.0
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host["embed"]["tokens"],
        "model.norm.weight": host["final_norm"]["scale"],
    }
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.ascontiguousarray(host["lm_head"].T)
    lyr = host["layers"]
    p = "model.layers.{}."
    for i in range(cfg.num_layers):
        a = lyr["attn"]
        out[p.format(i) + "self_attn.q_proj.weight"] = \
            np.ascontiguousarray(a["wq"][i].T)
        out[p.format(i) + "self_attn.k_proj.weight"] = \
            np.ascontiguousarray(a["wk"][i].T)
        out[p.format(i) + "self_attn.v_proj.weight"] = \
            np.ascontiguousarray(a["wv"][i].T)
        out[p.format(i) + "self_attn.o_proj.weight"] = \
            np.ascontiguousarray(a["wo"][i].T)
        if "bq" in a:
            out[p.format(i) + "self_attn.q_proj.bias"] = a["bq"][i]
            out[p.format(i) + "self_attn.k_proj.bias"] = a["bk"][i]
            out[p.format(i) + "self_attn.v_proj.bias"] = a["bv"][i]
            if cfg_hf.get("attention_bias"):
                # llama attention_bias layout (InternLM): o_proj bias
                # has a real slot
                out[p.format(i) + "self_attn.o_proj.bias"] = a["bo"][i]
            elif "bo" in a and np.abs(a["bo"][i]).max() > 1e-6:
                logger.warning(
                    "export_hf_checkpoint: layer %d o_proj bias is "
                    "nonzero but the qwen2 HF layout has no slot for it "
                    "— dropped (logits will differ)", i)
        out[p.format(i) + "input_layernorm.weight"] = lyr["ln1"]["scale"][i]
        out[p.format(i) + "post_attention_layernorm.weight"] = \
            lyr["ln2"]["scale"][i]
        if cfg.num_experts and cfg.shared_expert_size:   # qwen2_moe
            moe = lyr["moe"]
            out[p.format(i) + "mlp.gate.weight"] = \
                np.ascontiguousarray(moe["router"][i].T)
            for e in range(cfg.num_experts):
                ep = p.format(i) + f"mlp.experts.{e}."
                out[ep + "gate_proj.weight"] = \
                    np.ascontiguousarray(moe["wg"][i, e].T)
                out[ep + "up_proj.weight"] = \
                    np.ascontiguousarray(moe["wi"][i, e].T)
                out[ep + "down_proj.weight"] = \
                    np.ascontiguousarray(moe["wo"][i, e].T)
            sh = moe["shared"]
            sp = p.format(i) + "mlp.shared_expert."
            out[sp + "gate_proj.weight"] = np.ascontiguousarray(sh["wg"][i].T)
            out[sp + "up_proj.weight"] = np.ascontiguousarray(sh["wi"][i].T)
            out[sp + "down_proj.weight"] = np.ascontiguousarray(sh["wo"][i].T)
            out[p.format(i) + "mlp.shared_expert_gate.weight"] = \
                np.ascontiguousarray(sh["gate"][i].T)
        elif cfg.num_experts:
            moe = lyr["moe"]
            out[p.format(i) + "block_sparse_moe.gate.weight"] = \
                np.ascontiguousarray(moe["router"][i].T)
            for e in range(cfg.num_experts):
                ep = p.format(i) + f"block_sparse_moe.experts.{e}."
                out[ep + "w1.weight"] = np.ascontiguousarray(moe["wg"][i, e].T)
                out[ep + "w2.weight"] = np.ascontiguousarray(moe["wo"][i, e].T)
                out[ep + "w3.weight"] = np.ascontiguousarray(moe["wi"][i, e].T)
        else:
            m = lyr["mlp"]
            out[p.format(i) + "mlp.gate_proj.weight"] = \
                np.ascontiguousarray(m["wg"][i].T)
            out[p.format(i) + "mlp.up_proj.weight"] = \
                np.ascontiguousarray(m["wi"][i].T)
            out[p.format(i) + "mlp.down_proj.weight"] = \
                np.ascontiguousarray(m["wo"][i].T)
    _save_hf(out, cfg_hf, out_dir)


def _export_encoder(cfg: DecoderConfig, cfg_hf: Dict[str, Any],
                    params: Params, out_dir: str) -> None:
    """Inverse of ``_load_bert`` / ``_load_distilbert``: write a
    ``BertForMaskedLM`` / ``DistilBertForMaskedLM`` checkpoint
    transformers can reload."""
    import jax
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params)
    C = np.ascontiguousarray
    lyr = host["layers"]
    a, m = lyr["attn"], lyr["mlp"]
    out: Dict[str, np.ndarray] = {}
    bert = cfg_hf["model_type"] == "bert"
    pre = "bert." if bert else "distilbert."
    e = pre + "embeddings."
    out[e + "word_embeddings.weight"] = host["embed"]["tokens"]
    out[e + "position_embeddings.weight"] = host["embed"]["pos"]
    if bert:
        out[e + "token_type_embeddings.weight"] = \
            host["embed"]["token_type"]
    out[e + "LayerNorm.weight"] = host["embed_norm"]["scale"]
    out[e + "LayerNorm.bias"] = host["embed_norm"]["bias"]
    if bert:
        name = {
            "wq": "attention.self.query.weight",
            "bq": "attention.self.query.bias",
            "wk": "attention.self.key.weight",
            "bk": "attention.self.key.bias",
            "wv": "attention.self.value.weight",
            "bv": "attention.self.value.bias",
            "wo": "attention.output.dense.weight",
            "bo": "attention.output.dense.bias",
            "ln1": "attention.output.LayerNorm",
            "ln2": "output.LayerNorm",
            "wi": "intermediate.dense.weight",
            "bi": "intermediate.dense.bias",
            "wmo": "output.dense.weight",
            "bmo": "output.dense.bias",
        }
        p = pre + "encoder.layer.{}."
    else:
        name = {
            "wq": "attention.q_lin.weight", "bq": "attention.q_lin.bias",
            "wk": "attention.k_lin.weight", "bk": "attention.k_lin.bias",
            "wv": "attention.v_lin.weight", "bv": "attention.v_lin.bias",
            "wo": "attention.out_lin.weight",
            "bo": "attention.out_lin.bias",
            "ln1": "sa_layer_norm", "ln2": "output_layer_norm",
            "wi": "ffn.lin1.weight", "bi": "ffn.lin1.bias",
            "wmo": "ffn.lin2.weight", "bmo": "ffn.lin2.bias",
        }
        p = pre + "transformer.layer.{}."
    for i in range(cfg.num_layers):
        q = p.format(i)
        out[q + name["wq"]] = C(a["wq"][i].T)
        out[q + name["bq"]] = a["bq"][i]
        out[q + name["wk"]] = C(a["wk"][i].T)
        out[q + name["bk"]] = a["bk"][i]
        out[q + name["wv"]] = C(a["wv"][i].T)
        out[q + name["bv"]] = a["bv"][i]
        out[q + name["wo"]] = C(a["wo"][i].T)
        out[q + name["bo"]] = a["bo"][i]
        out[q + name["ln1"] + ".weight"] = lyr["ln1"]["scale"][i]
        out[q + name["ln1"] + ".bias"] = lyr["ln1"]["bias"][i]
        out[q + name["ln2"] + ".weight"] = lyr["ln2"]["scale"][i]
        out[q + name["ln2"] + ".bias"] = lyr["ln2"]["bias"][i]
        out[q + name["wi"]] = C(m["wi"][i].T)
        out[q + name["bi"]] = m["bi"][i]
        out[q + name["wmo"]] = C(m["wo"][i].T)
        out[q + name["bmo"]] = m["bo"][i]
    if "mlm_head" in host:
        mh = host["mlm_head"]
        if bert:
            t = "cls.predictions.transform."
            out[t + "dense.weight"] = C(mh["dense"].T)
            out[t + "dense.bias"] = mh["dense_bias"]
            out[t + "LayerNorm.weight"] = mh["ln"]["scale"]
            out[t + "LayerNorm.bias"] = mh["ln"]["bias"]
            out["cls.predictions.bias"] = mh["vocab_bias"]
            out["cls.predictions.decoder.weight"] = host["embed"]["tokens"]
            out["cls.predictions.decoder.bias"] = mh["vocab_bias"]
        else:
            out["vocab_transform.weight"] = C(mh["dense"].T)
            out["vocab_transform.bias"] = mh["dense_bias"]
            out["vocab_layer_norm.weight"] = mh["ln"]["scale"]
            out["vocab_layer_norm.bias"] = mh["ln"]["bias"]
            out["vocab_projector.weight"] = host["embed"]["tokens"]
            out["vocab_projector.bias"] = mh["vocab_bias"]
    _save_hf(out, cfg_hf, out_dir)


def _save_hf(out: Dict[str, np.ndarray], cfg_hf: Dict[str, Any],
             out_dir: str) -> None:
    """Shared export epilogue: safetensors + config.json."""
    from safetensors.numpy import save_file
    os.makedirs(out_dir, exist_ok=True)
    save_file(out, os.path.join(out_dir, "model.safetensors"),
              metadata={"format": "pt"})
    with open(os.path.join(out_dir, "config.json"), "w") as fh:
        json.dump(cfg_hf, fh, indent=2)


def _fuse_interleaved(a: Params, i: int, H: int, dh: int, D: int):
    """Re-pack separate q/k/v (+biases) into the NeoX/BLOOM head-
    interleaved fused layout: [H, 3, dh] on the out dim."""
    fused_w = np.stack(
        [a[k][i].T.reshape(H, dh, D) for k in ("wq", "wk", "wv")],
        axis=1).reshape(3 * H * dh, D)
    fused_b = np.stack(
        [a[k][i].reshape(H, dh) for k in ("bq", "bk", "bv")],
        axis=1).reshape(-1)
    return np.ascontiguousarray(fused_w), fused_b


def _export_classic(cfg: DecoderConfig, cfg_hf: Dict[str, Any],
                    params: Params, out_dir: str) -> None:
    """Reverse mappings for the classic families (GPT-2/GPT-BigCode/OPT/
    BLOOM/Falcon/Phi) — each the inverse of its ``_load_*`` including the fused-qkv
    re-pack and OPT's +2 position rows."""
    import jax
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params)
    mt = cfg_hf["model_type"]
    L, H, KV, dh, D = (cfg.num_layers, cfg.num_heads, cfg.kv_heads,
                       cfg.head_dim, cfg.hidden_size)
    lyr = host["layers"]
    a, m = lyr["attn"], lyr["mlp"]
    out: Dict[str, np.ndarray] = {}
    C = np.ascontiguousarray

    def put_ln(dst, src, i):
        out[dst + ".weight"] = src["scale"][i]
        out[dst + ".bias"] = src["bias"][i]

    if mt == "gpt2":
        out["transformer.wte.weight"] = host["embed"]["tokens"]
        out["transformer.wpe.weight"] = host["embed"]["pos"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            out[p + "attn.c_attn.weight"] = C(np.concatenate(
                [a["wq"][i], a["wk"][i], a["wv"][i]], axis=1))
            out[p + "attn.c_attn.bias"] = np.concatenate(
                [a["bq"][i], a["bk"][i], a["bv"][i]])
            out[p + "attn.c_proj.weight"] = a["wo"][i]
            out[p + "attn.c_proj.bias"] = a["bo"][i]
            out[p + "mlp.c_fc.weight"] = m["wi"][i]
            out[p + "mlp.c_fc.bias"] = m["bi"][i]
            out[p + "mlp.c_proj.weight"] = m["wo"][i]
            out[p + "mlp.c_proj.bias"] = m["bo"][i]
            put_ln(p + "ln_1", lyr["ln1"], i)
            put_ln(p + "ln_2", lyr["ln2"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "gpt_bigcode":
        out["transformer.wte.weight"] = host["embed"]["tokens"]
        out["transformer.wpe.weight"] = host["embed"]["pos"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            # nn.Linear [out, in]: concat q|k|v on OUT then transpose back
            out[p + "attn.c_attn.weight"] = C(np.concatenate(
                [a["wq"][i], a["wk"][i], a["wv"][i]], axis=1).T)
            out[p + "attn.c_attn.bias"] = np.concatenate(
                [a["bq"][i], a["bk"][i], a["bv"][i]])
            out[p + "attn.c_proj.weight"] = C(a["wo"][i].T)
            out[p + "attn.c_proj.bias"] = a["bo"][i]
            out[p + "mlp.c_fc.weight"] = C(m["wi"][i].T)
            out[p + "mlp.c_fc.bias"] = m["bi"][i]
            out[p + "mlp.c_proj.weight"] = C(m["wo"][i].T)
            out[p + "mlp.c_proj.bias"] = m["bo"][i]
            put_ln(p + "ln_1", lyr["ln1"], i)
            put_ln(p + "ln_2", lyr["ln2"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "gpt_neo":
        import math as _math
        inv = np.float32(1.0 / _math.sqrt(cfg.head_dim))
        out["transformer.wte.weight"] = host["embed"]["tokens"]
        out["transformer.wpe.weight"] = host["embed"]["pos"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            # un-fold the sqrt(dh) loaded into wq (see _load_gptneo)
            out[p + "attn.attention.q_proj.weight"] = C((a["wq"][i] * inv).T)
            out[p + "attn.attention.k_proj.weight"] = C(a["wk"][i].T)
            out[p + "attn.attention.v_proj.weight"] = C(a["wv"][i].T)
            out[p + "attn.attention.out_proj.weight"] = C(a["wo"][i].T)
            out[p + "attn.attention.out_proj.bias"] = a["bo"][i]
            out[p + "mlp.c_fc.weight"] = C(m["wi"][i].T)
            out[p + "mlp.c_fc.bias"] = m["bi"][i]
            out[p + "mlp.c_proj.weight"] = C(m["wo"][i].T)
            out[p + "mlp.c_proj.bias"] = m["bo"][i]
            put_ln(p + "ln_1", lyr["ln1"], i)
            put_ln(p + "ln_2", lyr["ln2"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "opt":
        out["model.decoder.embed_tokens.weight"] = host["embed"]["tokens"]
        # rows 0/1 are the padding-position slots HF indexes below the
        # +2 offset; they are never read for dense (full-mask) sequences
        out["model.decoder.embed_positions.weight"] = np.concatenate(
            [np.zeros((2, D), np.float32), host["embed"]["pos"]])
        out["model.decoder.final_layer_norm.weight"] = \
            host["final_norm"]["scale"]
        out["model.decoder.final_layer_norm.bias"] = \
            host["final_norm"]["bias"]
        for i in range(L):
            p = f"model.decoder.layers.{i}."
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"),
                                 ("v", "v_proj"), ("o", "out_proj")):
                key = "wo" if ours == "o" else "w" + ours
                bkey = "bo" if ours == "o" else "b" + ours
                out[p + f"self_attn.{theirs}.weight"] = C(a[key][i].T)
                out[p + f"self_attn.{theirs}.bias"] = a[bkey][i]
            out[p + "fc1.weight"] = C(m["wi"][i].T)
            out[p + "fc1.bias"] = m["bi"][i]
            out[p + "fc2.weight"] = C(m["wo"][i].T)
            out[p + "fc2.bias"] = m["bo"][i]
            put_ln(p + "self_attn_layer_norm", lyr["ln1"], i)
            put_ln(p + "final_layer_norm", lyr["ln2"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "bloom":
        out["transformer.word_embeddings.weight"] = host["embed"]["tokens"]
        out["transformer.word_embeddings_layernorm.weight"] = \
            host["embed_norm"]["scale"]
        out["transformer.word_embeddings_layernorm.bias"] = \
            host["embed_norm"]["bias"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            fused_w, fused_b = _fuse_interleaved(a, i, H, dh, D)
            out[p + "self_attention.query_key_value.weight"] = fused_w
            out[p + "self_attention.query_key_value.bias"] = fused_b
            out[p + "self_attention.dense.weight"] = C(a["wo"][i].T)
            out[p + "self_attention.dense.bias"] = a["bo"][i]
            out[p + "mlp.dense_h_to_4h.weight"] = C(m["wi"][i].T)
            out[p + "mlp.dense_h_to_4h.bias"] = m["bi"][i]
            out[p + "mlp.dense_4h_to_h.weight"] = C(m["wo"][i].T)
            out[p + "mlp.dense_4h_to_h.bias"] = m["bo"][i]
            put_ln(p + "input_layernorm", lyr["ln1"], i)
            put_ln(p + "post_attention_layernorm", lyr["ln2"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "falcon":
        new_arch = cfg_hf["new_decoder_architecture"]
        out["transformer.word_embeddings.weight"] = host["embed"]["tokens"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            q = a["wq"][i].T.reshape(H, dh, D)
            k = a["wk"][i].T.reshape(KV, dh, D)
            v = a["wv"][i].T.reshape(KV, dh, D)
            if new_arch:
                g = H // KV
                fused = np.concatenate(
                    [q.reshape(KV, g, dh, D), k[:, None], v[:, None]],
                    axis=1).reshape(KV * (g + 2) * dh, D)
            else:   # old MQA: H query heads then k then v
                fused = np.concatenate([q, k, v]).reshape((H + 2) * dh, D)
            out[p + "self_attention.query_key_value.weight"] = C(fused)
            out[p + "self_attention.dense.weight"] = C(a["wo"][i].T)
            out[p + "mlp.dense_h_to_4h.weight"] = C(m["wi"][i].T)
            out[p + "mlp.dense_4h_to_h.weight"] = C(m["wo"][i].T)
            if cfg.use_bias:   # "bias": true — inverse of split_fused
                qb = a["bq"][i].reshape(H, dh)
                kb = a["bk"][i].reshape(KV, dh)
                vb = a["bv"][i].reshape(KV, dh)
                if new_arch:
                    fb = np.concatenate(
                        [qb.reshape(KV, H // KV, dh), kb[:, None],
                         vb[:, None]], axis=1).reshape(-1)
                else:
                    fb = np.concatenate([qb, kb, vb]).reshape(-1)
                out[p + "self_attention.query_key_value.bias"] = fb
                out[p + "self_attention.dense.bias"] = a["bo"][i]
                out[p + "mlp.dense_h_to_4h.bias"] = m["bi"][i]
                out[p + "mlp.dense_4h_to_h.bias"] = m["bo"][i]
            if cfg.parallel_block_norms == 2:
                put_ln(p + "ln_attn", lyr["ln1"], i)
                put_ln(p + "ln_mlp", lyr["ln2"], i)
            else:
                put_ln(p + "input_layernorm", lyr["ln1"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
    elif mt == "gptj":
        inv = _gptj_rope_perm(cfg, inverse=True)
        out["transformer.wte.weight"] = host["embed"]["tokens"]
        out["transformer.ln_f.weight"] = host["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"transformer.h.{i}."
            out[p + "attn.q_proj.weight"] = C(a["wq"][i][:, inv].T)
            out[p + "attn.k_proj.weight"] = C(a["wk"][i][:, inv].T)
            out[p + "attn.v_proj.weight"] = C(a["wv"][i].T)
            out[p + "attn.out_proj.weight"] = C(a["wo"][i].T)
            out[p + "mlp.fc_in.weight"] = C(m["wi"][i].T)
            out[p + "mlp.fc_in.bias"] = m["bi"][i]
            out[p + "mlp.fc_out.weight"] = C(m["wo"][i].T)
            out[p + "mlp.fc_out.bias"] = m["bo"][i]
            put_ln(p + "ln_1", lyr["ln1"], i)
        out["lm_head.weight"] = C(host["lm_head"].T)
        out["lm_head.bias"] = host.get(
            "lm_head_bias", np.zeros(cfg.vocab_size, np.float32))
    else:   # phi
        out["model.embed_tokens.weight"] = host["embed"]["tokens"]
        out["model.final_layernorm.weight"] = host["final_norm"]["scale"]
        out["model.final_layernorm.bias"] = host["final_norm"]["bias"]
        for i in range(L):
            p = f"model.layers.{i}."
            for ours, theirs in (("q", "q_proj"), ("k", "k_proj"),
                                 ("v", "v_proj"), ("o", "dense")):
                key = "wo" if ours == "o" else "w" + ours
                bkey = "bo" if ours == "o" else "b" + ours
                out[p + f"self_attn.{theirs}.weight"] = C(a[key][i].T)
                out[p + f"self_attn.{theirs}.bias"] = a[bkey][i]
            out[p + "mlp.fc1.weight"] = C(m["wi"][i].T)
            out[p + "mlp.fc1.bias"] = m["bi"][i]
            out[p + "mlp.fc2.weight"] = C(m["wo"][i].T)
            out[p + "mlp.fc2.bias"] = m["bo"][i]
            put_ln(p + "input_layernorm", lyr["ln1"], i)
        if not cfg.tie_embeddings:
            out["lm_head.weight"] = C(host["lm_head"].T)
            out["lm_head.bias"] = host.get(
                "lm_head_bias", np.zeros(cfg.vocab_size, np.float32))
    _save_hf(out, cfg_hf, out_dir)


def _export_neox(cfg: DecoderConfig, params: Params, out_dir: str) -> None:
    """Reverse of _load_neox (re-interleaves the fused qkv)."""
    import jax
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params)
    H, dh, D = cfg.num_heads, cfg.head_dim, cfg.hidden_size
    out: Dict[str, np.ndarray] = {
        "gpt_neox.embed_in.weight": host["embed"]["tokens"],
        "gpt_neox.final_layer_norm.weight": host["final_norm"]["scale"],
        "gpt_neox.final_layer_norm.bias": host["final_norm"]["bias"],
    }
    if not cfg.tie_embeddings:
        out["embed_out.weight"] = np.ascontiguousarray(host["lm_head"].T)
    lyr = host["layers"]
    p = "gpt_neox.layers.{}."
    for i in range(cfg.num_layers):
        a = lyr["attn"]
        fused_w, fused_b = _fuse_interleaved(a, i, H, dh, D)
        pi = p.format(i)
        out[pi + "attention.query_key_value.weight"] = fused_w
        out[pi + "attention.query_key_value.bias"] = fused_b
        out[pi + "attention.dense.weight"] = \
            np.ascontiguousarray(a["wo"][i].T)
        out[pi + "attention.dense.bias"] = a["bo"][i]
        out[pi + "input_layernorm.weight"] = lyr["ln1"]["scale"][i]
        out[pi + "input_layernorm.bias"] = lyr["ln1"]["bias"][i]
        out[pi + "post_attention_layernorm.weight"] = lyr["ln2"]["scale"][i]
        out[pi + "post_attention_layernorm.bias"] = lyr["ln2"]["bias"][i]
        m = lyr["mlp"]
        out[pi + "mlp.dense_h_to_4h.weight"] = \
            np.ascontiguousarray(m["wi"][i].T)
        out[pi + "mlp.dense_h_to_4h.bias"] = m["bi"][i]
        out[pi + "mlp.dense_4h_to_h.weight"] = \
            np.ascontiguousarray(m["wo"][i].T)
        out[pi + "mlp.dense_4h_to_h.bias"] = m["bo"][i]
    _save_hf(out, config_to_hf(cfg), out_dir)
