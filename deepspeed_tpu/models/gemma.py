"""Gemma family presets (reference: AutoTP supported-model list,
module_inject/auto_tp.py — Gemma's distinctives are a decoupled head_dim,
GeGLU MLP, sqrt(d)-scaled embeddings, RMSNorm with a (1+w) weight
convention (folded into ``scale`` at HF load time, hf_loader.py), and —
Gemma2 — final-logit softcapping)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def gemma_config(size: str = "2b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=1, head_dim_override=32,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=256),
        # gemma-2b: MQA, head_dim 256 (8*256=2048 == hidden by luck),
        # GeGLU 16384
        "2b": dict(hidden_size=2048, num_layers=18, num_heads=8,
                   num_kv_heads=1, head_dim_override=256,
                   intermediate_size=16384),
        # gemma-7b: 16 heads * 256 = 4096 != 3072 hidden — the decoupled
        # q_dim path
        "7b": dict(hidden_size=3072, num_layers=28, num_heads=16,
                   num_kv_heads=16, head_dim_override=256,
                   intermediate_size=24576),
        # NOTE: Gemma2 is NOT fully modeled (it adds attention-score
        # softcapping, interleaved sliding-window layers, and pre/post-FFN
        # norms); only its final-logit softcap exists here as the
        # ``logit_softcap`` knob.
    }
    base = dict(vocab_size=256000, max_seq_len=8192, norm="rmsnorm",
                activation="gelu_glu", pos_emb="rope", rope_theta=10000.0,
                use_bias=False, tie_embeddings=True, norm_eps=1e-6,
                scale_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
