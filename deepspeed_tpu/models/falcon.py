"""Falcon family presets (reference: inference/v2/model_implementations/
falcon/ — parallel-residual decoder with multi-query attention)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def falcon_config(size: str = "7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=1, intermediate_size=256, vocab_size=512,
                     max_seq_len=256),
        # falcon-7b: MQA (1 kv head), parallel attn+mlp w/ ONE shared
        # input_layernorm, 4*d FFN
        "7b": dict(hidden_size=4544, num_layers=32, num_heads=71,
                   num_kv_heads=1, intermediate_size=18176),
        # falcon-40b new_decoder_architecture: separate ln_attn / ln_mlp
        "40b": dict(hidden_size=8192, num_layers=60, num_heads=128,
                    num_kv_heads=8, intermediate_size=32768,
                    parallel_block_norms=2),
    }
    base = dict(vocab_size=65024, max_seq_len=2048, norm="layernorm",
                activation="gelu_exact", pos_emb="rope", rope_theta=10000.0,
                use_bias=False, norm_bias=True,   # LNs keep bias; linears do not
                tie_embeddings=True, parallel_block=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
