"""GPT-NeoX family presets (reference: the megatron-family policies in
module_inject/containers — parallel residual + partial rotary)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def gptneox_config(size: str = "20b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=256),
        # pythia family shares the architecture
        "410m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     intermediate_size=4096),
        "6.9b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     intermediate_size=16384),
        "20b": dict(hidden_size=6144, num_layers=44, num_heads=64,
                    intermediate_size=24576),
    }
    base = dict(vocab_size=50432, max_seq_len=2048, norm="layernorm",
                activation="gelu_exact", pos_emb="rope", rope_theta=10000.0,
                rotary_pct=0.25, use_bias=True, tie_embeddings=False,
                # NeoX parallel residual uses SEPARATE input/post_attention
                # norms on x (HF use_parallel_residual)
                parallel_block=True, parallel_block_norms=2)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
