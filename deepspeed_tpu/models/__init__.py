from deepspeed_tpu.models.transformer import (
    DecoderConfig,
    cross_entropy_loss,
    dot_product_attention,
    forward,
    init_params,
    partition_specs,
)
from deepspeed_tpu.models.gpt import gpt2_config
from deepspeed_tpu.models.llama import llama3_config
from deepspeed_tpu.models.mixtral import mixtral_config
from deepspeed_tpu.models.mistral import mistral_config
from deepspeed_tpu.models.qwen import qwen_config
from deepspeed_tpu.models.qwen2 import qwen2_config
from deepspeed_tpu.models.falcon import falcon_config
from deepspeed_tpu.models.gptneox import gptneox_config
from deepspeed_tpu.models.phi import phi_config
from deepspeed_tpu.models.opt import opt_config
from deepspeed_tpu.models.gemma import gemma_config
from deepspeed_tpu.models.bloom import bloom_config
from deepspeed_tpu.models.gpt_bigcode import gpt_bigcode_config
from deepspeed_tpu.models.qwen2_moe import qwen2_moe_config
from deepspeed_tpu.models.gptj import gptj_config
from deepspeed_tpu.models.bert import bert_config, distilbert_config
from deepspeed_tpu.models.gptneo import gptneo_config
from deepspeed_tpu.models.internlm import internlm_config
from deepspeed_tpu.models.megatron import load_megatron_checkpoint

__all__ = [
    "DecoderConfig", "init_params", "forward", "partition_specs",
    "cross_entropy_loss", "dot_product_attention",
    "gpt2_config", "llama3_config", "mixtral_config",
    "mistral_config", "qwen_config", "qwen2_config", "falcon_config",
    "gptneox_config",
    "gpt_bigcode_config", "qwen2_moe_config", "gptj_config",
    "phi_config", "opt_config", "gemma_config", "bloom_config",
    "bert_config", "distilbert_config", "gptneo_config",
    "internlm_config", "load_megatron_checkpoint",
]
