"""BERT / DistilBERT encoder family presets (reference: the encoder
injection policies in module_inject/containers/bert.py and
distil_bert.py — DeepSpeed's v1 inference covered encoders, and the
1-bit optimizer benchmarks in BASELINE.md are BERT pretraining runs).

Encoders are the same scan core as the decoders with three knobs
flipped: ``causal=False`` (bidirectional attention), ``prenorm=False``
(post-LN residual order — h = LN(x + sublayer(x)) — with no final
norm), and ``mlm_head=True`` (the HF ``cls.predictions`` transform +
tied decode + vocab bias). BERT adds segment embeddings via
``type_vocab_size``; DistilBERT drops them.
"""

from deepspeed_tpu.models.transformer import DecoderConfig


def bert_config(size: str = "base", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "base": dict(hidden_size=768, num_layers=12, num_heads=12,
                     intermediate_size=3072),
        "large": dict(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096),
    }
    base = dict(vocab_size=30522, max_seq_len=512, norm="layernorm",
                activation="gelu_exact", pos_emb="learned",
                norm_eps=1e-12, use_bias=True, tie_embeddings=True,
                causal=False, prenorm=False, embed_norm=True,
                type_vocab_size=2, mlm_head=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)


def distilbert_config(size: str = "base", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "base": dict(hidden_size=768, num_layers=6, num_heads=12,
                     intermediate_size=3072),
    }
    base = dict(vocab_size=30522, max_seq_len=512, norm="layernorm",
                activation="gelu_exact", pos_emb="learned",
                norm_eps=1e-12, use_bias=True, tie_embeddings=True,
                causal=False, prenorm=False, embed_norm=True,
                type_vocab_size=0, mlm_head=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
