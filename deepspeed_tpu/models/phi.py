"""Phi family presets (reference: inference/v2/model_implementations/phi/
— parallel residual with one shared input layernorm, partial rotary)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def phi_config(size: str = "2", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=256, rotary_pct=0.5),
        # phi-2 (2.7B): rotary_dim 32 of head_dim 80 -> pct 0.4
        "2": dict(hidden_size=2560, num_layers=32, num_heads=32,
                  intermediate_size=10240, rotary_pct=0.4,
                  vocab_size=51200),
    }
    base = dict(vocab_size=51200, max_seq_len=2048, norm="layernorm",
                activation="gelu", pos_emb="rope", rope_theta=10000.0,
                use_bias=True, tie_embeddings=False, lm_head_bias=True,
                parallel_block=True, parallel_block_norms=1)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
