"""GPT-J family presets (reference: the GPT-J injection policy in
module_inject/containers/gptj.py).

Architecture: parallel residual with ONE shared input layernorm, partial
rotary (rotary_dim of each 256-dim head), tanh-GELU MLP, bias-less
attention projections but biased fc_in/fc_out, untied lm_head WITH bias.
GPT-J applies RoPE with the INTERLEAVED pairing (rotate_every_two); the
HF loader folds that into a load-time permutation of the q/k weight
columns so the in-repo rotate-half kernels apply unchanged
(models/hf_loader.py:_gptj_rope_perm).
"""

from deepspeed_tpu.models.transformer import DecoderConfig


def gptj_config(size: str = "6b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=256, rotary_pct=0.5),
        "6b": dict(hidden_size=4096, num_layers=28, num_heads=16,
                   intermediate_size=16384,
                   # rotary_dim 64 of head_dim 256
                   rotary_pct=0.25),
    }
    base = dict(vocab_size=50400, max_seq_len=2048, norm="layernorm",
                activation="gelu", pos_emb="rope", rope_theta=10000.0,
                use_bias=True, attn_bias=False, tie_embeddings=False,
                lm_head_bias=True, parallel_block=True,
                parallel_block_norms=1)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
