"""Functional decoder-only transformer core.

This is the flagship model family of deepspeed_tpu, playing the role the
reference's injected/containers model zoo plays for DeepSpeed
(module_inject/containers/*, inference/v2/model_implementations/*) — but
designed TPU-first:

- parameters are a plain pytree; per-layer weights are **stacked** on a
  leading ``layers`` axis and the block is applied with ``lax.scan`` →
  constant-size HLO regardless of depth, fast compiles, and natural
  pipeline-stage splitting;
- every parameter has a ``PartitionSpec`` produced by
  :func:`partition_specs`, composing tensor-parallel sharding (over the
  ``model`` axis — the AutoTP analogue of module_inject/auto_tp.py) with
  ZeRO-3/FSDP sharding (over ``data``+``expert``);
- attention is pluggable: local (reference jnp), Ulysses all-to-all
  (deepspeed/sequence/layer.py analogue), or ring attention — selected by
  the engine from the config;
- supports GPT-2 (learned pos, LayerNorm, gelu MLP, biases) and Llama
  (RoPE, RMSNorm, SwiGLU, no biases, GQA) families from one code path.
"""

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax import lax
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None     # GQA; None => num_heads
    intermediate_size: Optional[int] = None  # None => 4*hidden (gelu) / llama default
    max_seq_len: int = 1024
    norm: str = "layernorm"                # 'layernorm' | 'rmsnorm'
    #: 'gelu' (tanh approx — HF gelu_new/gelu_pytorch_tanh) | 'gelu_exact'
    #: (erf — HF "gelu": Falcon, NeoX) | 'relu' | 'silu_glu' (Llama
    #: SwiGLU) | 'gelu_glu' (Gemma GeGLU)
    activation: str = "gelu"
    pos_emb: str = "learned"               # 'learned' | 'rope' | 'alibi'
    rope_theta: float = 10000.0
    use_bias: bool = True
    tie_embeddings: bool = True
    norm_eps: float = 1e-5
    #: parallel residual (GPT-J/NeoX/Falcon/Phi): h = x + attn(...) +
    #: mlp(...)
    parallel_block: bool = False
    #: 1 = ONE shared pre-norm feeds both branches (GPT-J / Falcon-7B /
    #: Phi); 2 = separate input/post_attention norms on x (GPT-NeoX /
    #: Pythia / Falcon-40B new_decoder_architecture)
    parallel_block_norms: int = 1
    #: LayerNorm bias independent of linear biases (Falcon: bias-less
    #: linears but LNs WITH bias). None → follow use_bias.
    norm_bias: Optional[bool] = None
    #: attention-projection biases independent of the MLP/LN biases
    #: (GPT-J: biased fc_in/fc_out/LN but bias-less q/k/v/out_proj).
    #: None → follow use_bias.
    attn_bias: Optional[bool] = None
    #: partial rotary (GPT-NeoX rotary_pct / GPT-J rotary_dim): RoPE on
    #: the first rotary_pct of each head's dims, pass-through on the rest
    rotary_pct: float = 1.0
    #: out-projection bias decoupled from the q/k/v biases (GPT-Neo:
    #: bias-less q/k/v but biased out_proj). None → follow qkv_bias.
    attn_out_bias: Optional[bool] = None
    #: per-layer attention windows tiled over depth (GPT-Neo
    #: attention_types: (0, 256) = alternating global/local-256; 0 means
    #: full causal). Routes to the masked attention path — the static
    #: block-skip kernels keep using ``sliding_window``.
    layer_window_pattern: Optional[Tuple[int, ...]] = None
    # MoE (used by mixtral preset; dense when num_experts == 0)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    #: normalize the selected top-k routing probs (Mixtral True;
    #: Qwen2-MoE ships norm_topk_prob False — raw softmax values)
    norm_topk_prob: bool = True
    #: Qwen2-MoE/DeepSeek shared expert: a dense MLP of this
    #: intermediate size runs on EVERY token alongside the routed
    #: experts (0 = none)
    shared_expert_size: int = 0
    #: sigmoid(x @ gate) scaling on the shared expert output (Qwen2-MoE)
    shared_expert_gate: bool = False
    #: DeepSpeed Residual-MoE (PR-MoE's "R"; reference moe/layer.py
    #: use_residual): every MoE layer also runs a DENSE MLP and the two
    #: outputs are mixed by a learned per-token 2-way softmax coefficient
    #: — out = moe·c₀ + mlp·c₁. Unlike the shared expert (additive,
    #: Qwen2-MoE) the mixture is convex and learned per token.
    moe_residual: bool = False
    # initializer
    init_std: float = 0.02
    #: decoupled head dim (Gemma head_dim=256 with H*Dh != hidden);
    #: None → hidden_size // num_heads
    head_dim_override: Optional[int] = None
    #: Gemma2 final_logit_softcapping: logits = c*tanh(logits/c); 0 = off
    logit_softcap: float = 0.0
    #: Gemma: scale token embeddings by sqrt(hidden) after lookup
    scale_embeddings: bool = False
    #: BLOOM word_embeddings_layernorm: a norm between embed and block 0
    embed_norm: bool = False
    #: causal sliding-window attention (Mistral SWA): each query sees at
    #: most the last `sliding_window` keys; None = full causal
    sliding_window: Optional[int] = None
    #: untied lm_head carries a bias vector (HF Phi's ``lm_head.bias``)
    lm_head_bias: bool = False
    #: model-health stat taps (telemetry/health.py): the scan body emits
    #: a per-layer stats dict (aux_loss, activation RMS/absmax, MoE
    #: expert load + routing entropy) instead of the scalar aux, and
    #: ``forward_hidden`` returns it stacked [L] as a third output.
    #: Trace-time static — only the training loss_fn ever sets it (on a
    #: replaced config instance), so inference/pipeline callers keep the
    #: 2-tuple contract.
    health_taps: bool = False
    #: False → bidirectional (encoder: BERT/DistilBERT). The reference's
    #: encoder containers are module_inject/containers/bert.py and
    #: distil_bert.py; here encoders are the same scan core with the
    #: causal mask dropped.
    causal: bool = True
    #: False → post-LN residuals (original-transformer/BERT order:
    #: h = LN(x + sublayer(x))); True → pre-LN (GPT-2/Llama). Post-LN
    #: models have no final norm — the last block's output LN is it.
    prenorm: bool = True
    #: >0 → segment/token-type embeddings (BERT); adds an
    #: ``embed["token_type"]`` leaf added before the embed norm
    type_vocab_size: int = 0
    #: BERT masked-LM head: transform dense+gelu+LN before the tied
    #: decode, plus a vocab bias (HF cls.predictions.*)
    mlm_head: bool = False
    #: FPDT sequence-chunked dense MLP (reference fpdt_layer.py:1056,
    #: set from activation_checkpointing.ffn_chunk): >0 runs the MLP in
    #: ffn_chunk-token tiles under remat so its [T, ffn] activations
    #: never materialize — the 128K+ single-chip memory knob. Applies
    #: to the dense MLP path only (MoE layers dispatch per token
    #: already); inference paths ignore it (decode is 1 token).
    ffn_chunk: int = 0

    def __post_init__(self):
        if self.mlm_head and not self.tie_embeddings:
            # the MLM decode is defined as tied-embedding + vocab bias
            # (HF cls.predictions.decoder); an untied lm_head would make
            # lm_logits and the chunked-CE loss decode different heads
            raise ValueError("mlm_head requires tie_embeddings=True")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def has_final_norm(self) -> bool:
        return self.prenorm

    def window_per_layer(self):
        """``layer_window_pattern`` tiled over depth as a plain list
        (0 = full causal) — the ONE home for the expansion, shared by
        the forward scan and the HF export."""
        pat = self.layer_window_pattern
        return [pat[i % len(pat)] for i in range(self.num_layers)]

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_heads

    @property
    def q_dim(self) -> int:
        """Total query width H*Dh (== hidden_size unless head_dim is
        decoupled, Gemma-style)."""
        return self.num_heads * self.head_dim

    @property
    def is_glu(self) -> bool:
        return self.activation.endswith("_glu")

    @property
    def qkv_bias(self) -> bool:
        return self.use_bias if self.attn_bias is None else self.attn_bias

    @property
    def out_bias(self) -> bool:
        return self.qkv_bias if self.attn_out_bias is None \
            else self.attn_out_bias

    @property
    def ln_bias(self) -> bool:
        if self.norm != "layernorm":
            return False
        return self.use_bias if self.norm_bias is None else self.norm_bias

    @property
    def has_ln2(self) -> bool:
        return (not self.parallel_block) or self.parallel_block_norms == 2

    @property
    def rope_dim(self) -> int:
        """Dims per head that get RoPE (even; rotary_pct of head_dim)."""
        r = int(self.head_dim * self.rotary_pct)
        return r - (r % 2)

    @property
    def ffn_size(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        if self.is_glu:
            return int(8 * self.hidden_size / 3 // 128 * 128) or 4 * self.hidden_size
        return 4 * self.hidden_size

    def num_params(self) -> int:
        """Approximate parameter count (used for MFU accounting)."""
        d, v, l = self.hidden_size, self.vocab_size, self.num_layers
        h = self.ffn_size
        attn = d * self.q_dim + 2 * d * self.kv_heads * self.head_dim \
            + self.q_dim * d
        if self.is_glu:
            mlp = 3 * d * h
        else:
            mlp = 2 * d * h
        if self.num_experts:
            dense_mlp = mlp
            mlp = mlp * self.num_experts + d * self.num_experts  # + router
            if self.shared_expert_size:
                mlp += 3 * d * self.shared_expert_size \
                    + (d if self.shared_expert_gate else 0)
            if self.moe_residual:
                mlp += dense_mlp + 2 * d + 2   # dense MLP + coefficient
        per_layer = attn + mlp + 2 * d
        emb = v * d + (self.max_seq_len * d if self.pos_emb == "learned"
                       else 0) + self.type_vocab_size * d
        head = 0 if self.tie_embeddings else v * d + (v if self.lm_head_bias
                                                      else 0)
        if self.mlm_head:
            head += d * d + 3 * d + v
        return l * per_layer + emb + head + d

    def num_active_params(self) -> int:
        """Parameters touched per token (== num_params for dense models;
        MoE counts experts_per_tok of the num_experts expert MLPs) — the
        correct basis for MoE MFU/FLOPs accounting."""
        if not self.num_experts:
            return self.num_params()
        d, h = self.hidden_size, self.ffn_size
        expert = (3 if self.is_glu else 2) * d * h
        inactive = (self.num_experts - self.num_experts_per_tok) * expert
        return self.num_params() - self.num_layers * inactive


# ---------------------------------------------------------------------------
# Normalization (Pallas-accelerated versions live in deepspeed_tpu/ops)
# ---------------------------------------------------------------------------

def _norm(cfg: DecoderConfig, params: Params, x: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * lax.rsqrt(var + cfg.norm_eps) * params["scale"]
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) * lax.rsqrt(var + cfg.norm_eps) * params["scale"]
        if "bias" in params:
            out = out + params["bias"]
    return out.astype(x.dtype)


def _norm_params(cfg: DecoderConfig, shape_prefix=()) -> Params:
    p = {"scale": jnp.ones(shape_prefix + (cfg.hidden_size,), jnp.float32)}
    if cfg.ln_bias:
        p["bias"] = jnp.zeros(shape_prefix + (cfg.hidden_size,), jnp.float32)
    return p


def embed_tokens(cfg: DecoderConfig, em: Params, tokens: jax.Array,
                 positions: jax.Array,
                 embed_norm: Optional[Params] = None,
                 token_type_ids: Optional[jax.Array] = None) -> jax.Array:
    """The ONE home for token-embedding semantics (Gemma sqrt(d) scaling,
    learned positions, BLOOM word_embeddings_layernorm, BERT token-type
    segments) — shared by forward_hidden, forward_with_cache, the
    pipeline stages, and the ragged inference engine so a new
    embed-affecting knob can't silently diverge between paths."""
    x = em["tokens"][tokens]
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.hidden_size)
             ).astype(x.dtype)
    if cfg.pos_emb == "learned":
        x = x + em["pos"][positions]
    if cfg.type_vocab_size:
        if token_type_ids is None:
            token_type_ids = jnp.zeros(tokens.shape, jnp.int32)
        x = x + em["token_type"][token_type_ids]
    if cfg.embed_norm:
        x = _norm(cfg, embed_norm, x)
    return x


# ---------------------------------------------------------------------------
# Rotary embeddings
# ---------------------------------------------------------------------------

def rope_table(cfg: DecoderConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """positions: [B, T] int32 → (sin, cos) each [B, T, rope_dim//2]
    (rope_dim == head_dim unless rotary_pct < 1 — GPT-NeoX partial
    rotary)."""
    half = cfg.rope_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """x: [B, T, H, Dh]; rotate-half convention (Llama). When the table
    covers fewer dims than Dh (partial rotary), the tail passes through
    unrotated (GPT-NeoX/GPT-J convention)."""
    rot = 2 * sin.shape[-1]
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x1, x2 = jnp.split(x_rot, 2, axis=-1)
    sin = sin[:, :, None, :]
    cos = cos[:, :, None, :]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                              axis=-1)
    if x_pass.shape[-1]:
        rotated = jnp.concatenate([rotated, x_pass], axis=-1)
    return rotated.astype(x.dtype)


# ---------------------------------------------------------------------------
# Attention (reference local path; Ulysses/ring wrap this fn)
# ---------------------------------------------------------------------------

def alibi_slopes(num_heads: int) -> jax.Array:
    """Per-head ALiBi slopes (Press et al.; BLOOM build_alibi_tensor
    convention): geometric sequence 2^(-8/n · i), with the closest
    power-of-two interpolation for non-power-of-2 head counts."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        s = pow2_slopes(num_heads)
    else:
        base = 1 << int(math.floor(math.log2(num_heads)))
        s = pow2_slopes(base)
        extra = pow2_slopes(2 * base)[0::2][:num_heads - base]
        s = s + extra
    return jnp.asarray(s, jnp.float32)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool = True,
                          q_offset: int = 0,
                          alibi: Optional[jax.Array] = None,
                          window: Optional[int] = None,
                          key_mask: Optional[jax.Array] = None) -> jax.Array:
    """q: [B, Tq, H, Dh], k/v: [B, Tk, KvH, Dh] → [B, Tq, H, Dh].

    GQA handled by head repetition at the einsum level (no materialized
    repeat). fp32 softmax for numerics; XLA fuses the whole block onto MXU.
    ``alibi``: per-head slopes [H] → adds slope·(kpos − qpos) to the
    scores (BLOOM/Press-et-al. linear position bias). ``window``: causal
    sliding window (Mistral SWA) — key kp visible iff qp−window < kp ≤ qp.
    ``key_mask``: [B, Tk] bool, False = padding key (HF attention_mask;
    the correctness-critical case is padded ENCODER batches).
    """
    b, tq, h, dh = q.shape
    _, tk, kvh, _ = k.shape
    groups = h // kvh
    qg = q.reshape(b, tq, kvh, groups, dh)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(dh)
    qpos = jnp.arange(tq) + q_offset
    kpos = jnp.arange(tk)
    if alibi is not None:
        rel = (kpos[None, :] - qpos[:, None]).astype(jnp.float32)  # ≤ 0 kept
        scores = scores + alibi.reshape(kvh, groups)[None, :, :, None, None] \
            * rel[None, None, None]
    if causal or window is not None:
        mask = qpos[:, None] >= kpos[None, :] if causal else \
            jnp.ones((tq, tk), bool)
        if window is not None:
            # ``window`` may be a traced per-layer scalar (GPT-Neo
            # alternating local attention); <= 0 means full causal
            w = jnp.asarray(window)
            mask = mask & ((w <= 0) | (kpos[None, :] > qpos[:, None] - w))
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, tq, h, dh)


AttentionFn = Callable[..., jax.Array]


def default_attention(cfg: DecoderConfig) -> AttentionFn:
    """Config-correct plain attention: ALiBi models get their slopes baked
    in (a bare ``dot_product_attention`` would silently train a
    position-free BLOOM), encoders (BERT) get the causal mask dropped."""
    if not cfg.causal:
        return partial(dot_product_attention, causal=False)
    if cfg.pos_emb == "alibi":
        return partial(dot_product_attention,
                       alibi=alibi_slopes(cfg.num_heads))
    if cfg.sliding_window is not None:
        return partial(dot_product_attention, window=cfg.sliding_window)
    return dot_product_attention


def layer_windows(cfg: DecoderConfig) -> jax.Array:
    """[L] int32 of per-layer attention windows (0 = full causal), the
    ``layer_window_pattern`` tiled over depth — GPT-Neo's
    ``attention_types`` expansion."""
    return jnp.asarray(cfg.window_per_layer(), jnp.int32)


def resolve_remat_policy(name: Optional[str]):
    """Map config policy names (ActivationCheckpointingConfig.policy) to
    jax.checkpoint policies; 'full'/None -> save nothing extra."""
    policies = {
        "none": None,
        "full": None,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_with_no_batch_dims_saveable":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # save each block's attention output (64MB/layer at 8x2048x2048
        # bf16); backward recomputes the cheap-to-recompute MLP/projection
        # GEMMs but NOT attention — the best memory/time trade when
        # attention is bandwidth-bound
        # "moe_dispatch" rides along in every save_* policy: the MoE
        # counting-sort metadata (parallel/moe.py) is ~0.4MB/layer but
        # recomputing it in backward re-runs the dispatch histogram
        "save_attn_out":
            jax.checkpoint_policies.save_only_these_names(
                "attn_out", "moe_dispatch", "moe_xs"),
        # save the Pallas flash kernel's residuals (pre-projection out +
        # lse, named inside the custom_vjp fwd) instead of the projected
        # attn_out: same bytes (+~1% for lse), but the backward no longer
        # re-runs the flash FORWARD kernel to rebuild them — a whole extra
        # attention pass per layer at long sequence. Only the cheap wo
        # projection recomputes. Pallas-attention configs only (other
        # impls don't emit these names and would save nothing).
        "save_attn_kernel":
            jax.checkpoint_policies.save_only_these_names(
                "attn_kernel_out", "attn_lse", "moe_dispatch",
                "moe_xs"),
        # + the MoE GLU pre-activations (~2x[R, ffn] bf16 per layer of
        # extra HBM). Only affects the UNSCALED grouped-matmul path —
        # the default fused-combine path recomputes gate/up in-kernel
        # and has no moe_glu residuals (measured FASTER than stacking
        # them across the layer scan; ops/grouped_matmul.py docstring)
        "save_attn_kernel_moe_glu":
            jax.checkpoint_policies.save_only_these_names(
                "attn_kernel_out", "attn_lse", "moe_dispatch",
                "moe_xs", "moe_glu"),
        # also save post-rope q/k/v: backward skips the QKV projection
        # recompute at +(q_dim+2·kv·Dh)·2B per token of HBM. Helps only
        # when HBM is loose — at the 1.27B/seq2048/b8 bench point the
        # extra residency evicts the CE chunk budget and LOSES 20+ MFU
        # points; measure before enabling
        "save_attn_qkv":
            jax.checkpoint_policies.save_only_these_names(
                "attn_out", "qkv", "moe_dispatch", "moe_xs"),
        # flash-kernel residuals AND post-rope q/k/v: backward re-runs
        # neither the flash forward nor the qkv projections/rope —
        # +(q+2kv)·Dh·2B per token of HBM on top of save_attn_kernel;
        # measure per geometry (same eviction caveat as save_attn_qkv)
        "save_attn_kernel_qkv":
            jax.checkpoint_policies.save_only_these_names(
                "attn_kernel_out", "attn_lse", "qkv", "moe_dispatch",
                "moe_xs"),
        # Host-DRAM activation offload — the reference's cpu_checkpointing
        # (runtime/activation_checkpointing/checkpointing.py partition/
        # cpu_checkpoint knobs). XLA emits async copy-start/copy-done pairs
        # to pinned host memory, overlapped with layer compute; backward
        # streams the tensors back. 'offload_attn_out' keeps the
        # save_attn_out recompute profile but parks attention outputs in
        # host DRAM instead of HBM; 'offload_full' offloads each layer's
        # residual-stream input and recomputes the whole block from it
        # (max HBM savings — the cpu_checkpointing analogue proper).
        "offload_attn_out":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["moe_dispatch"],
                names_which_can_be_offloaded=["attn_out"],
                offload_src="device", offload_dst="pinned_host"),
        "offload_attn_qkv":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["moe_dispatch"],
                names_which_can_be_offloaded=["attn_out", "qkv"],
                offload_src="device", offload_dst="pinned_host"),
        "offload_full":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["moe_dispatch"],
                names_which_can_be_offloaded=["block_in"],
                offload_src="device", offload_dst="pinned_host"),
        # block_in to host + attn_out kept in HBM: backward skips the
        # flash-attention recompute (the expensive part of 'full') while
        # the carry chain stops occupying HBM — the long-context sweet
        # spot when save_attn_out alone no longer fits
        "offload_save_attn_out":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["attn_out", "moe_dispatch"],
                names_which_can_be_offloaded=["block_in"],
                offload_src="device", offload_dst="pinned_host"),
        # flash-kernel residuals kept in HBM (backward skips the flash
        # FORWARD re-run entirely — see 'save_attn_kernel') + block inputs
        # parked on host: the 32K+ sweet spot where keeping both the
        # residual chain and the kernel outputs on device OOMs
        "offload_save_attn_kernel":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["attn_kernel_out", "attn_lse",
                                           "moe_dispatch"],
                names_which_can_be_offloaded=["block_in"],
                offload_src="device", offload_dst="pinned_host"),
        # the 128K+ regime: block inputs AND the flash-kernel residuals
        # all live in host DRAM — backward re-runs only the projections
        # and MLP, never the flash forward, and device HBM holds no
        # per-layer [T, ...] residuals at all. The extra ~1GB/layer of
        # D2H+H2D traffic vanishes under the attention math at these
        # sequence lengths (attention is ~97% of step FLOPs at 128K).
        "offload_save_attn_kernel_host":
            jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=["moe_dispatch"],
                names_which_can_be_offloaded=["block_in",
                                              "attn_kernel_out",
                                              "attn_lse"],
                offload_src="device", offload_dst="pinned_host"),
    }
    if name is not None and name not in policies:
        raise ValueError(f"unknown remat policy '{name}'; "
                         f"known: {sorted(policies)}")
    return policies.get(name)


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

def linear_2d(x: jax.Array, p: Params, name: str) -> jax.Array:
    """``x [..., K] @ p[name] [K, N]`` honoring int8 weight-only
    quantization: a ``<name>_scale`` leaf (ops/quantized_linear.py
    convention, attached by the inference engines' ``weight_quant``
    config) routes through the Pallas dequant-in-VMEM matmul — weights
    live in HBM at half the bytes (a memory-capacity feature; see the
    measured tradeoffs in ops/quantized_linear.py). Without a scale
    leaf this is a plain einsum (training path, fully
    differentiable)."""
    w = p[name]
    if name + "_scale" not in p:
        return jnp.einsum("...k,kn->...n", x, w)
    from deepspeed_tpu.ops.quantized_linear import qmatmul_tp
    lead = x.shape[:-1]
    # TP roles mirror partition_specs: out-projections ("wo") are
    # row-parallel, everything else column-parallel
    out = qmatmul_tp(x.reshape(-1, x.shape[-1]), w, p[name + "_scale"],
                     role="row" if name == "wo" else "col")
    return out.reshape(*lead, w.shape[-1])


def _mlp(cfg: DecoderConfig, p: Params, x: jax.Array) -> jax.Array:
    if cfg.is_glu:
        gate = linear_2d(x, p, "wg")
        up = linear_2d(x, p, "wi")
        act = jax.nn.silu(gate) if cfg.activation == "silu_glu" \
            else jax.nn.gelu(gate, approximate=True)
        hidden = act * up
    else:
        hidden = linear_2d(x, p, "wi")
        if "bi" in p:
            hidden = hidden + p["bi"]
        if cfg.activation == "relu":
            hidden = jax.nn.relu(hidden)
        else:
            hidden = jax.nn.gelu(
                hidden, approximate=cfg.activation != "gelu_exact")
    out = linear_2d(hidden, p, "wo")
    if "bo" in p:
        out = out + p["bo"]
    return out


def qkv_project(cfg: DecoderConfig, p: Params, x: jax.Array, sin, cos
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared projection for training and KV-cached inference:
    x [B,t,D] -> q [B,t,H,Dh], k/v [B,t,KvH,Dh] with bias + RoPE applied."""
    b, t = x.shape[:2]
    q = linear_2d(x, p, "wq").reshape(b, t, cfg.num_heads, cfg.head_dim)
    k = linear_2d(x, p, "wk").reshape(b, t, cfg.kv_heads, cfg.head_dim)
    v = linear_2d(x, p, "wv").reshape(b, t, cfg.kv_heads, cfg.head_dim)
    if "bq" in p:
        q = q + p["bq"].reshape(cfg.num_heads, cfg.head_dim)
        k = k + p["bk"].reshape(cfg.kv_heads, cfg.head_dim)
        v = v + p["bv"].reshape(cfg.kv_heads, cfg.head_dim)
    if cfg.pos_emb == "rope":
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
    q = checkpoint_name(q, "qkv")
    k = checkpoint_name(k, "qkv")
    v = checkpoint_name(v, "qkv")
    return q, k, v


def attn_out_project(cfg: DecoderConfig, p: Params, out: jax.Array
                     ) -> jax.Array:
    b, t = out.shape[:2]
    out = linear_2d(out.reshape(b, t, cfg.q_dim), p, "wo")
    if "bo" in p:
        out = out + p["bo"]
    return out


def _attention_block(cfg: DecoderConfig, p: Params, x: jax.Array,
                     sin, cos, attn_fn: AttentionFn,
                     layer_window: Optional[jax.Array] = None) -> jax.Array:
    q, k, v = qkv_project(cfg, p, x, sin, cos)
    out = attn_fn(q, k, v) if layer_window is None \
        else attn_fn(q, k, v, window=layer_window)
    return attn_out_project(cfg, p, out)


def decoder_block(cfg: DecoderConfig, p: Params, x: jax.Array, sin, cos,
                  attn_fn: AttentionFn,
                  moe_fn: Optional[Callable] = None,
                  layer_window: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Returns (hidden, aux_loss) — aux is 0 for dense blocks, the scaled
    load-balance loss for MoE blocks (reference sharded_moe.py l_aux).

    Under ``cfg.health_taps`` the second output is instead a per-layer
    stats dict ({aux_loss, act_rms, act_absmax} + MoE router stats) that
    ``lax.scan`` stacks into [L]-leading arrays for telemetry/health.py.
    """
    pre = _norm(cfg, p["ln1"], x) if cfg.prenorm else x
    attn_out = _attention_block(cfg, p["attn"], pre, sin, cos, attn_fn,
                                layer_window)
    attn_out = checkpoint_name(attn_out, "attn_out")
    if not getattr(cfg, "health_taps", False):
        return block_combine(cfg, p, x, pre, attn_out, moe_fn)
    h, aux, rstats = block_combine(cfg, p, x, pre, attn_out, moe_fn)
    hf = h.astype(jnp.float32)
    stats = {"aux_loss": aux,
             "act_rms": jnp.sqrt(jnp.mean(jnp.square(hf))),
             "act_absmax": jnp.max(jnp.abs(hf))}
    if rstats is not None:
        stats.update(rstats)
    return h, stats


def block_combine(cfg: DecoderConfig, p: Params, x: jax.Array,
                  pre: jax.Array, attn_out: jax.Array,
                  moe_fn: Optional[Callable]) -> Tuple[jax.Array, jax.Array]:
    """Residual combine shared by training, cached decode, and ragged
    inference (one home for the parallel/sequential branch math).

    Parallel (GPT-J/NeoX/Falcon): h = x + attn + mlp(src) where src is
    the shared pre-norm (1-norm variants) or a separate ln2(x) (NeoX /
    Falcon-40B 2-norm variants); attention and MLP matmuls overlap on the
    MXU. Sequential (GPT-2/Llama): post-attention pre-norm MLP.
    Post-LN (BERT/original transformer, prenorm=False):
    h = ln1(x + attn(x)); out = ln2(h + mlp(h)).
    """
    def ffn(src):
        if cfg.num_experts and moe_fn is not None:
            ret = moe_fn(cfg, p["moe"], src)
            out, aux = ret[0], ret[1]
            # 3rd element = router-health stats, present iff the moe
            # layer saw cfg.health_taps (parallel/moe.py)
            rstats = ret[2] if len(ret) > 2 else None
            if "residual" in p["moe"]:
                # Residual-MoE (reference moe/layer.py use_residual):
                # learned convex mix of the routed output and a dense MLP
                res = _mlp(cfg, p["moe"]["residual"], src)
                coef = jax.nn.softmax(
                    jnp.einsum("...d,dc->...c", src.astype(jnp.float32),
                               p["moe"]["coef"].astype(jnp.float32))
                    + p["moe"]["coef_b"].astype(jnp.float32),
                    axis=-1).astype(src.dtype)
                out = out * coef[..., 0:1] + res * coef[..., 1:2]
            return out, aux, rstats
        if cfg.ffn_chunk and src.shape[1] > cfg.ffn_chunk:
            # FPDT chunked MLP: [T, ffn]-sized activations become
            # [ffn_chunk, ffn]-sized (parallel/fpdt.fpdt_ffn)
            from deepspeed_tpu.parallel.fpdt import fpdt_ffn
            return (fpdt_ffn(partial(_mlp, cfg, p["mlp"]), src,
                             chunk=cfg.ffn_chunk),
                    jnp.zeros((), jnp.float32), None)
        return _mlp(cfg, p["mlp"], src), jnp.zeros((), jnp.float32), None

    if not cfg.prenorm:
        h = _norm(cfg, p["ln1"], x + attn_out)
        ff, aux, rstats = ffn(h)
        out = _norm(cfg, p["ln2"], h + ff)
    elif cfg.parallel_block:
        src = _norm(cfg, p["ln2"], x) if cfg.parallel_block_norms == 2 \
            else pre
        ff, aux, rstats = ffn(src)
        out = x + attn_out + ff
    else:
        h = x + attn_out
        ff, aux, rstats = ffn(_norm(cfg, p["ln2"], h))
        out = h + ff
    if getattr(cfg, "health_taps", False):
        return out, aux, rstats
    return out, aux


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(cfg: DecoderConfig, rng: jax.Array,
                dtype=jnp.float32) -> Params:
    """Initialize the full parameter pytree (stacked layers)."""
    d, v, L = cfg.hidden_size, cfg.vocab_size, cfg.num_layers
    h = cfg.ffn_size
    kd = cfg.kv_heads * cfg.head_dim
    qd = cfg.q_dim
    keys = jax.random.split(rng, 20)

    def w(key, shape, std=cfg.init_std):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)

    attn = {
        "wq": w(keys[0], (L, d, qd)),
        "wk": w(keys[1], (L, d, kd)),
        "wv": w(keys[2], (L, d, kd)),
        "wo": w(keys[3], (L, qd, d), std=cfg.init_std / math.sqrt(2 * L)),
    }
    if cfg.qkv_bias:
        attn.update(bq=jnp.zeros((L, qd), dtype), bk=jnp.zeros((L, kd), dtype),
                    bv=jnp.zeros((L, kd), dtype))
    if cfg.out_bias:
        attn["bo"] = jnp.zeros((L, d), dtype)

    layers: Params = {
        "attn": attn,
        "ln1": _norm_params(cfg, (L,)),
    }
    if cfg.has_ln2:
        layers["ln2"] = _norm_params(cfg, (L,))
    if cfg.num_experts:
        E = cfg.num_experts
        layers["moe"] = {
            "router": w(keys[4], (L, d, E)),
            "wg": w(keys[5], (L, E, d, h)),
            "wi": w(keys[6], (L, E, d, h)),
            "wo": w(keys[7], (L, E, h, d), std=cfg.init_std / math.sqrt(2 * L)),
        }
        if cfg.shared_expert_size:
            hs = cfg.shared_expert_size
            shared = {
                "wg": w(keys[12], (L, d, hs)),
                "wi": w(keys[13], (L, d, hs)),
                "wo": w(keys[14], (L, hs, d),
                        std=cfg.init_std / math.sqrt(2 * L)),
            }
            if cfg.shared_expert_gate:
                shared["gate"] = w(keys[15], (L, d, 1))
            layers["moe"]["shared"] = shared
        if cfg.moe_residual:
            # Residual-MoE dense branch + 2-way mixing coefficient
            # (reference moe/layer.py: self.mlp + self.coefficient)
            if cfg.is_glu:
                residual = {
                    "wg": w(keys[16], (L, d, h)),
                    "wi": w(keys[17], (L, d, h)),
                    "wo": w(keys[18], (L, h, d),
                            std=cfg.init_std / math.sqrt(2 * L)),
                }
            else:
                residual = {
                    "wi": w(keys[17], (L, d, h)),
                    "wo": w(keys[18], (L, h, d),
                            std=cfg.init_std / math.sqrt(2 * L)),
                }
                if cfg.use_bias:
                    residual.update(bi=jnp.zeros((L, h), dtype),
                                    bo=jnp.zeros((L, d), dtype))
            layers["moe"]["residual"] = residual
            layers["moe"]["coef"] = w(keys[19], (L, d, 2))
            layers["moe"]["coef_b"] = jnp.zeros((L, 2), dtype)
    else:
        if cfg.is_glu:
            layers["mlp"] = {
                "wg": w(keys[5], (L, d, h)),
                "wi": w(keys[6], (L, d, h)),
                "wo": w(keys[7], (L, h, d), std=cfg.init_std / math.sqrt(2 * L)),
            }
        else:
            layers["mlp"] = {
                "wi": w(keys[6], (L, d, h)),
                "wo": w(keys[7], (L, h, d), std=cfg.init_std / math.sqrt(2 * L)),
            }
            if cfg.use_bias:
                layers["mlp"].update(bi=jnp.zeros((L, h), dtype),
                                     bo=jnp.zeros((L, d), dtype))

    params: Params = {
        "embed": {"tokens": w(keys[8], (v, d))},
        "layers": layers,
    }
    if cfg.has_final_norm:
        params["final_norm"] = _norm_params(cfg)
    if cfg.embed_norm:
        params["embed_norm"] = _norm_params(cfg)
    if cfg.pos_emb == "learned":
        params["embed"]["pos"] = w(keys[9], (cfg.max_seq_len, d))
    if cfg.type_vocab_size:
        params["embed"]["token_type"] = w(keys[11], (cfg.type_vocab_size, d))
    if cfg.mlm_head:
        params["mlm_head"] = {
            "dense": w(keys[12], (d, d)),
            "dense_bias": jnp.zeros((d,), dtype),
            "ln": _norm_params(cfg),
            "vocab_bias": jnp.zeros((v,), dtype),
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = w(keys[10], (d, v))
        if cfg.lm_head_bias:
            params["lm_head_bias"] = jnp.zeros((v,), dtype)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward_hidden(cfg: DecoderConfig, params: Params, tokens: jax.Array,
                   attn_fn: Optional[AttentionFn] = None,
                   moe_fn: Optional[Callable] = None,
                   positions: Optional[jax.Array] = None,
                   remat_policy: Optional[str] = None,
                   token_type_ids: Optional[jax.Array] = None,
                   attention_mask: Optional[jax.Array] = None,
                   layer_loop: Optional[Callable] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """tokens: [B, T] int32 → (final-norm hidden [B, T, D], MoE aux loss).

    Layers applied with ``lax.scan`` over the stacked pytree; optional
    ``jax.checkpoint`` per block (the reference's activation checkpointing
    runtime/activation_checkpointing/ → remat on TPU).

    ``layer_loop``: optional replacement for the plain
    ``lax.scan(body, x, xs)`` with the same contract (carry in, carry +
    stacked-aux out) — the ZeRO-3 chunked-overlap path
    (runtime/zero/overlap.py OverlapPlan.layer_loop) injects its
    gather/compute pipeline here without this module importing runtime.

    ``attention_mask``: [B, T] (1 = real, 0 = pad; HF convention). Only
    needed for ENCODERS, where pad keys attend into every position;
    right-padded decoder batches are already correct under the causal
    mask (+ label -100). The selected ``attn_fn`` must accept
    ``key_mask`` (the masked/chunked paths do; Pallas flash is
    causal-only and never selected for encoders).
    """
    if attn_fn is None:
        attn_fn = default_attention(cfg)
    if attention_mask is not None:
        attn_fn = partial(attn_fn, key_mask=attention_mask.astype(bool))
    b, t = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    x = embed_tokens(cfg, params["embed"], tokens, positions,
                     params.get("embed_norm"), token_type_ids)
    if cfg.pos_emb == "rope":
        sin, cos = rope_table(cfg, positions)
    else:   # learned: applied in embed; alibi: bias in the attention impl
        sin = cos = jnp.zeros((b, t, 0), x.dtype)

    block = partial(decoder_block, cfg, attn_fn=attn_fn, moe_fn=moe_fn)

    if cfg.layer_window_pattern:
        def body(carry, xs):
            layer_params, w = xs
            carry = checkpoint_name(carry, "block_in")
            out, aux = block(layer_params, carry, sin, cos, layer_window=w)
            return out, aux
        scan_xs = (params["layers"],
                   layer_windows(cfg))
    else:
        def body(carry, layer_params):
            carry = checkpoint_name(carry, "block_in")
            out, aux = block(layer_params, carry, sin, cos)
            return out, aux
        scan_xs = params["layers"]

    if remat_policy and remat_policy != "none":
        body = jax.checkpoint(body, policy=resolve_remat_policy(remat_policy))

    if layer_loop is not None:
        x, aux = layer_loop(body, x, scan_xs)
    else:
        x, aux = lax.scan(body, x, scan_xs)
    if cfg.has_final_norm:
        x = _norm(cfg, params["final_norm"], x)
    if getattr(cfg, "health_taps", False):
        # aux is the scan-stacked per-layer stats dict ([L]-leading
        # leaves); the loss consumes only the aux_loss component, the
        # rest flows to telemetry/health.py as a third output
        return x, jnp.sum(aux["aux_loss"]), aux
    return x, jnp.sum(aux)


def _softcap(cfg: DecoderConfig, logits: jax.Array) -> jax.Array:
    """Gemma2 final_logit_softcapping: c·tanh(logits/c)."""
    if cfg.logit_softcap:
        c = cfg.logit_softcap
        return c * jnp.tanh(logits / c)
    return logits


def mlm_transform(cfg: DecoderConfig, mh: Params, x: jax.Array) -> jax.Array:
    """HF ``cls.predictions.transform``: dense + the config activation +
    LN (shared by lm_logits and chunked_cross_entropy so the training
    loss optimizes the exact serving logits)."""
    x = jnp.einsum("btd,de->bte", x, mh["dense"]) + mh["dense_bias"]
    if cfg.activation == "relu":
        x = jax.nn.relu(x)
    else:
        x = jax.nn.gelu(x, approximate=cfg.activation != "gelu_exact")
    return _norm(cfg, mh["ln"], x)


def lm_logits(cfg: DecoderConfig, params: Params, x: jax.Array,
              pre_transformed: bool = False) -> jax.Array:
    """Final projection: hidden [B,T,D] → logits [B,T,V] fp32.

    ``mlm_head`` models (BERT) first run the HF ``cls.predictions.
    transform`` — dense+act+LN — then the tied decode plus vocab bias
    (``pre_transformed=True`` when the caller already applied it)."""
    if cfg.mlm_head and "mlm_head" in params:
        if not pre_transformed:
            x = mlm_transform(cfg, params["mlm_head"], x)
        logits = jnp.einsum("btd,vd->btv", x, params["embed"]["tokens"],
                            preferred_element_type=jnp.float32)
        return logits + params["mlm_head"]["vocab_bias"].astype(jnp.float32)
    q_name = "lm_head_q" if "lm_head_q" in params else \
        ("lm_head" if "lm_head_scale" in params else None)
    if q_name:   # int8 serving head (tied models carry a transposed copy)
        from deepspeed_tpu.ops.quantized_linear import qmatmul_tp
        b, t, d = x.shape
        logits = qmatmul_tp(x.reshape(b * t, d), params[q_name],
                            params[q_name + "_scale"], role="col",
                            out_dtype=jnp.float32).reshape(b, t, -1)
        if "lm_head_bias" in params:
            logits = logits + params["lm_head_bias"].astype(jnp.float32)
    elif cfg.tie_embeddings:
        logits = jnp.einsum("btd,vd->btv", x, params["embed"]["tokens"],
                            preferred_element_type=jnp.float32)
    else:
        logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                            preferred_element_type=jnp.float32)
        if "lm_head_bias" in params:
            logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return _softcap(cfg, logits)


def forward(cfg: DecoderConfig, params: Params, tokens: jax.Array,
            attn_fn: Optional[AttentionFn] = None,
            moe_fn: Optional[Callable] = None,
            positions: Optional[jax.Array] = None,
            remat_policy: Optional[str] = None,
            with_aux: bool = False,
            token_type_ids: Optional[jax.Array] = None,
            attention_mask: Optional[jax.Array] = None
            ) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
    """tokens → logits [B,T,V] fp32 (with_aux: plus MoE aux loss)."""
    x, aux = forward_hidden(cfg, params, tokens, attn_fn=attn_fn,
                            moe_fn=moe_fn, positions=positions,
                            remat_policy=remat_policy,
                            token_type_ids=token_type_ids,
                            attention_mask=attention_mask)
    logits = lm_logits(cfg, params, x)
    if with_aux:
        return logits, aux
    return logits


#: dense (unchunked, no-remat) logits allowed up to this size only — the
#: chunk budget below may be larger, but an unchunked CE also KEEPS the
#: logits for backward, so its cap stays conservative
_DENSE_LOGITS_BYTES = 128 * 1024 * 1024


def _pick_chunk(t: int, b: int, v: int,
                budget_bytes: Optional[int] = None,
                max_chunk: Optional[int] = None,
                elt_bytes: int = 4) -> int:
    """Largest divisor of T (≤ max_chunk) whose fp32 logits chunk fits
    the budget.

    The budget trades HBM for MXU shape: too small and the [B·C, D]×[D, V]
    chunk matmul has so few rows the MXU idles (measured on v5e 1.27B/
    128k-vocab: 512 MB ≈ 11% faster steps than 128 MB). Overridable via
    ``DSTPU_CE_BUDGET_MB`` for tuning."""
    if budget_bytes is None:
        import os
        budget_bytes = int(os.environ.get("DSTPU_CE_BUDGET_MB", 512)) \
            * 1024 * 1024
    best = 1
    for c in range(1, (max_chunk or t) + 1):
        if t % c == 0 and b * c * v * elt_bytes <= budget_bytes:
            best = c
    return best


def chunked_cross_entropy(cfg: DecoderConfig, params: Params, x: jax.Array,
                          targets: jax.Array, ignore_index: int = -100,
                          chunk_size: Optional[int] = None,
                          budget_bytes: Optional[int] = None,
                          logits_dtype=None) -> jax.Array:
    """Token-mean CE without materializing [B,T,V] logits.

    TPU-native equivalent of the reference's tiled logits-loss
    (runtime/sequence_parallel/ulysses_sp.py:TiledFusedLogitsLoss:960):
    the sequence is scanned in chunks with ``jax.checkpoint`` on the chunk
    body, so backward recomputes each chunk's logits and peak memory is
    one chunk — the difference between OOM and training for 128k vocabs.
    """
    b, t, d = x.shape
    v = cfg.vocab_size
    # BERT-class heads: run the cls.predictions transform ONCE on the
    # full hidden (a cheap [B,T,D]×[D,D]), so every path below — dense
    # shortcut and chunk scan — decodes the exact serving logits
    mlm = cfg.mlm_head and "mlm_head" in params
    if mlm:
        x = mlm_transform(cfg, params["mlm_head"], x)
    # chunk sizing follows the EMITTED logits dtype (bf16 chunks are half
    # the bytes, so the same budget buys twice the rows for the MXU); the
    # dense shortcut below stays a 4-byte bound — that path materializes
    # fp32 lm_logits
    eb = 2 if logits_dtype == jnp.bfloat16 else 4
    chunk = chunk_size or _pick_chunk(t, b, v, budget_bytes, elt_bytes=eb)
    if chunk >= t and chunk_size is None and \
            b * t * v * 4 > _DENSE_LOGITS_BYTES:
        # the whole-T logits fit the CHUNK budget, but an unchunked CE
        # would also hold them live for backward (no remat) — keep the
        # scan with at least two chunks instead
        chunk = _pick_chunk(t, b, v, budget_bytes, max_chunk=t // 2,
                            elt_bytes=eb)
    if chunk >= t:
        return cross_entropy_loss(
            lm_logits(cfg, params, x, pre_transformed=True), targets,
            ignore_index)
    w = params["embed"]["tokens"] if cfg.tie_embeddings else params["lm_head"]
    nc = t // chunk
    xs = jnp.moveaxis(x.reshape(b, nc, chunk, d), 1, 0)       # [nc,B,C,D]
    ts = jnp.moveaxis(targets.reshape(b, nc, chunk), 1, 0)    # [nc,B,C]

    # logits_dtype=bf16 emits chunk logits in bf16 and upcasts inside the
    # fused reductions: the MXU still accumulates fp32 (preferred_element_
    # type sets the OUTPUT type on TPU), but the [B,C,V] HBM roundtrip
    # halves — measured +0.6 MFU points on the v5e bench. Default fp32.
    out_dt = logits_dtype or jnp.float32

    @jax.checkpoint
    def body(carry, xc_tc):
        nll_sum, cnt = carry
        xc, tc = xc_tc
        if cfg.tie_embeddings:
            logits = jnp.einsum("bcd,vd->bcv", xc, w,
                                preferred_element_type=out_dt)
            if mlm:
                logits = logits + \
                    params["mlm_head"]["vocab_bias"].astype(out_dt)
        else:
            logits = jnp.einsum("bcd,dv->bcv", xc, w,
                                preferred_element_type=out_dt)
            if "lm_head_bias" in params:
                logits = logits + params["lm_head_bias"].astype(out_dt)
        logits = _softcap(cfg, logits)
        mask = tc != ignore_index
        safe = jnp.where(mask, tc, 0)
        logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None],
                                   axis=-1)[..., 0].astype(jnp.float32)
        nll = jnp.sum((logz - gold) * mask)
        return (nll_sum + nll, cnt + jnp.sum(mask)), None

    (nll, cnt), _ = lax.scan(body, (jnp.zeros((), jnp.float32),
                                    jnp.zeros((), jnp.int32)), (xs, ts))
    return nll / jnp.maximum(cnt, 1)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       ignore_index: int = -100) -> jax.Array:
    """Token-mean CE in fp32 (reference: sequence/cross_entropy.py
    semantics; under TP the embed/lm_head specs shard the vocab dim over
    'model' and GSPMD emits the vocab-parallel max/sum collectives the
    reference hand-writes)."""
    logits = logits.astype(jnp.float32)
    mask = (targets != ignore_index)
    safe_targets = jnp.where(mask, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    return nll.sum() / jnp.maximum(mask.sum(), 1)


# ---------------------------------------------------------------------------
# KV-cached forward (inference; reference: inference_context.h KV rings +
# inference/v2 blocked KV — here a static-shape cache updated in place)
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: DecoderConfig, batch: int, max_len: int,
                  dtype=jnp.bfloat16) -> Params:
    shape = (cfg.num_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_attention(cfg: DecoderConfig, p: Params, x, sin, cos,
                      k_cache, v_cache, cache_len, layer_window=None):
    """One block's attention against the cache; returns (out, k_new, v_new).

    x: [B, t, D] new tokens; k_cache/v_cache: [B, Tmax, KvH, Dh];
    cache_len: scalar int32 — tokens already cached. ``layer_window``:
    traced per-layer window (GPT-Neo local layers; <=0 = full).
    """
    b, t, d = x.shape
    q, k, v = qkv_project(cfg, p, x, sin, cos)
    k_cache = lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (0, cache_len, 0, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (0, cache_len, 0, 0))

    # attend over the whole (static) cache with a validity+causal mask
    tmax = k_cache.shape[1]
    kvh, dh = cfg.kv_heads, cfg.head_dim
    groups = cfg.num_heads // kvh
    qg = q.reshape(b, t, kvh, groups, dh)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg,
                        k_cache.astype(q.dtype),
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(dh)
    qpos = cache_len + jnp.arange(t)
    kpos = jnp.arange(tmax)
    if cfg.pos_emb == "alibi":
        rel = (kpos[None, :] - qpos[:, None]).astype(jnp.float32)
        scores = scores + alibi_slopes(cfg.num_heads).reshape(
            kvh, groups)[None, :, :, None, None] * rel[None, None, None]
    mask = qpos[:, None] >= kpos[None, :]
    if cfg.sliding_window is not None:
        mask = mask & (kpos[None, :] > qpos[:, None] - cfg.sliding_window)
    if layer_window is not None:
        w = jnp.asarray(layer_window)
        mask = mask & ((w <= 0) | (kpos[None, :] > qpos[:, None] - w))
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v_cache)
    out = out.reshape(b, t, cfg.num_heads, dh)
    return attn_out_project(cfg, p, out), k_cache, v_cache


def forward_with_cache(cfg: DecoderConfig, params: Params, tokens: jax.Array,
                       cache: Params, cache_len: jax.Array,
                       moe_fn: Optional[Callable] = None
                       ) -> Tuple[jax.Array, Params]:
    """tokens: [B, t] (prefill t>1 or decode t==1) → (logits of the LAST
    position [B, V] fp32, updated cache). cache_len: tokens already held.
    """
    b, t = tokens.shape
    positions = cache_len + jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    x = embed_tokens(cfg, params["embed"], tokens, positions,
                     params.get("embed_norm"))
    if cfg.pos_emb == "rope":
        sin, cos = rope_table(cfg, positions)
    else:
        sin = cos = jnp.zeros((b, t, 0), x.dtype)

    def body(carry, layer):
        x = carry
        layer_params, k_c, v_c = layer[:3]
        w = layer[3] if len(layer) > 3 else None
        h_in = _norm(cfg, layer_params["ln1"], x) if cfg.prenorm else x
        attn_out, k_c, v_c = _cached_attention(
            cfg, layer_params["attn"], h_in, sin, cos, k_c, v_c, cache_len,
            layer_window=w)
        out, _aux = block_combine(cfg, layer_params, x, h_in, attn_out,
                                  moe_fn)
        return out, (k_c, v_c)

    scan_xs = (params["layers"], cache["k"], cache["v"])
    if cfg.layer_window_pattern:
        scan_xs = scan_xs + (layer_windows(cfg),)
    x, (k_new, v_new) = lax.scan(body, x, scan_xs)
    x = x[:, -1:]
    if cfg.has_final_norm:
        x = _norm(cfg, params["final_norm"], x)
    logits = lm_logits(cfg, params, x)[:, 0]
    return logits, {"k": k_new, "v": v_new}


# ---------------------------------------------------------------------------
# Partition specs — the AutoTP + ZeRO sharding planner
# ---------------------------------------------------------------------------

def partition_specs(cfg: DecoderConfig, zero_stage: int = 0,
                    tp: bool = False, mics: bool = False) -> Params:
    """PartitionSpec pytree matching :func:`init_params`.

    TP (reference module_inject/auto_tp.py row/col slicing): qkv + mlp-in are
    column-parallel (shard output dim over 'model'), attn-out + mlp-out are
    row-parallel (shard input dim); embeddings shard vocab.

    ZeRO-3 (reference zero/partition_parameters.py): shard a *different* axis
    over ('data','expert') so FSDP and TP compose. Stages 0-2 leave params
    replicated (grads/opt-state sharding is handled by the engine).
    """
    # MiCS (reference runtime/zero/mics.py:63): param shards live within
    # the (data_inner, expert) sub-group and replicate across 'data', so
    # stage-3 allgathers stay inside the cheap sub-group links
    if zero_stage >= 3:
        fsdp = ("data_inner", "expert") if mics else \
            ("data", "data_inner", "expert")
    else:
        fsdp = None
    model = "model" if tp else None

    def spec(*axes):
        return P(*axes)

    attn = {
        "wq": spec(None, fsdp, model),
        "wk": spec(None, fsdp, model),
        "wv": spec(None, fsdp, model),
        "wo": spec(None, model, fsdp),
    }
    if cfg.qkv_bias:
        attn.update(bq=spec(None, model), bk=spec(None, model),
                    bv=spec(None, model))
    if cfg.out_bias:
        attn["bo"] = spec(None, None)

    layers: Params = {
        "attn": attn,
        "ln1": {"scale": spec(None, None)},
    }
    if cfg.has_ln2:
        layers["ln2"] = {"scale": spec(None, None)}
    if cfg.ln_bias:
        layers["ln1"]["bias"] = spec(None, None)
        if cfg.has_ln2:
            layers["ln2"]["bias"] = spec(None, None)

    if cfg.num_experts:
        # expert weights: E dim sharded over 'expert'; FSDP restricted to
        # the data axes so they don't collide (reference: expert params are
        # DP'd over the expert-data-parallel group only, groups.py:315)
        if zero_stage >= 3:
            efsdp = "data_inner" if mics else ("data", "data_inner")
        else:
            efsdp = None
        layers["moe"] = {
            "router": spec(None, fsdp, None),
            "wg": spec(None, "expert", efsdp, model),
            "wi": spec(None, "expert", efsdp, model),
            "wo": spec(None, "expert", model, efsdp),
        }
        if cfg.shared_expert_size:
            # shared expert is DENSE (runs on every token): sharded like
            # a dense MLP, replicated over 'expert'
            shared = {
                "wg": spec(None, fsdp, model),
                "wi": spec(None, fsdp, model),
                "wo": spec(None, model, fsdp),
            }
            if cfg.shared_expert_gate:
                shared["gate"] = spec(None, fsdp, None)
            layers["moe"]["shared"] = shared
        if cfg.moe_residual:
            # residual dense branch: sharded like a dense MLP,
            # replicated over 'expert' (runs on every token)
            residual = {
                "wi": spec(None, fsdp, model),
                "wo": spec(None, model, fsdp),
            }
            if cfg.is_glu:
                residual["wg"] = spec(None, fsdp, model)
            elif cfg.use_bias:
                residual.update(bi=spec(None, model), bo=spec(None, None))
            layers["moe"]["residual"] = residual
            layers["moe"]["coef"] = spec(None, fsdp, None)
            layers["moe"]["coef_b"] = spec(None, None)
    else:
        mlp = {
            "wi": spec(None, fsdp, model),
            "wo": spec(None, model, fsdp),
        }
        if cfg.is_glu:
            mlp["wg"] = spec(None, fsdp, model)
        elif cfg.use_bias:
            mlp.update(bi=spec(None, model), bo=spec(None, None))
        layers["mlp"] = mlp

    specs: Params = {
        "embed": {"tokens": spec(model, fsdp)},
        "layers": layers,
    }
    if cfg.has_final_norm:
        specs["final_norm"] = {"scale": spec(None)}
        if cfg.ln_bias:
            specs["final_norm"]["bias"] = spec(None)
    if cfg.type_vocab_size:
        specs["embed"]["token_type"] = spec(None, fsdp)
    if cfg.mlm_head:
        mh = {"dense": spec(fsdp, None), "dense_bias": spec(None),
              "ln": {"scale": spec(None)}, "vocab_bias": spec(model)}
        if cfg.ln_bias:
            mh["ln"]["bias"] = spec(None)
        specs["mlm_head"] = mh
    if cfg.embed_norm:
        specs["embed_norm"] = {"scale": spec(None)}
        if cfg.ln_bias:
            specs["embed_norm"]["bias"] = spec(None)
    if cfg.pos_emb == "learned":
        specs["embed"]["pos"] = spec(None, fsdp)
    if not cfg.tie_embeddings:
        specs["lm_head"] = spec(fsdp, model)
        if cfg.lm_head_bias:
            specs["lm_head_bias"] = spec(model)
    return specs
