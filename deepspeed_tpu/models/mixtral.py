"""Mixtral-style MoE presets (BASELINE.md: MoE Mixtral-8x7B EP + AutoTP)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def mixtral_config(size: str = "8x7b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                     intermediate_size=128, vocab_size=512, max_seq_len=256,
                     num_experts=4, num_experts_per_tok=2),
        "8x7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     num_kv_heads=8, intermediate_size=14336,
                     num_experts=8, num_experts_per_tok=2),
    }
    base = dict(vocab_size=32000, max_seq_len=8192, norm="rmsnorm",
                activation="silu_glu", pos_emb="rope", rope_theta=1000000.0,
                use_bias=False, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
