"""OPT family presets (reference: inference/v2/model_implementations/opt/
— learned positions, ReLU MLP, sequential blocks, biases everywhere)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def opt_config(size: str = "1.3b", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12,
                     intermediate_size=3072),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=32,
                     intermediate_size=8192),
        "6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     intermediate_size=16384),
        "30b": dict(hidden_size=7168, num_layers=48, num_heads=56,
                    intermediate_size=28672),
    }
    base = dict(vocab_size=50272, max_seq_len=2048, norm="layernorm",
                activation="relu", pos_emb="learned", use_bias=True,
                tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
