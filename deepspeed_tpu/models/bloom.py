"""BLOOM family presets (reference: module_inject/containers/bloom.py —
the reference ships a dedicated BLOOM injection policy. Distinctives:
ALiBi position bias instead of RoPE/learned positions, a LayerNorm
directly after the word embeddings, fused-bias GELU MLP, tied head)."""

from deepspeed_tpu.models.transformer import DecoderConfig


def bloom_config(size: str = "560m", **overrides) -> DecoderConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     vocab_size=512, max_seq_len=256),
        "560m": dict(hidden_size=1024, num_layers=24, num_heads=16),
        "7b1": dict(hidden_size=4096, num_layers=30, num_heads=32),
        "176b": dict(hidden_size=14336, num_layers=70, num_heads=112),
    }
    base = dict(vocab_size=250880, max_seq_len=2048, norm="layernorm",
                activation="gelu", pos_emb="alibi", use_bias=True,
                tie_embeddings=True, embed_norm=True)
    base.update(presets[size])
    base.update(overrides)
    return DecoderConfig(**base)
