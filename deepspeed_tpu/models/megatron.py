"""Megatron-LM GPT checkpoint import (reference:
module_inject/containers/megatron_gpt.py + features/megatron.py).

The reference's v1 inference kernel-injects Megatron-LM
``ParallelTransformerLayer`` models; its policy documents the layout
this loader maps into the pytree:

- fused ``(self_)attention.query_key_value`` with the HEAD-MAJOR
  per-head [q|k|v] interleave (features/megatron.py:_align_qkv_transposed
  splits the out dim viewed as [H, 3·dh] into per-head thirds — the same
  convention as GPT-NeoX, which this repo's loaders already roundtrip
  against transformers);
- GPT-2 block otherwise: learned positions, LayerNorm with bias,
  sequential residual, dense_h_to_4h/dense_4h_to_h MLP, tied head.

Accepts the standard ``mp_rank_00/model_optim_rng.pt`` layout (or a
direct .pt path), reads model hyperparameters from the checkpoint's
``args`` when present, and handles both the modern (``encoder`` /
``self_attention``) and legacy (``transformer`` / ``attention``)
sub-module names.
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import DecoderConfig
from deepspeed_tpu.utils.logging import logger

Params = Any


def _flatten(d, prefix="") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        name = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, name))
        else:
            out[name] = v
    return out


def _resolve_ckpt_file(path: str) -> str:
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        ranks = sorted(d for d in os.listdir(path)
                       if d.startswith("mp_rank_"))
        if len(ranks) > 1:
            # silently reading only rank 0's shard would return a
            # structurally-valid half-sized garbage model
            raise NotImplementedError(
                f"checkpoint at {path!r} is tensor-parallel sharded "
                f"({len(ranks)} mp_rank_* dirs); merge the TP shards "
                "first (concatenate qkv/h_to_4h on the out dim, "
                "dense/4h_to_h on the in dim) — sharded import is not "
                "supported")
    for sub in ("mp_rank_00/model_optim_rng.pt",
                "mp_rank_00/model_rng.pt", "model_optim_rng.pt"):
        cand = os.path.join(path, sub)
        if os.path.isfile(cand):
            return cand
    raise FileNotFoundError(
        f"no Megatron checkpoint found under {path!r} (looked for "
        "mp_rank_00/model_optim_rng.pt and friends)")


def load_megatron_checkpoint(path: str,
                             num_heads: Optional[int] = None,
                             activation: str = "gelu_exact",
                             dtype=np.float32
                             ) -> Tuple[DecoderConfig, Params]:
    """Megatron-LM GPT checkpoint → (DecoderConfig, params pytree).

    ``num_heads`` overrides the value from the checkpoint's ``args``
    (required when the checkpoint carries no args). ``activation``:
    Megatron's default F.gelu is the exact erf form; pass ``"gelu"``
    for models trained with the tanh/openai variant.
    """
    import torch
    f = _resolve_ckpt_file(path)
    ckpt = torch.load(f, map_location="cpu", weights_only=False)
    model = ckpt.get("model", ckpt)
    lm = model.get("language_model", model)
    flat = {k: (v.float().numpy() if hasattr(v, "numpy") else
                np.asarray(v, np.float32))
            for k, v in _flatten(lm).items()
            if hasattr(v, "shape")}

    args = ckpt.get("args")
    emb = flat["embedding.word_embeddings.weight"]
    pos = flat["embedding.position_embeddings.weight"]
    core = "encoder" if any(k.startswith("encoder.") for k in flat) \
        else "transformer"
    attn = "self_attention" if \
        f"{core}.layers.0.self_attention.query_key_value.weight" in flat \
        else "attention"
    L = 1 + max(int(k.split(".")[2]) for k in flat
                if k.startswith(f"{core}.layers."))
    D = emb.shape[1]
    H = num_heads or (getattr(args, "num_attention_heads", None)
                      if args is not None else None)
    if H is None:
        raise ValueError(
            "checkpoint has no 'args'; pass num_heads= explicitly")
    ffn = flat[f"{core}.layers.0.mlp.dense_h_to_4h.weight"].shape[0]
    # --untie-embeddings-and-output-weights checkpoints carry an
    # explicit output_layer; dropping it would silently decode through
    # the (different) word embeddings
    untied = "output_layer.weight" in flat
    cfg = DecoderConfig(
        hidden_size=D, num_layers=L, num_heads=int(H),
        intermediate_size=int(ffn),
        vocab_size=emb.shape[0], max_seq_len=pos.shape[0],
        norm="layernorm", activation=activation, pos_emb="learned",
        norm_eps=float(getattr(args, "layernorm_epsilon", 1e-5)
                       if args is not None else 1e-5),
        use_bias=True, tie_embeddings=not untied)

    dh = cfg.head_dim
    p = f"{core}.layers.{{}}.{attn}."

    def split_qkv_w(i):
        w = flat[p.format(i) + "query_key_value.weight"]
        w = w.astype(dtype).reshape(int(H), 3, dh, D)
        return tuple(np.ascontiguousarray(
            w[:, j].reshape(int(H) * dh, D).T) for j in range(3))

    def split_qkv_b(i):
        b = flat[p.format(i) + "query_key_value.bias"]
        b = b.astype(dtype).reshape(int(H), 3, dh)
        return tuple(b[:, j].reshape(-1) for j in range(3))

    def stack(fmt):
        return np.stack([flat[fmt.format(i)].astype(dtype)
                         for i in range(L)])

    def stackT(fmt):
        return np.stack([np.ascontiguousarray(
            flat[fmt.format(i)].astype(dtype).T) for i in range(L)])

    qw, kw, vw = zip(*(split_qkv_w(i) for i in range(L)))
    qb, kb, vb = zip(*(split_qkv_b(i) for i in range(L)))
    lp = f"{core}.layers.{{}}."
    layers = {
        "attn": {
            "wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
            "wo": stackT(p + "dense.weight"),
            "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
            "bo": stack(p + "dense.bias"),
        },
        "ln1": {"scale": stack(lp + "input_layernorm.weight"),
                "bias": stack(lp + "input_layernorm.bias")},
        "ln2": {"scale": stack(lp + "post_attention_layernorm.weight"),
                "bias": stack(lp + "post_attention_layernorm.bias")},
        "mlp": {
            "wi": stackT(lp + "mlp.dense_h_to_4h.weight"),
            "bi": stack(lp + "mlp.dense_h_to_4h.bias"),
            "wo": stackT(lp + "mlp.dense_4h_to_h.weight"),
            "bo": stack(lp + "mlp.dense_4h_to_h.bias"),
        },
    }
    params: Params = {
        "embed": {"tokens": emb.astype(dtype), "pos": pos.astype(dtype)},
        "layers": layers,
        "final_norm": {
            "scale": flat[f"{core}.final_layernorm.weight"].astype(dtype),
            "bias": flat[f"{core}.final_layernorm.bias"].astype(dtype)},
    }
    if untied:
        params["lm_head"] = np.ascontiguousarray(
            flat["output_layer.weight"].astype(dtype).T)
    logger.info(f"loaded Megatron checkpoint from {path}: "
                f"{cfg.num_params() / 1e6:.1f}M params, {L} layers, "
                f"{attn} naming")
    return cfg, params
