"""Communication logging.

Equivalent of reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger``
:67) + the ``timed_op`` decorator (comm/comm.py:102). Under jit, per-op
wall-clock timing is meaningless (ops are fused and overlapped by XLA), so
the TPU logger records collectives at *trace time* (op, message size, axis,
dtype) and derives algorithmic bandwidth figures from step-level timing plus
the XLA cost model; `log_summary` mirrors the reference's table.
"""

import math
from collections import defaultdict
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


def convert_size(size_bytes: int) -> str:
    """Reference: utils/comms_logging.py:convert_size — hardened: a
    negative size (buggy caller, or a delta computed across a reset)
    used to crash in math.log; render it signed instead of taking the
    whole summary table down."""
    if size_bytes == 0:
        return "0B"
    if size_bytes < 0:
        return f"-{convert_size(-size_bytes)}"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = min(int(math.floor(math.log(size_bytes, 1024))), len(names) - 1)
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


#: ops whose algorithmic bandwidth factor is known (reference get_bw)
_KNOWN_MSG_OPS = frozenset((
    "all_reduce", "psum", "all_gather", "reduce_scatter", "all_to_all",
    "broadcast", "send", "recv", "barrier", "ppermute", "pmean"))
#: unrecognized op names seen so far (warned once, listing all of them)
_unknown_msg_ops: set = set()


def get_msg_size(op_name: str, size_bytes: int, world: int) -> int:
    """Algorithmic message size per rank for bandwidth accounting
    (reference utils/comms_logging.py:get_bw factor logic). An
    unrecognized op falls back to ``size_bytes`` (factor 1) — correct for
    point-to-point, an over-estimate for unknown collectives — and warns
    ONCE naming every unknown op seen so far, so a typo'd op name can't
    silently skew the doctor's bandwidth table forever."""
    if size_bytes < 0:
        raise ValueError(f"get_msg_size: negative size_bytes "
                         f"({size_bytes}) for op {op_name!r}")
    if op_name not in _KNOWN_MSG_OPS and op_name not in _unknown_msg_ops:
        _unknown_msg_ops.add(op_name)
        logger.warning(
            f"get_msg_size: unrecognized op {op_name!r} — using factor 1 "
            f"(raw bytes) for bandwidth accounting. Unknown ops so far: "
            f"{sorted(_unknown_msg_ops)}")
    if world <= 1:
        return size_bytes
    if op_name in ("all_reduce", "psum"):
        return int(size_bytes * 2 * (world - 1) / world)
    if op_name in ("all_gather", "reduce_scatter", "all_to_all"):
        return int(size_bytes * (world - 1) / world)
    return size_bytes


class CommsLogger:
    """Singleton registry of collective-call records."""

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops: List[str] = []
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(
            lambda: defaultdict(lambda: [0, 0.0]))  # op -> size -> [count, total_time]

    def configure(self, config) -> None:
        self.enabled = config.comms_logger.enabled
        self.verbose = config.comms_logger.verbose
        self.prof_all = config.comms_logger.prof_all
        self.prof_ops = list(config.comms_logger.prof_ops)

    def should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, size_bytes: int, axis: Any = None,
               time_sec: float = 0.0) -> None:
        if not self.should_log(op_name):
            return
        rec = self.comms_dict[op_name][size_bytes]
        rec[0] += 1
        rec[1] += time_sec
        # mirror into the unified telemetry spine: a trace-time instant
        # (these fire while jax traces, not while the collective runs —
        # ph='X' with a wall-clock dur would be a lie) plus byte counters
        from deepspeed_tpu.telemetry import registry, tracer
        tracer.instant(f"comm/{op_name}", bytes=size_bytes,
                       axis=str(axis) if axis is not None else None)
        registry.counter("comm/bytes",
                         help="bytes entering collectives (trace-time)"
                         ).inc(max(0, size_bytes))
        registry.counter(f"comm/{op_name}/calls").inc()
        if self.verbose:
            logger.info("comm op: %s | size: %s | axis: %s", op_name,
                        convert_size(size_bytes), axis)

    def append_chunked(self, op_name: str, size_bytes: int, axis: Any = None,
                       chunks: int = 1) -> None:
        """Record ``chunks`` same-sized collective calls in one go (the
        ZeRO-3 chunked-overlap path issues dozens of small per-chunk
        collectives per step — one record per chunk would flood the
        tracer ring and the log). Accounting stays EXACT: comms_dict
        counts every chunk and the byte counters accrue
        ``chunks × size_bytes`` (flight-recorder comm-bytes deltas are
        computed from these counters). At default verbosity the tracer
        gets ONE coalesced instant carrying the chunk count; under
        ``verbose`` the raw per-chunk instants + log lines come back."""
        if chunks <= 1:
            return self.append(op_name, size_bytes, axis)
        if not self.should_log(op_name):
            return
        rec = self.comms_dict[op_name][size_bytes]
        rec[0] += chunks
        from deepspeed_tpu.telemetry import registry, tracer
        registry.counter("comm/bytes",
                         help="bytes entering collectives (trace-time)"
                         ).inc(max(0, size_bytes) * chunks)
        registry.counter(f"comm/{op_name}/calls").inc(chunks)
        ax = str(axis) if axis is not None else None
        if self.verbose:
            for _ in range(chunks):
                tracer.instant(f"comm/{op_name}", bytes=size_bytes, axis=ax)
            logger.info("comm op: %s | size: %s | axis: %s | x%d chunks",
                        op_name, convert_size(size_bytes), axis, chunks)
        else:
            tracer.instant(f"comm/{op_name}", bytes=size_bytes * chunks,
                           axis=ax, chunks=chunks,
                           chunk_bytes=size_bytes)

    def reset(self) -> None:
        self.comms_dict.clear()

    def has_records(self, op_name: str) -> bool:
        return op_name in self.comms_dict

    def log_summary(self, show_straggler: bool = False) -> None:
        """Reference ``CommsLogger.log_all(show_straggler=...)``
        (utils/comms_logging.py:67, comm/comm.py:435): the straggler view
        gathers each process's per-op totals and splits a rank's time
        into TRANSMIT (the fastest rank's time — what the wire costs)
        and WAIT (everything above it — time spent blocked on slower
        ranks). One process degenerates to wait = 0 everywhere.

        COLLECTIVE under multi-process: ``show_straggler=True`` enters a
        process allgather, so EVERY process must make this call (a
        rank-0-only call would hang on the rendezvous) — same contract
        as the reference's dist.all_gather-based straggler table."""
        lines = [f"{'op':<18}{'size':>12}{'count':>8}{'total ms':>12}"]
        for op_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, total) in sorted(sizes.items()):
                lines.append(f"{op_name:<18}{convert_size(size):>12}"
                             f"{count:>8}{total * 1e3:>12.2f}")
        log_dist("\n".join(lines))
        if show_straggler:
            import jax
            all_ranks = _gather_comm_records(self._records_payload())
            log_dist("\n".join(straggler_rows(
                all_ranks, own_rank=jax.process_index())))

    def _records_payload(self) -> Dict[str, Dict[int, List[float]]]:
        return {op: {int(s): [int(c), float(t)]
                     for s, (c, t) in sizes.items()}
                for op, sizes in self.comms_dict.items()}


def straggler_rows(all_ranks: List[Dict[str, Dict[int, List[float]]]],
                   own_rank: int = 0) -> List[str]:
    """Pure straggler analysis over every rank's {op: {size: [count,
    total_sec]}} records → formatted table rows. For each (op, size):
    transmit = min total across ranks (what the collective itself
    costs); wait(rank) = own total − transmit (time blocked on
    stragglers); the max-total rank is named as the straggler."""
    rows = [f"{'op':<18}{'size':>12}{'min ms':>10}{'max ms':>10}"
            f"{'max rank':>10}{'own wait ms':>13}"]
    keys = sorted({(op, size) for r in all_ranks
                   for op, sizes in r.items() for size in sizes})
    for op, size in keys:
        # only ranks that actually RECORDED this (op, size) participate:
        # defaulting absentees to 0 would drive the transmit estimate to
        # zero and misattribute the whole time as wait
        present = [(i, r[op][size][1]) for i, r in enumerate(all_ranks)
                   if size in r.get(op, {})]
        totals = [t for _, t in present]
        t_min = min(totals)
        t_max = max(totals)
        max_rank = present[totals.index(t_max)][0]
        own = dict(present).get(own_rank)
        wait = (own - t_min) if own is not None else 0.0
        rows.append(f"{op:<18}{convert_size(size):>12}"
                    f"{t_min * 1e3:>10.2f}{t_max * 1e3:>10.2f}"
                    f"{max_rank:>10}"
                    f"{wait * 1e3:>13.2f}")
    return rows


def _gather_comm_records(payload) -> List[Dict]:
    """Allgather each process's records dict (JSON over fixed-width u8
    arrays — process_allgather needs equal shapes, so lengths go first).
    Single-process: just [payload]."""
    import jax
    if jax.process_count() == 1:
        return [payload]
    import json as _json
    import numpy as _np
    from jax.experimental import multihost_utils as mh
    raw = _json.dumps(payload, sort_keys=True).encode()
    lens = mh.process_allgather(_np.asarray([len(raw)], _np.int32))
    width = int(lens.max())
    buf = _np.zeros((width,), _np.uint8)
    buf[:len(raw)] = _np.frombuffer(raw, _np.uint8)
    bufs = mh.process_allgather(buf)
    out = []
    for i in range(bufs.shape[0]):
        n = int(lens.reshape(-1)[i])
        rec = _json.loads(bytes(bufs[i, :n]).decode())
        # JSON stringifies the int size keys — restore them
        out.append({op: {int(s): v for s, v in sizes.items()}
                    for op, sizes in rec.items()})
    return out


comms_logger = CommsLogger()
