"""Communication logging.

Equivalent of reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger``
:67) + the ``timed_op`` decorator (comm/comm.py:102). Under jit, per-op
wall-clock timing is meaningless (ops are fused and overlapped by XLA), so
the TPU logger records collectives at *trace time* (op, message size, axis,
dtype) and derives algorithmic bandwidth figures from step-level timing plus
the XLA cost model; `log_summary` mirrors the reference's table.
"""

import math
from collections import defaultdict
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


def convert_size(size_bytes: int) -> str:
    """Reference: utils/comms_logging.py:convert_size."""
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


def get_msg_size(op_name: str, size_bytes: int, world: int) -> int:
    """Algorithmic message size per rank for bandwidth accounting
    (reference utils/comms_logging.py:get_bw factor logic)."""
    if world <= 1:
        return size_bytes
    if op_name in ("all_reduce", "psum"):
        return int(size_bytes * 2 * (world - 1) / world)
    if op_name in ("all_gather", "reduce_scatter", "all_to_all"):
        return int(size_bytes * (world - 1) / world)
    return size_bytes


class CommsLogger:
    """Singleton registry of collective-call records."""

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops: List[str] = []
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(
            lambda: defaultdict(lambda: [0, 0.0]))  # op -> size -> [count, total_time]

    def configure(self, config) -> None:
        self.enabled = config.comms_logger.enabled
        self.verbose = config.comms_logger.verbose
        self.prof_all = config.comms_logger.prof_all
        self.prof_ops = list(config.comms_logger.prof_ops)

    def should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, size_bytes: int, axis: Any = None,
               time_sec: float = 0.0) -> None:
        if not self.should_log(op_name):
            return
        rec = self.comms_dict[op_name][size_bytes]
        rec[0] += 1
        rec[1] += time_sec
        if self.verbose:
            logger.info("comm op: %s | size: %s | axis: %s", op_name,
                        convert_size(size_bytes), axis)

    def reset(self) -> None:
        self.comms_dict.clear()

    def has_records(self, op_name: str) -> bool:
        return op_name in self.comms_dict

    def log_summary(self) -> None:
        lines = [f"{'op':<18}{'size':>12}{'count':>8}{'total ms':>12}"]
        for op_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, total) in sorted(sizes.items()):
                lines.append(f"{op_name:<18}{convert_size(size):>12}"
                             f"{count:>8}{total * 1e3:>12.2f}")
        log_dist("\n".join(lines))


comms_logger = CommsLogger()
