"""deepspeed_tpu.comm — the communication facade.

TPU-native equivalent of the reference's ``deepspeed.comm`` module
(deepspeed/comm/comm.py: ``init_distributed``:788, ``all_reduce``:641,
``all_gather_into_tensor``:310, ``reduce_scatter_tensor``:293,
``all_to_all_single``:344, ``barrier``:419). Two layers:

1. **Process-level** (multi-host TPU pods): ``init_distributed`` wraps
   ``jax.distributed.initialize`` — the rendezvous that the reference does
   via torch.distributed.init_process_group (comm/torch.py:148). Rank ==
   jax process index; world == process count.

2. **Device-level collectives**: thin wrappers over ``jax.lax`` collectives
   (psum/all_gather/psum_scatter/all_to_all/ppermute) that (a) are valid
   inside ``shard_map`` over a named mesh axis and (b) register themselves
   with the CommsLogger at trace time. Outside shard_map, the eager-mode
   fallbacks operate on global arrays via device_put + resharding so unit
   tests can call them directly.

There is no NCCL analogue to manage: XLA lowers these to ICI/DCN
collectives, choosing algorithms per topology. The Backend abstraction of
the reference (comm/backend.py) collapses to this single XLA backend; a
``compressed`` backend for 1-bit optimizers lives in
deepspeed_tpu/comm/compressed.py.
"""

import os
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comms_logger import comms_logger
from deepspeed_tpu.utils.logging import log_dist, logger

_INITIALIZED = False


# ---------------------------------------------------------------------------
# Process-level API
# ---------------------------------------------------------------------------

def init_distributed(dist_backend: str = "ici",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True,
                     timeout: Optional[int] = None,
                     **_: Any) -> None:
    """Initialize multi-host communication (reference comm/comm.py:788).

    Single-host (or already-initialized) is a no-op. Multi-host coordinates
    through ``jax.distributed.initialize``; env-var discovery mirrors the
    reference's MPI/launcher env patching (comm.py:857-949) but reads the
    TPU-VM / launcher variables (COORDINATOR_ADDRESS, NUM_PROCESSES,
    PROCESS_ID) that deepspeed_tpu's launcher exports.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None and "DSTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DSTPU_NUM_PROCESSES"])
    if process_id is None and "DSTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DSTPU_PROCESS_ID"])
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        log_dist(f"jax.distributed initialized: "
                 f"{jax.process_index()}/{jax.process_count()} processes")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank() -> int:
    """Process rank (reference comm.py:705 — but device-granular ranks only
    exist inside shard_map on TPU; use lax.axis_index there)."""
    return jax.process_index()


def get_world_size() -> int:
    """Total device count (the reference's world == device count since it
    runs one process per GPU)."""
    return jax.device_count()


def get_local_rank() -> int:
    return jax.process_index()


def barrier() -> None:
    """Reference comm.py:419. On jax: round-trip a tiny psum across all
    devices and block."""
    x = jnp.zeros((), jnp.int32)
    jax.block_until_ready(
        jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(
            jnp.zeros((jax.local_device_count(),), jnp.int32)))
    del x


# ---------------------------------------------------------------------------
# Device-level collectives (valid inside shard_map; log at trace time)
# ---------------------------------------------------------------------------

AxisName = Union[str, Sequence[str]]


def _timed(op: str, x: jax.Array, axis: AxisName, run) -> jax.Array:
    """Register the collective with the CommsLogger, and — on the
    synchronous path in verbose mode — record its MEASURED wall time so
    the goodput ledger's ``comm_exposed`` attribution has a ground-truth
    cross-check against the roofline estimate. Inside shard_map/pmap
    ``x`` is an abstract tracer: timing a trace-time call would clock
    XLA's lowering, not the collective, so those register untimed (the
    roofline remains the estimate there). The timed path blocks on the
    result, which the synchronous eager semantics already imply."""
    try:
        size = x.size * x.dtype.itemsize
    except Exception:
        size = 0
    if not (comms_logger.verbose and comms_logger.should_log(op)) \
            or isinstance(x, jax.core.Tracer):
        comms_logger.append(op, size, axis)
        return run()
    from deepspeed_tpu.telemetry.tracer import tracer
    t0 = tracer.now()
    try:
        out = jax.block_until_ready(run())
    except Exception:
        comms_logger.append(op, size, axis)
        raise
    t1 = tracer.now()
    comms_logger.append(op, size, axis, time_sec=t1 - t0)
    tracer.complete(f"comm/{op}", t0, t1, bytes=size)
    return out


def _log(op: str, x: jax.Array, axis: AxisName) -> None:
    try:
        size = x.size * x.dtype.itemsize
    except Exception:
        size = 0
    comms_logger.append(op, size, axis)


def all_reduce(x: jax.Array, axis_name: AxisName, op: str = "sum") -> jax.Array:
    """Reference comm.py:641 (all_reduce). Inside shard_map/pmap only."""
    if op == "sum":
        return _timed("all_reduce", x, axis_name,
                      lambda: lax.psum(x, axis_name))
    if op == "mean":
        return _timed("all_reduce", x, axis_name,
                      lambda: lax.pmean(x, axis_name))
    if op == "max":
        return _timed("all_reduce", x, axis_name,
                      lambda: lax.pmax(x, axis_name))
    if op == "min":
        return _timed("all_reduce", x, axis_name,
                      lambda: lax.pmin(x, axis_name))
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x: jax.Array, axis_name: AxisName, axis: int = 0,
               tiled: bool = True) -> jax.Array:
    """Reference comm.py:310 (all_gather_into_tensor)."""
    return _timed("all_gather", x, axis_name,
                  lambda: lax.all_gather(x, axis_name, axis=axis,
                                         tiled=tiled))


def reduce_scatter(x: jax.Array, axis_name: AxisName, axis: int = 0,
                   tiled: bool = True) -> jax.Array:
    """Reference comm.py:293 (reduce_scatter_tensor) — the ZeRO-2 hot path
    (stage_1_and_2.py:average_tensor:1184)."""
    return _timed("reduce_scatter", x, axis_name,
                  lambda: lax.psum_scatter(x, axis_name,
                                           scatter_dimension=axis,
                                           tiled=tiled))


def all_to_all(x: jax.Array, axis_name: AxisName, split_axis: int,
               concat_axis: int, tiled: bool = True) -> jax.Array:
    """Reference comm.py:344 (all_to_all_single) — the Ulysses/MoE hot path
    (sequence/layer.py:single_all_to_all:221, moe/sharded_moe.py:_AllToAll:96)."""
    return _timed("all_to_all", x, axis_name,
                  lambda: lax.all_to_all(x, axis_name, split_axis=split_axis,
                                         concat_axis=concat_axis,
                                         tiled=tiled))


def ppermute(x: jax.Array, axis_name: AxisName, perm) -> jax.Array:
    """Point-to-point ring shift (reference pipe/p2p.py send/recv analogue,
    expressed as a collective permute so XLA can pipeline it on ICI)."""
    return _timed("ppermute", x, axis_name,
                  lambda: lax.ppermute(x, axis_name, perm))


def send_recv_next(x: jax.Array, axis_name: AxisName, world: int) -> jax.Array:
    """Shift activations to the next pipeline stage (reference p2p.py:46,67)."""
    perm = [(i, (i + 1) % world) for i in range(world)]
    return ppermute(x, axis_name, perm)


def send_recv_prev(x: jax.Array, axis_name: AxisName, world: int) -> jax.Array:
    perm = [(i, (i - 1) % world) for i in range(world)]
    return ppermute(x, axis_name, perm)


def axis_index(axis_name: AxisName) -> jax.Array:
    """Device rank along a mesh axis (reference get_rank(group=...))."""
    return lax.axis_index(axis_name)


def log_summary(show_straggler: bool = False) -> None:
    """Reference comm.py:435 (log_summary): ``show_straggler`` gathers
    per-process op timings and prints the cross-rank min/max split into
    transmit vs wait time (utils/comms_logging.py:67). With
    ``show_straggler`` this is a COLLECTIVE under multi-process — every
    process must call it, not just rank 0."""
    comms_logger.log_summary(show_straggler=show_straggler)
