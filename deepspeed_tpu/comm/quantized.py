"""ZeRO++ quantized collectives — qwZ / qgZ over ICI/DCN.

Reference: quantized weight allgather (qwZ — runtime/zero/stage3.py:1636
``quantize_nontrainable_params`` + ``AllGatherCoalescedHandle`` quantized
path, csrc/quantization/quantize.cu) and hierarchical quantized gradient
reduce (qgZ — runtime/comm/coalesced_collectives.py
``all_to_all_quant_reduce``, blogs/zeropp: 4× allgather + grad traffic
reduction).

TPU mapping: block-quantize locally (ops/quantizer.py), move int8/int4
bytes with ``lax.all_gather``/``lax.all_to_all`` inside shard_map (XLA
routes them over ICI, or DCN for the outer axis of the hierarchical
reduce), dequantize after landing. The hierarchical qgZ pattern —
all-to-all + reduce *within* a slice first, then across slices — rides the
cheap axis for the big tensors exactly like the reference rides NVLink
before InfiniBand.

All functions are shard_map-valid (static shapes, no host sync) and log
through the CommsLogger.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comms_logger import comms_logger
from deepspeed_tpu.ops.quantizer import (DEFAULT_BLOCK, dequantize_blocks,
                                         quantize_blocks)


def _pad_to(x: jax.Array, multiple: int) -> Tuple[jax.Array, int]:
    n = x.shape[0]
    pad = (-n) % multiple
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x, n


def quantized_all_gather(x: jax.Array, axis_name: str,
                         block: int = DEFAULT_BLOCK, bits: int = 8,
                         dtype=None) -> jax.Array:
    """qwZ: allgather a shard in int8/int4 + per-block fp32 scales.

    x: this device's flat shard [n]. Returns [world * n] in ``dtype``
    (default x.dtype). Traffic: n bytes (int8) vs 2n (bf16) / 4n (fp32),
    plus n/block scales.
    """
    dtype = dtype or x.dtype
    xp, n = _pad_to(x.reshape(-1), block)
    q, s, _ = quantize_blocks(xp, block=block, bits=bits)
    comms_logger.append("quantized_all_gather", q.nbytes + s.nbytes,
                        axis_name)
    qg = lax.all_gather(q, axis_name)            # [world, npad/(8/bits)]
    sg = lax.all_gather(s, axis_name)            # [world, npad/block]
    deq = jax.vmap(lambda qq, ss: dequantize_blocks(
        qq, ss, block=block, bits=bits, dtype=dtype))(qg, sg)
    return deq[:, :n].reshape(-1)


def quantized_reduce_scatter(x: jax.Array, axis_name: str,
                             block: int = DEFAULT_BLOCK, bits: int = 8,
                             mean: bool = True) -> jax.Array:
    """qgZ (single hop): quantized all-to-all + local reduce.

    x: full-size flat local gradient [n] (n divisible by world). Chunk i of
    every device lands on device i (int8/4 traffic), is dequantized and
    reduced there. Returns this device's reduced chunk [n / world].
    """
    world = lax.psum(1, axis_name)
    xp, n = _pad_to(x.reshape(-1), block * world)
    chunks = xp.reshape(world, -1)               # [world, c]
    q, s, _ = jax.vmap(lambda c: quantize_blocks(c, block=block,
                                                 bits=bits))(chunks)
    comms_logger.append("quantized_reduce_scatter", q.nbytes + s.nbytes,
                        axis_name)
    qr = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                        tiled=True).reshape(world, -1)
    sr = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                        tiled=True).reshape(world, -1)
    deq = jax.vmap(lambda qq, ss: dequantize_blocks(
        qq, ss, block=block, bits=bits))(qr, sr)     # [world, c]
    red = deq.mean(axis=0) if mean else deq.sum(axis=0)
    c = xp.shape[0] // world
    # callers must slice padding off the LAST device's chunk; with n
    # divisible by world there is none
    del n
    return red[:c]


def all_to_all_quant_reduce(x: jax.Array, inner_axis: str,
                            outer_axis: Optional[str] = None,
                            block: int = DEFAULT_BLOCK,
                            inner_bits: int = 8, outer_bits: int = 4
                            ) -> jax.Array:
    """qgZ hierarchical reduce (reference coalesced_collectives.py
    ``all_to_all_quant_reduce``): reduce over the cheap ``inner_axis``
    (ICI / intra-slice) at ``inner_bits`` first — shrinking the tensor by
    the inner world size — then over ``outer_axis`` (DCN / cross-slice) at
    the more aggressive ``outer_bits``. Returns this device's chunk
    [n / (inner_world * outer_world)].

    Chunk placement is INNER-axis-major: the device at (inner=i, outer=o)
    holds the flat segment (i * outer_world + o) — reassembly needs
    out_specs ``P((inner, outer))`` ordering (the reference's qgZ has the
    same post-reduce layout contract, coalesced_collectives.py).
    """
    local = quantized_reduce_scatter(x, inner_axis, block=block,
                                     bits=inner_bits)
    if outer_axis is None:
        return local
    return quantized_reduce_scatter(local, outer_axis, block=block,
                                    bits=outer_bits)
