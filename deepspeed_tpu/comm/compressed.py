"""Compressed (1-bit error-feedback) collectives.

TPU-native re-design of the reference's compressed backends
(runtime/comm/nccl.py:52 ``compressed_allreduce``, runtime/comm/
compressed.py:58, cupy packbits in runtime/compression/cupy.py). The
algorithm is the 1-bit Adam exchange: every worker sends only the SIGN of
its (error-compensated) tensor plus one fp32 scale, a "server" stage
averages and re-compresses with its own error feedback, and the result is
broadcast back — 32× less traffic than an fp32 allreduce, with both error
buffers guaranteeing the residual is re-injected next step.

Mapping to TPU: the reference's torch.distributed all-to-all/allgather over
packed cupy bits become ``lax.all_to_all``/``lax.all_gather`` over packed
uint8 sign arrays inside ``shard_map``; XLA routes them over ICI/DCN. Bit
packing is a reshape+dot on device (no cupy/CPU round-trip).

All functions are shard_map/jit compatible (static shapes, no Python
branches on traced values).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comms_logger import comms_logger

import numpy as _np
#: numpy constant — a jnp array here would initialize the JAX backend at
#: import time, pinning the platform before drivers can set XLA_FLAGS
_POWERS = (2 ** _np.arange(8, dtype=_np.uint16)).astype(_np.uint8)


def pack_signs(x: jax.Array) -> jax.Array:
    """f32[n] (n % 8 == 0) → uint8[n/8]; bit k of byte j = sign(x[8j+k])>=0."""
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    return (bits * _POWERS).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jax.Array) -> jax.Array:
    """uint8[m] → f32[8m] of ±1."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def _compress(x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """x → (packed signs, scale, new error). scale = mean|x| preserves the
    l1 norm (the reference's scale choice, nccl.py:92)."""
    scale = jnp.mean(jnp.abs(x))
    packed = pack_signs(x)
    decompressed = scale * unpack_signs(packed)
    return packed, scale, x - decompressed


def compressed_allreduce(x: jax.Array,
                         worker_error: jax.Array,
                         server_error: jax.Array,
                         axis_name: str) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """1-bit error-feedback allreduce (mean) along a mesh axis.

    Must run inside shard_map. ``x`` is this worker's flat f32 tensor whose
    length is divisible by 8 × axis size (pad upstream; see
    :func:`padded_size`). ``worker_error``/``server_error`` have shapes
    [n] and [n / world] respectively.

    Returns (averaged tensor [n], new worker_error, new server_error).
    """
    world = lax.psum(1, axis_name)
    n = x.shape[0]
    comms_logger.append("compressed_allreduce", n // 8 + 4, axis_name)

    # -- worker phase: compensate, compress, record residual --------------
    compensated = x + worker_error
    packed, scale, new_worker_error = _compress(compensated)

    # -- exchange: chunk i of every worker lands on worker i --------------
    # packed: [n/8] → [world, n/(8*world)]; all_to_all swaps the leading
    # chunk axis for the worker axis (reference: dist.all_to_all_single)
    chunks = packed.reshape(world, -1)
    recv = lax.all_to_all(chunks, axis_name, split_axis=0, concat_axis=0,
                          tiled=True).reshape(world, -1)      # [world, n/8w]
    scales = lax.all_gather(scale, axis_name)                 # [world]

    # -- server phase: decompress, average, re-compress w/ server error --
    signs = jax.vmap(unpack_signs)(recv)                      # [world, n/w]
    avg = (scales[:, None] * signs).mean(axis=0)              # [n/world]
    compensated_s = avg + server_error
    packed_s, scale_s, new_server_error = _compress(compensated_s)

    # -- broadcast: gather every server's compressed chunk ----------------
    all_packed = lax.all_gather(packed_s, axis_name)              # [world, n/8w]
    all_scales = lax.all_gather(scale_s, axis_name)               # [world]
    out = (all_scales[:, None] *
           jax.vmap(unpack_signs)(all_packed)).reshape(n)
    return out, new_worker_error, new_server_error


def padded_size(n: int, world: int) -> int:
    """Smallest length ≥ n divisible by 8 × world (pack + chunk granularity)."""
    q = 8 * world
    return ((n + q - 1) // q) * q


def init_error_buffers(n: int, world: int) -> Tuple[jax.Array, jax.Array]:
    """Zero-initialized (worker_error, server_error) for a padded length n."""
    assert n % (8 * world) == 0, f"{n} not divisible by 8*{world}"
    return jnp.zeros((n,), jnp.float32), jnp.zeros((n // world,), jnp.float32)
