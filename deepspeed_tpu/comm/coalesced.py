"""Coalesced collectives — one launch for many tensors.

Reference: ``runtime/comm/coalesced_collectives.py``
(``reduce_scatter_coalesced``: flattens a tensor list into per-rank
contiguous partitions and issues ONE reduce-scatter;
``all_to_all_quant_reduce`` lives in comm/quantized.py here). On TPU the
latency win is the same: many small collectives serialize on ICI launch
overhead, one big flat collective streams at line rate. XLA sometimes
fuses adjacent collectives itself, but an explicit coalesce is
deterministic — this is the bucketing knob ``reduce_bucket_size`` /
``allgather_bucket_size`` map to.

All functions are shard_map-valid.
"""

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.comms_logger import comms_logger


def _flatten(tensors: Sequence[jax.Array], pad_to: int
             ) -> Tuple[jax.Array, List[Tuple[Tuple[int, ...], int]]]:
    """Reductions run in fp32; int/fp64 inputs would silently round-trip
    through fp32 and corrupt (e.g. int32 ids > 2^24) — reject them."""
    for t in tensors:
        if not jnp.issubdtype(t.dtype, jnp.floating) or \
                t.dtype == jnp.float64:
            raise TypeError(
                f"coalesced collectives take inexact ≤32-bit dtypes "
                f"(got {t.dtype}); gather ints per-tensor instead")
    metas = [(t.shape, int(jnp.size(t))) for t in tensors]
    flat = jnp.concatenate([t.reshape(-1).astype(jnp.float32)
                            for t in tensors])
    pad = (-flat.shape[0]) % pad_to
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat, metas


def _unflatten(flat: jax.Array, metas, dtypes) -> List[jax.Array]:
    out, off = [], 0
    for (shape, size), dt in zip(metas, dtypes):
        out.append(lax.dynamic_slice_in_dim(flat, off, size)
                   .reshape(shape).astype(dt))
        off += size
    return out


def reduce_scatter_coalesced(tensors: Sequence[jax.Array], axis_name: str,
                             mean: bool = True) -> jax.Array:
    """Flatten → ONE tiled reduce-scatter → this device's flat chunk
    (reference reduce_scatter_coalesced). The chunk stays flat — ZeRO
    keeps flat partitions; unflatten happens at consumption."""
    world = lax.psum(1, axis_name)
    flat, _ = _flatten(tensors, world)
    comms_logger.append("reduce_scatter_coalesced", flat.nbytes, axis_name)
    out = lax.psum_scatter(flat, axis_name, tiled=True)
    return out / world if mean else out


def all_reduce_coalesced(tensors: Sequence[jax.Array], axis_name: str,
                         mean: bool = True) -> List[jax.Array]:
    """Flatten → ONE psum → unflatten (reference engine
    buffered_allreduce_fallback:3007 bucketing)."""
    world = lax.psum(1, axis_name)
    flat, metas = _flatten(tensors, 1)
    comms_logger.append("all_reduce_coalesced", flat.nbytes, axis_name)
    red = lax.psum(flat, axis_name)
    if mean:
        red = red / world
    return _unflatten(red, metas, [t.dtype for t in tensors])


def all_gather_coalesced(tensors: Sequence[jax.Array], axis_name: str
                         ) -> List[jax.Array]:
    """Flatten local shards → ONE all_gather → per-tensor full arrays,
    where each input is this device's equal shard of the corresponding
    output's LEADING dim (reference allgather_bucket path)."""
    world = lax.psum(1, axis_name)
    flat, metas = _flatten(tensors, 1)
    comms_logger.append("all_gather_coalesced", flat.nbytes, axis_name)
    gat = lax.all_gather(flat, axis_name)            # [world, n]
    out = []
    off = 0
    for (shape, size), t in zip(metas, tensors):
        piece = lax.dynamic_slice_in_dim(gat, off, size, axis=1)
        out.append(piece.reshape((world * shape[0],) + tuple(shape[1:]))
                   .astype(t.dtype))
        off += size
    return out
