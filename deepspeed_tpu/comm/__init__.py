from deepspeed_tpu.comm.comm import (
    all_gather,
    all_reduce,
    all_to_all,
    axis_index,
    barrier,
    get_local_rank,
    get_rank,
    get_world_size,
    init_distributed,
    is_initialized,
    log_summary,
    ppermute,
    reduce_scatter,
    send_recv_next,
    send_recv_prev,
)
from deepspeed_tpu.comm.comms_logger import comms_logger

__all__ = [
    "init_distributed", "is_initialized", "get_rank", "get_world_size",
    "get_local_rank", "barrier", "all_reduce", "all_gather",
    "reduce_scatter", "all_to_all", "ppermute", "send_recv_next",
    "send_recv_prev", "axis_index", "comms_logger", "log_summary",
]
