"""Collective micro-benchmark sweep — the ``ds_bench`` analogue.

Reference: ``bin/ds_bench`` driving ``benchmarks/communication/run_all.py``
(all_reduce/all_gather/all_to_all/pt2pt/broadcast over a size sweep, with
algorithm- and bus-bandwidth columns). The TPU-native version times XLA
collectives (`psum`, `all_gather`, `reduce_scatter`, `all_to_all`,
`ppermute`) inside a jitted ``shard_map`` over the active mesh axis, so
what is measured is exactly what the training engine runs on ICI/DCN.

Bus-bandwidth factors follow the standard ring-collective accounting
(nccl-tests / reference utils.py:max_numel):
  allreduce       busbw = algbw * 2(n-1)/n
  allgather       busbw = algbw * (n-1)/n    (algbw over the FULL tensor)
  reducescatter   busbw = algbw * (n-1)/n
  alltoall        busbw = algbw * (n-1)/n
  ppermute (p2p)  busbw = algbw
"""

import time
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from deepspeed_tpu.parallel import mesh as mesh_lib

_OPS = ("allreduce", "allgather", "reducescatter", "alltoall", "ppermute")


def _collective_fn(op: str, axis: str, n: int):
    if op == "allreduce":
        return lambda x: jax.lax.psum(x, axis)
    if op == "allgather":
        return lambda x: jax.lax.all_gather(x, axis, tiled=True)
    if op == "reducescatter":
        return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
    if op == "alltoall":
        return lambda x: jax.lax.all_to_all(
            x.reshape(n, -1), axis, split_axis=0, concat_axis=0).reshape(-1)
    if op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lambda x: jax.lax.ppermute(x, axis, perm)
    raise ValueError(f"unknown op {op!r}; expected one of {_OPS}")


def _busbw_factor(op: str, n: int) -> float:
    if op == "allreduce":
        return 2.0 * (n - 1) / n
    if op in ("allgather", "reducescatter", "alltoall"):
        return (n - 1) / n
    return 1.0  # ppermute: point-to-point


def bench_collective(op: str, numel: int, mesh: Optional[Mesh] = None,
                     axis: str = "data", dtype=jnp.bfloat16,
                     warmup: int = 2, trials: int = 10) -> dict:
    """Time one collective at one size; returns a result row dict.

    ``numel`` is the PER-DEVICE element count of the input shard (the
    reference sweeps per-rank buffer sizes the same way).
    """
    mesh = mesh or mesh_lib.get_mesh()
    n = mesh.shape[axis]
    if op == "alltoall":  # per-device shard reshapes to (n, -1)
        numel = max(n, -(-numel // n) * n)
    fn = _collective_fn(op, axis, n)
    mapped = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))

    x = jax.device_put(
        jnp.zeros((numel * n,), dtype=dtype),
        jax.sharding.NamedSharding(mesh, P(axis)))
    for _ in range(warmup):
        jax.block_until_ready(mapped(x))
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.block_until_ready(mapped(x))
        times.append(time.perf_counter() - t0)
    t = float(np.min(times))  # min over trials: steady-state, no host jitter
    itemsize = jnp.dtype(dtype).itemsize
    # algbw convention (nccl-tests): full logical tensor size / time for
    # gather-type ops, per-shard size for permute
    size_bytes = numel * n * itemsize if op != "ppermute" else numel * itemsize
    algbw = size_bytes / t / 1e9
    return {"op": op, "world": n, "axis": axis,
            "numel_per_device": numel, "dtype": str(jnp.dtype(dtype)),
            "size_mb": size_bytes / 2**20, "time_ms": t * 1e3,
            "algbw_gbps": algbw,
            "busbw_gbps": algbw * _busbw_factor(op, n)}


def run_sweep(ops=_OPS, mesh: Optional[Mesh] = None, axis: str = "data",
              min_numel: int = 1 << 10, max_numel: int = 1 << 24,
              dtype=jnp.bfloat16, trials: int = 10) -> List[dict]:
    """Power-of-two size sweep over the requested collectives."""
    mesh = mesh or mesh_lib.get_mesh()
    rows = []
    for op in ops:
        numel = min_numel
        while numel <= max_numel:
            rows.append(bench_collective(op, numel, mesh=mesh, axis=axis,
                                         dtype=dtype, trials=trials))
            numel <<= 2
    return rows


def format_table(rows: List[dict]) -> str:
    hdr = (f"{'op':<14}{'world':>6}{'size(MB)':>10}{'time(ms)':>10}"
           f"{'algbw(GB/s)':>13}{'busbw(GB/s)':>13}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        lines.append(
            f"{r['op']:<14}{r['world']:>6}{r['size_mb']:>10.2f}"
            f"{r['time_ms']:>10.3f}{r['algbw_gbps']:>13.2f}"
            f"{r['busbw_gbps']:>13.2f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import json

    from deepspeed_tpu.utils.platform import sync_jax_platform_env
    sync_jax_platform_env()

    parser = argparse.ArgumentParser(
        prog="dstpu_bench_comm",
        description="collective bandwidth sweep over the device mesh "
                    "(reference: bin/ds_bench)")
    parser.add_argument("--ops", nargs="+", default=list(_OPS),
                        choices=list(_OPS))
    parser.add_argument("--axis", default="data")
    parser.add_argument("--devices", type=int, default=0,
                        help="mesh size (default: all visible devices)")
    parser.add_argument("--min-mb", type=float, default=0.0625)
    parser.add_argument("--max-mb", type=float, default=64.0)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--trials", type=int, default=10)
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line per row instead of a table")
    args = parser.parse_args(argv)

    devs = jax.devices()
    n = args.devices or len(devs)
    mesh = mesh_lib.build_mesh(**{args.axis: n}, devices=devs[:n])
    itemsize = jnp.dtype(args.dtype).itemsize
    # interpret --min/max-mb as the full logical tensor size
    min_numel = max(1, int(args.min_mb * 2**20 / itemsize / n))
    max_numel = max(min_numel, int(args.max_mb * 2**20 / itemsize / n))
    rows = run_sweep(ops=args.ops, mesh=mesh, axis=args.axis,
                     min_numel=min_numel, max_numel=max_numel,
                     dtype=jnp.dtype(args.dtype), trials=args.trials)
    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        print(format_table(rows))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
