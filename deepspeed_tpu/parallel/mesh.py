"""Device-mesh topology for deepspeed_tpu.

TPU-native replacement for the reference's process-group factory
(``deepspeed/utils/groups.py`` — ``_create_model_parallel``:191,
``_create_expert_and_data_parallel``:240, sequence accessors:642) and the
pipeline rank grid (``runtime/pipe/topology.py:ProcessTopology``). Instead of
building torch.distributed groups per parallelism flavor, we build ONE
``jax.sharding.Mesh`` whose named axes are the parallelism dimensions; every
"group" of the reference becomes an axis name usable in PartitionSpecs and
collectives.

Axis order (outermost → innermost) is chosen for ICI locality: tensor
('model') collectives are the most latency-sensitive so the model axis maps
to adjacent chips; 'pipe' is outermost since pipeline P2P tolerates DCN.
"""

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist, logger

#: canonical axis order, outermost first. 'data_inner' is the MiCS / hpZ
#: sub-group axis (reference runtime/zero/mics.py:63): when its size > 1,
#: ZeRO-3 param shards live WITHIN a (data_inner × expert) sub-group and
#: replicate across 'data' — param allgathers ride the cheap inner links
#: while gradients still reduce across the full DP product. Size 1 (the
#: default) collapses it to plain ZeRO.
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "data_inner", "expert",
                              "seq", "model")

#: ZeRO shards over the full data-parallel product (reference groups.py
#: expert-data parallel design)
ZERO_AXES: Tuple[str, ...] = ("data", "data_inner", "expert")

#: MiCS/hpZ sub-group axes — stage-3 param sharding when mics_shard_size>1
MICS_AXES: Tuple[str, ...] = ("data_inner", "expert")

_CURRENT_MESH: Optional[Mesh] = None


def build_mesh(data: Optional[int] = None,
               model: int = 1,
               pipe: int = 1,
               seq: int = 1,
               expert: int = 1,
               data_inner: int = 1,
               devices: Optional[Sequence[jax.Device]] = None,
               set_current: bool = True) -> Mesh:
    """Build the framework mesh.

    ``data=None`` infers the data-parallel degree from the device count
    (reference analogue: world_size / (tp×pp×sp×ep)). ``data_inner`` is
    the MiCS/hpZ sub-group size (divides the total DP degree).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = model * pipe * seq * expert * data_inner
    if data is None:
        if n % fixed:
            raise ValueError(
                f"device count {n} not divisible by "
                f"model×pipe×seq×expert×data_inner={fixed}")
        data = n // fixed
    total = data * fixed
    if total != n:
        raise ValueError(
            f"mesh axes product {total} != device count {n} "
            f"(pipe={pipe} data={data} data_inner={data_inner} "
            f"expert={expert} seq={seq} model={model})")
    arr = np.array(devices[:total]).reshape(pipe, data, data_inner,
                                            expert, seq, model)
    mesh = Mesh(arr, MESH_AXES)
    if set_current:
        set_mesh(mesh)
    log_dist(f"built mesh: pipe={pipe} data={data} "
             f"data_inner={data_inner} expert={expert} "
             f"seq={seq} model={model}")
    return mesh


def mesh_from_config(config, devices=None) -> Mesh:
    """Build a mesh from a DeepSpeedTPUConfig's parallel-topology knobs."""
    return build_mesh(
        model=config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1,
        pipe=config.pipeline.stages,
        seq=config.sequence_parallel.size,
        expert=config.moe.ep_size if config.moe.enabled else 1,
        data_inner=max(int(config.zero_optimization.mics_shard_size or 1),
                       1),
        devices=devices,
    )


def set_mesh(mesh: Mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_mesh() -> Mesh:
    if _CURRENT_MESH is None:
        raise RuntimeError("no mesh set; call build_mesh() or "
                           "deepspeed_tpu.initialize() first")
    return _CURRENT_MESH


def has_mesh() -> bool:
    return _CURRENT_MESH is not None


# ---------------------------------------------------------------------------
# Group accessors — API parity with reference deepspeed/utils/groups.py, but
# returning axis names/sizes instead of torch process groups.
# ---------------------------------------------------------------------------

def _axis_size(mesh: Optional[Mesh], axis: str) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape[axis]


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """DP degree for non-expert params = data × data_inner × expert
    (reference groups.py:_get_data_parallel_world_size with expert
    interleaving)."""
    mesh = mesh or get_mesh()
    return mesh.shape["data"] * mesh.shape["data_inner"] * \
        mesh.shape["expert"]


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "model")


def get_pipe_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "pipe")


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "seq")


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "expert")


def get_world_size() -> int:
    """Devices in the active mesh (NOT jax.device_count(): a sub-mesh —
    e.g. dryrun over devices[:n] — must report its own size)."""
    if _CURRENT_MESH is not None:
        return _CURRENT_MESH.size
    return jax.device_count()


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())
