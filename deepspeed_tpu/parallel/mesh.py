"""Device-mesh topology for deepspeed_tpu.

TPU-native replacement for the reference's process-group factory
(``deepspeed/utils/groups.py`` — ``_create_model_parallel``:191,
``_create_expert_and_data_parallel``:240, sequence accessors:642) and the
pipeline rank grid (``runtime/pipe/topology.py:ProcessTopology``). Instead of
building torch.distributed groups per parallelism flavor, we build ONE
``jax.sharding.Mesh`` whose named axes are the parallelism dimensions; every
"group" of the reference becomes an axis name usable in PartitionSpecs and
collectives.

Axis order (outermost → innermost) is chosen for ICI locality: tensor
('model') collectives are the most latency-sensitive so the model axis maps
to adjacent chips; 'pipe' is outermost since pipeline P2P tolerates DCN.
"""

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist, logger

#: canonical axis order, outermost first. 'data_inner' is the MiCS / hpZ
#: sub-group axis (reference runtime/zero/mics.py:63): when its size > 1,
#: ZeRO-3 param shards live WITHIN a (data_inner × expert) sub-group and
#: replicate across 'data' — param allgathers ride the cheap inner links
#: while gradients still reduce across the full DP product. Size 1 (the
#: default) collapses it to plain ZeRO.
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "data_inner", "expert",
                              "seq", "model")

#: ZeRO shards over the full data-parallel product (reference groups.py
#: expert-data parallel design)
ZERO_AXES: Tuple[str, ...] = ("data", "data_inner", "expert")

#: MiCS/hpZ sub-group axes — stage-3 param sharding when mics_shard_size>1
MICS_AXES: Tuple[str, ...] = ("data_inner", "expert")

_CURRENT_MESH: Optional[Mesh] = None


def _hybrid_device_array(devices, shape: Dict[str, int],
                         dcn: Dict[str, int],
                         slice_ids: Sequence[int]) -> np.ndarray:
    """Lay devices out so DCN (cross-slice) hops land ONLY on the axes
    named in ``dcn`` — the multi-slice analogue of the reference's
    node-local hierarchy (MiCS hpZ sub-groups, hierarchical allgather).

    Each axis of size S with a DCN factor f splits into f outer (slice-
    crossing) blocks of S/f ICI-contiguous indices; axes without a DCN
    factor stay entirely within one slice, so their collectives never
    touch the data-center network.
    """
    order = sorted(set(slice_ids))
    n_slices = len(order)
    groups = {s: [d for d, sid in zip(devices, slice_ids) if sid == s]
              for s in order}
    sizes = {len(g) for g in groups.values()}
    if len(sizes) != 1:
        raise ValueError(f"uneven slices: {sorted(sizes)} devices/slice")
    dcn_shape = tuple(dcn.get(ax, 1) for ax in MESH_AXES)
    if int(np.prod(dcn_shape)) != n_slices:
        raise ValueError(
            f"dcn factors {dict(dcn)} multiply to "
            f"{int(np.prod(dcn_shape))} but {n_slices} slices detected")
    ici_shape = []
    for ax in MESH_AXES:
        f = dcn.get(ax, 1)
        if shape[ax] % f:
            raise ValueError(f"axis '{ax}' size {shape[ax]} not "
                             f"divisible by its dcn factor {f}")
        ici_shape.append(shape[ax] // f)
    per_slice = len(groups[order[0]])
    if per_slice != int(np.prod(ici_shape)):
        raise ValueError(
            f"{per_slice} devices/slice != ICI axes product "
            f"{int(np.prod(ici_shape))}")
    full = np.empty(tuple(shape[ax] for ax in MESH_AXES), dtype=object)
    for lin, dcn_coord in enumerate(np.ndindex(dcn_shape)):
        block = np.array(groups[order[lin]], dtype=object
                         ).reshape(ici_shape)
        idx = tuple(slice(c * i, (c + 1) * i)
                    for c, i in zip(dcn_coord, ici_shape))
        full[idx] = block
    return full


def build_mesh(data: Optional[int] = None,
               model: int = 1,
               pipe: int = 1,
               seq: int = 1,
               expert: int = 1,
               data_inner: int = 1,
               devices: Optional[Sequence[jax.Device]] = None,
               dcn: Optional[Dict[str, int]] = None,
               slice_ids: Optional[Sequence[int]] = None,
               set_current: bool = True) -> Mesh:
    """Build the framework mesh.

    ``data=None`` infers the data-parallel degree from the device count
    (reference analogue: world_size / (tp×pp×sp×ep)). ``data_inner`` is
    the MiCS/hpZ sub-group size (divides the total DP degree).

    Multi-slice (DCN-connected) topologies: pass ``dcn={axis: factor}``
    naming which axes cross slice boundaries (factors must multiply to
    the slice count). All other axes stay ICI-local. ``slice_ids``
    overrides per-device slice detection (``device.slice_index``) — used
    by tests on CPU meshes. With multiple slices and no ``dcn``, the
    outermost nontrivial axis divisible by the slice count is chosen
    (pipe, then data) and logged.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = model * pipe * seq * expert * data_inner
    if data is None:
        if n % fixed:
            raise ValueError(
                f"device count {n} not divisible by "
                f"model×pipe×seq×expert×data_inner={fixed}")
        data = n // fixed
    total = data * fixed
    if total != n:
        raise ValueError(
            f"mesh axes product {total} != device count {n} "
            f"(pipe={pipe} data={data} data_inner={data_inner} "
            f"expert={expert} seq={seq} model={model})")
    devices = list(devices[:total])
    shape = {"pipe": pipe, "data": data, "data_inner": data_inner,
             "expert": expert, "seq": seq, "model": model}
    if slice_ids is None:
        slice_ids = [getattr(d, "slice_index", 0) or 0 for d in devices]
    n_slices = len(set(slice_ids))
    if n_slices > 1:
        if dcn is None:
            for ax in ("pipe", "data"):
                if shape[ax] % n_slices == 0 and shape[ax] >= n_slices:
                    dcn = {ax: n_slices}
                    break
            else:
                raise ValueError(
                    f"{n_slices} slices but neither pipe={pipe} nor "
                    f"data={data} is divisible by the slice count; pass "
                    f"dcn={{axis: factor}} explicitly")
            logger.info(f"multi-slice topology ({n_slices} slices): "
                        f"auto-assigned DCN axis {dcn}")
        arr = _hybrid_device_array(devices, shape, dcn, slice_ids)
    else:
        if dcn and any(v > 1 for v in dcn.values()):
            raise ValueError(f"dcn={dict(dcn)} given but only one slice "
                             f"detected")
        arr = np.array(devices).reshape(pipe, data, data_inner,
                                        expert, seq, model)
    mesh = Mesh(arr, MESH_AXES)
    if set_current:
        set_mesh(mesh)
    log_dist(f"built mesh: pipe={pipe} data={data} "
             f"data_inner={data_inner} expert={expert} "
             f"seq={seq} model={model}"
             + (f" over {n_slices} slices, dcn={dict(dcn)}"
                if n_slices > 1 else ""))
    return mesh


def mesh_from_config(config, devices=None) -> Mesh:
    """Build a mesh from a DeepSpeedTPUConfig's parallel-topology knobs."""
    return build_mesh(
        model=config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1,
        pipe=config.pipeline.stages,
        seq=config.sequence_parallel.size,
        expert=config.moe.ep_size if config.moe.enabled else 1,
        data_inner=max(int(config.zero_optimization.mics_shard_size or 1),
                       1),
        devices=devices,
    )


def set_mesh(mesh: Mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_mesh() -> Mesh:
    if _CURRENT_MESH is None:
        raise RuntimeError("no mesh set; call build_mesh() or "
                           "deepspeed_tpu.initialize() first")
    return _CURRENT_MESH


def has_mesh() -> bool:
    return _CURRENT_MESH is not None


# ---------------------------------------------------------------------------
# Group accessors — API parity with reference deepspeed/utils/groups.py, but
# returning axis names/sizes instead of torch process groups.
# ---------------------------------------------------------------------------

def _axis_size(mesh: Optional[Mesh], axis: str) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape[axis]


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """DP degree for non-expert params = data × data_inner × expert
    (reference groups.py:_get_data_parallel_world_size with expert
    interleaving)."""
    mesh = mesh or get_mesh()
    return mesh.shape["data"] * mesh.shape["data_inner"] * \
        mesh.shape["expert"]


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "model")


def get_pipe_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "pipe")


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "seq")


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "expert")


def get_world_size() -> int:
    """Devices in the active mesh (NOT jax.device_count(): a sub-mesh —
    e.g. dryrun over devices[:n] — must report its own size)."""
    if _CURRENT_MESH is not None:
        return _CURRENT_MESH.size
    return jax.device_count()


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())
