from deepspeed_tpu.parallel.mesh import (
    MESH_AXES,
    ZERO_AXES,
    build_mesh,
    get_data_parallel_world_size,
    get_expert_parallel_world_size,
    get_mesh,
    get_model_parallel_world_size,
    get_pipe_parallel_world_size,
    get_sequence_parallel_world_size,
    get_world_size,
    has_mesh,
    mesh_from_config,
    named_sharding,
    replicated,
    set_mesh,
)

__all__ = [
    "MESH_AXES", "ZERO_AXES", "build_mesh", "mesh_from_config", "get_mesh",
    "set_mesh", "has_mesh", "named_sharding", "replicated",
    "get_data_parallel_world_size", "get_model_parallel_world_size",
    "get_pipe_parallel_world_size", "get_sequence_parallel_world_size",
    "get_expert_parallel_world_size", "get_world_size",
]
