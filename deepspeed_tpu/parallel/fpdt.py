"""FPDT — Fully Pipelined Distributed Transformer (long-context tier).

Reference: ``sequence/fpdt_layer.py`` — ``_FPDTGPUOffloadingAttentionImpl_``
(:510): chunked blockwise attention with online softmax
(``update_out_and_lse``:58) whose KV chunks live in HOST memory and are
double-buffered back per query chunk; plus chunked FFN (:1056) and logits
loss (:1137). This is how the reference reaches 8–16M-token sequences at
55% MFU (blogs/ulysses-offload).

TPU-native mapping: host offload is expressed through JAX memory kinds —
the KV chunk store is placed in ``pinned_host`` memory and each chunk is
``device_put`` back inside the scan; XLA's latency-hiding scheduler
overlaps the H2D stream with the previous chunk's attention math (the
reference's manual double-buffer streams). Chunked FFN is a remat scan
over sequence tiles. SP composition (Ulysses/ring first, then FPDT
chunking each shard's local sequence) is a design note, NOT wired up:
``attention_impl='fpdt'`` is single-shard today and ``select_attention``
rejects it under ``sequence_parallel.size > 1``.
"""

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.logging import logger

_NEG_INF = -1e30


def host_offload_supported() -> bool:
    try:
        d = jax.devices()[0]
        return any(m.kind == "pinned_host"
                   for m in d.addressable_memories())
    except Exception:       # pragma: no cover - exotic backends
        return False


def _to_memory(x: jax.Array, kind: str) -> jax.Array:
    """Move an array between device and host memory (jit-compatible:
    jax.memory.Space works on tracers, unlike sharding.with_memory_kind)."""
    space = jax.memory.Space.Host if kind == "pinned_host" else \
        jax.memory.Space.Device
    return jax.device_put(x, space)


def fpdt_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   chunk: int = 1024, causal: bool = True,
                   offload: Optional[bool] = None) -> jax.Array:
    """Chunked online-softmax attention with host-resident KV.

    q/k/v: [B, T, H|KvH, Dh]. Peak device KV memory is ONE chunk (+ the
    accumulators) regardless of T — the rest waits in host DRAM.
    ``offload=None`` auto-enables when the backend exposes pinned_host
    memory. T not divisible by ``chunk`` is zero-padded at the sequence
    end (exact: padded keys sit above every real query's causal horizon;
    padded query rows are sliced off).

    TRAINING CAUTION: reverse-mode AD through the chunk loops stores
    per-iteration softmax intermediates (O(T²) bytes across the loop) —
    fine at the lengths the tests cover, ruinous at 100K+. For
    long-context TRAINING use the Pallas flash path with the
    ``offload_save_attn_kernel_host`` remat policy (its custom VJP
    recomputes scores from out/lse); fpdt attention serves forward/
    serving paths and shapes the flash kernel does not support.
    """
    t_real = q.shape[1]
    pad = (-t_real) % chunk
    if pad:
        def _pad(a):
            return jnp.concatenate(
                [a, jnp.zeros((a.shape[0], pad) + a.shape[2:], a.dtype)],
                axis=1)
        q, k, v = _pad(q), _pad(k), _pad(v)
    b, t, h, dh = q.shape
    _, _, kvh, _ = k.shape
    groups = h // kvh
    nc = t // chunk
    if offload is None:
        offload = host_offload_supported()
    if offload and not host_offload_supported():
        logger.warning("fpdt: pinned_host memory unavailable; KV stays "
                       "on device")
        offload = False

    kc = k.reshape(b, nc, chunk, kvh, dh).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(b, nc, chunk, kvh, dh).transpose(1, 0, 2, 3, 4)
    if offload:
        kc = _to_memory(kc, "pinned_host")
        vc = _to_memory(vc, "pinned_host")
    scale = 1.0 / math.sqrt(dh)

    def q_chunk_body(_, i):
        qi = lax.dynamic_index_in_dim(
            q.reshape(b, nc, chunk, h, dh), i, 1, keepdims=False)
        qg = qi.reshape(b, chunk, kvh, groups, dh)

        def kv_body(j, carry):
            acc, m, l = carry
            kj = lax.dynamic_index_in_dim(kc, j, 0, keepdims=False)
            vj = lax.dynamic_index_in_dim(vc, j, 0, keepdims=False)
            if offload:
                kj = _to_memory(kj, "device")
                vj = _to_memory(vj, "device")
            s = jnp.einsum("bckgd,bskd->bkgcs", qg, kj.astype(q.dtype),
                           preferred_element_type=jnp.float32) * scale
            if causal:
                qpos = i * chunk + jnp.arange(chunk)
                kpos = j * chunk + jnp.arange(chunk)
                mask = qpos[:, None] >= kpos[None, :]
                s = jnp.where(mask[None, None, None], s, _NEG_INF)
            blk_max = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, blk_max)
            p = jnp.exp(s - m_new[..., None])
            alive = m_new > _NEG_INF / 2
            p = jnp.where(alive[..., None], p, 0.0)
            corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
            acc = acc * corr[..., None] + jnp.einsum(
                "bkgcs,bskd->bkgcd", p.astype(vj.dtype), vj,
                preferred_element_type=jnp.float32)
            l = l * corr + jnp.sum(p, axis=-1)
            return acc, m_new, l

        acc0 = jnp.zeros((b, kvh, groups, chunk, dh), jnp.float32)
        m0 = jnp.full((b, kvh, groups, chunk), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, kvh, groups, chunk), jnp.float32)
        # static bounds so reverse-mode AD works (a traced `i + 1` upper
        # bound breaks vjp of fori_loop); chunks past the causal diagonal
        # contribute nothing — the mask sends their scores to -inf
        acc, m, l = lax.fori_loop(0, nc, kv_body, (acc0, m0, l0))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        # [b, kvh, g, c, dh] -> [b, c, h, dh]
        out = out.transpose(0, 3, 1, 2, 4).reshape(b, chunk, h, dh)
        return None, out.astype(q.dtype)

    _, chunks = lax.scan(q_chunk_body, None,
                         jnp.arange(nc, dtype=jnp.int32))
    # [nc, b, chunk, h, dh] -> [b, t, h, dh]
    out = chunks.transpose(1, 0, 2, 3, 4).reshape(b, t, h, dh)
    return out[:, :t_real] if pad else out


def fpdt_ffn(mlp_fn: Callable[[jax.Array], jax.Array], x: jax.Array,
             chunk: int = 1024, remat: bool = True) -> jax.Array:
    """Sequence-chunked FFN (reference FPDT_FFN:1056): the MLP runs one
    sequence tile at a time under remat, so activation memory is one tile.
    x: [B, T, D]. T not divisible by ``chunk`` is handled by zero-padding
    the last tile (the MLP is per-token, so padding is exact) — silently
    falling back to the unchunked MLP would OOM in exactly the long-
    context regime this exists for."""
    b, t, d = x.shape
    pad = (-t) % chunk
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((b, pad, d), x.dtype)], axis=1)
    tp = t + pad
    xs = x.reshape(b, tp // chunk, chunk, d).transpose(1, 0, 2, 3)

    def body(_, xc):
        return None, mlp_fn(xc)

    step = jax.checkpoint(body) if remat else body
    _, out = lax.scan(step, None, xs)
    out = out.transpose(1, 0, 2, 3).reshape(b, tp, d)
    return out[:, :t] if pad else out
