"""Mixture-of-Experts with expert parallelism — TPU-native.

Reference: ``deepspeed/moe/sharded_moe.py`` (``top1gating``:183,
``top2gating``:290, ``topkgating``:374, ``MOELayer``:536 with its einsum
dispatch masks, ``_AllToAll``:96) and ``deepspeed/moe/layer.py:17``.

The reference dispatches tokens to experts with an explicit
``dist.all_to_all_single`` over the EP process group. Here the dispatch is
the GShard einsum formulation — build ``[S,E,C]`` dispatch/combine masks,
``einsum('sec,sd->ecd')`` into per-expert buffers — and the expert dim of
the buffer carries a sharding constraint over the ``'expert'`` mesh axis,
so XLA lowers the regroup to the same ICI all-to-all, overlapped with the
expert GEMMs. Capacity is static (jit-friendly); tokens over capacity are
dropped (``drop_tokens``) or routed best-effort via the mask arithmetic.

Load-balance auxiliary loss per reference top1gating: ``E · Σ_e mē·c̄e``.
RTS (random token selection, reference :225): with ``use_rts`` the
capacity-slot priority is a random token permutation per step (keyed
from the engine's per-step rng), matching the reference's default
top-1 behavior; off → deterministic sequence-order priority.
"""

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.comms_logger import comms_logger


def topk_gates_t(gates_t: jax.Array, k: int
                 ) -> Tuple[jax.Array, jax.Array]:
    """Transposed top-k: ``gates_t`` [E, S] → (topv_t, topi_t) [k, S].

    The whole dropless routing chain runs in this [E, S] orientation —
    E on SUBLANES, tokens on lanes — so softmax/max/argmax reduce over
    8 sublanes with all 128 lanes busy. The [S, E] orientation puts E
    on lanes (8 of 128 used) and measured ~2 ms/layer of pure layout
    waste at [16K, 8] fwd+bwd on v5e (the same finding that shaped
    ``aligned_dispatch``'s [E, R0] histogram).
    """
    e = gates_t.shape[0]
    rows = jnp.arange(e, dtype=jnp.int32)
    g = gates_t
    vals, idxs = [], []
    for _ in range(k):
        v = jnp.max(g, axis=0)
        i = jnp.argmax(g, axis=0).astype(jnp.int32)
        vals.append(v)
        idxs.append(i)
        g = jnp.where(rows[:, None] == i[None, :], -jnp.inf, g)
    return jnp.stack(vals, 0), jnp.stack(idxs, 0)


def _capacity(num_tokens: int, num_experts: int, k: int,
              capacity_factor: float, min_capacity: int) -> int:
    """Reference sharded_moe.py:_capacity — static on TPU (shapes fixed
    at trace time)."""
    cap = math.ceil(num_tokens * k / num_experts * capacity_factor)
    return max(cap, min_capacity)


def topk_gating(logits: jax.Array, k: int, capacity: int,
                norm_probs: bool = True,
                rts_key: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k gating with capacity (reference topkgating:374).

    logits: [S, E] fp32 → (dispatch [S,E,C] bool, combine [S,E,C] f32,
    aux_loss scalar). Tokens whose per-expert slot position exceeds
    ``capacity`` are dropped; callers wanting the reference's
    ``drop_tokens=False`` semantics pass ``capacity == S`` (static worst
    case — the TPU answer to the reference's dynamic capacity raise).
    ``norm_probs``: renormalize the selected gate values (Mixtral); off
    for Qwen2-MoE's norm_topk_prob=False raw-softmax convention.
    ``rts_key``: Random Token Selection (reference top1gating:225) —
    capacity slots are claimed in a RANDOM token order instead of
    sequence order, so over-capacity drops don't always punish the same
    trailing tokens. None = deterministic sequence-order priority.
    """
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)                   # [S,E]
    topv, topi = lax.top_k(gates, k)                          # [S,k]
    if norm_probs:   # reference topkgating norm
        topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    # aux loss from the top-1 assignment (reference top1gating:262)
    mask1 = jax.nn.one_hot(topi[:, 0], e, dtype=jnp.float32)
    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    aux = jnp.sum(me * ce) * e

    perm = None
    if rts_key is not None:
        perm = jax.random.permutation(rts_key, s)

    # positions: running per-expert counts across the k choices
    counts = jnp.zeros((e,), jnp.int32)
    dispatch = jnp.zeros((s, e, capacity), jnp.bool_)
    combine = jnp.zeros((s, e, capacity), jnp.float32)
    for i in range(k):
        mask_i = jax.nn.one_hot(topi[:, i], e, dtype=jnp.int32)   # [S,E]
        if perm is not None:
            # claim slots in permuted (random-priority) order, then
            # scatter the positions back to token order
            pos_p = jnp.cumsum(mask_i[perm], axis=0) - mask_i[perm] \
                + counts[None, :]
            pos_i = jnp.zeros_like(pos_p).at[perm].set(pos_p)
        else:
            pos_i = jnp.cumsum(mask_i, axis=0) - mask_i + counts[None, :]
        pos_tok = jnp.sum(pos_i * mask_i, axis=1)                 # [S]
        keep = pos_tok < capacity
        oh_cap = jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)
        sel = (mask_i.astype(jnp.float32) * keep[:, None])        # [S,E]
        d_i = sel[:, :, None] * oh_cap[:, None, :]                # [S,E,C]
        dispatch = jnp.logical_or(dispatch, d_i > 0)
        combine = combine + d_i * topv[:, i][:, None, None]
        counts = counts + jnp.sum(mask_i * keep[:, None].astype(jnp.int32),
                                  axis=0)
    return dispatch, combine, aux


def _shared_expert(sh, xf: jax.Array) -> jax.Array:
    """Qwen2-MoE/DeepSeek dense shared expert on every token.

    xf [S,d] → [S,d]; handles int8/fp8 weight_quant leaves (scale-suffix
    convention, ops/quantized_linear.py) and the optional sigmoid gate.
    ONE implementation shared by the capacity and dropless paths."""
    from deepspeed_tpu.ops.quantized_linear import SCALE_SUFFIX
    if "wg" + SCALE_SUFFIX in sh:
        # qmatmul_tp so int8/fp8 shared-expert weights TP-shard like the
        # dense MLP (col gate/up, row down); only reached from the
        # capacity path — dropless is unquantized by construction, so
        # no nested-manual-mesh conflict with its batch shard_map
        from deepspeed_tpu.ops.quantized_linear import qmatmul_tp
        gate_s = qmatmul_tp(xf, sh["wg"], sh["wg_scale"], role="col",
                            out_dtype=xf.dtype)
        up_s = qmatmul_tp(xf, sh["wi"], sh["wi_scale"], role="col",
                          out_dtype=xf.dtype)
        s_out = qmatmul_tp(jax.nn.silu(gate_s) * up_s, sh["wo"],
                           sh["wo_scale"], role="row",
                           out_dtype=xf.dtype)
    else:
        gate_s = jnp.einsum("sd,dh->sh", xf, sh["wg"])
        up_s = jnp.einsum("sd,dh->sh", xf, sh["wi"])
        s_out = jnp.einsum("sh,hd->sd", jax.nn.silu(gate_s) * up_s,
                           sh["wo"])
    if "gate" in sh:
        s_out = s_out * jax.nn.sigmoid(
            jnp.einsum("sd,do->so", xf.astype(jnp.float32),
                       sh["gate"].astype(jnp.float32))).astype(xf.dtype)
    return s_out


def _use_pallas_gmm(d: int, f: int) -> bool:
    """Kernel selection for the dropless FFN: DSTPU_MOE_KERNEL ∈
    auto (default: Pallas on TPU when shapes tile) | pallas | xla."""
    import os
    from deepspeed_tpu.ops import grouped_matmul as gmm
    mode = os.environ.get("DSTPU_MOE_KERNEL", "auto")
    if mode == "xla":
        return False
    if mode == "pallas":
        return True
    return jax.default_backend() == "tpu" and gmm.supported(d, f)


def _dropless_ffn(p, xf: jax.Array, topv: jax.Array, topi: jax.Array,
                  top_k: int) -> jax.Array:
    """Token-local dropless dispatch: sort + grouped matmul + combine.

    xf [S,d], topv/topi [k,S] SLOT-MAJOR (``topk_gates_t``'s layout —
    tokens on lanes) → out [S,d]. Every op is per-token local
    (no collectives), so this body runs unchanged either globally or as
    the per-shard body of a shard_map over the batch axes.

    Two grouped-matmul backends (ops/grouped_matmul.py docstring has the
    design): the Pallas suite (block-aligned counting-sort dispatch +
    fused GLU kernels — the r4 decomposition's "grouped matmul with
    fused dispatch" lever) on TPU, and the original argsort +
    ``lax.ragged_dot`` path elsewhere / via DSTPU_MOE_KERNEL=xla.
    """
    s, d = xf.shape
    e = p["wg"].shape[0]
    f = p["wg"].shape[-1]
    if _use_pallas_gmm(d, f):
        from jax.ad_checkpoint import checkpoint_name
        from deepspeed_tpu.ops import grouped_matmul as gmm
        bm, bnf, bnd = gmm.pick_blocks(d, f, xf.dtype.itemsize)
        # the counting-sort metadata is tiny (~0.4MB/layer) but its
        # recompute under remat is not (cumsum histogram + int scatters
        # re-run in backward) — name it so the save_* policies keep it
        # cast combine weights to compute dtype BEFORE the dispatch
        # scatter: values are identical to casting after the gather (a
        # scatter moves bits), but the scatter payload halves
        tok, w, g_of_tile, sizes, pos, live = checkpoint_name(
            gmm.aligned_dispatch(topi, topv.astype(xf.dtype), e, bm),
            "moe_dispatch")
        xf1 = jnp.concatenate([xf, jnp.zeros((1, d), xf.dtype)])
        # the sorted-gather is a random-row HBM access pattern — save it
        # (bf16 [R_pad, d], ~74MB/layer at the 16K-token bench) so the
        # remat backward does not re-run it
        xs = checkpoint_name(gmm.gather_rows(xf1, tok, pos), "moe_xs")
        if bm % 128 == 0:
            # combine weights fused into the kernels (w applied in the
            # down kernel, dw computed in the dgdu kernel), the combine
            # below is a residual-free gather-sum, and the backward
            # recomputes gate/up in-kernel from xs — so the layer
            # backward re-runs nothing under any remat policy
            # (ops/grouped_matmul.py module docstring)
            z = gmm.grouped_glu_ffn(
                xs, p["wg"].astype(xs.dtype), p["wi"].astype(xs.dtype),
                p["wo"].astype(xs.dtype), g_of_tile, sizes, live,
                bm=bm, bnf=bnf, bnd=bnd, w=w,
                interpret=jax.default_backend() != "tpu")
            out = gmm.gather_sum(z, tok, pos)
        else:
            # the fused path's lanes-major w tiles need bm % 128 == 0
            # (TPU block rule); tiny-bm geometries (VMEM-shrunk or
            # DSTPU_GMM_BM override) keep the unfused combine
            y = gmm.grouped_glu_ffn(
                xs, p["wg"].astype(xs.dtype), p["wi"].astype(xs.dtype),
                p["wo"].astype(xs.dtype), g_of_tile, sizes, live,
                bm=bm, bnf=bnf, bnd=bnd,
                interpret=jax.default_backend() != "tpu")
            out = gmm.gather_combine(y, w.astype(y.dtype), tok, pos)
    else:
        # stable sort of the S*k (slot, token) assignments by expert id
        flat_e = topi.reshape(-1)                             # [k*S]
        order = jnp.argsort(flat_e, stable=True)              # [k*S]
        tok = order % s                                       # source token
        xs = xf[tok]                                          # [k*S, d]
        group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)

        gate_b = lax.ragged_dot(xs, p["wg"].astype(xs.dtype), group_sizes)
        up_b = lax.ragged_dot(xs, p["wi"].astype(xs.dtype), group_sizes)
        hidden = jax.nn.silu(gate_b) * up_b
        out_s = lax.ragged_dot(hidden, p["wo"].astype(xs.dtype),
                               group_sizes)

        w = topv.reshape(-1)[order].astype(xf.dtype)          # [k*S]
        out = jnp.zeros((s, d), xf.dtype).at[tok].add(out_s * w[:, None])

    if "shared" in p:   # dense shared expert, same as the capacity path
        out = out + _shared_expert(p["shared"], xf)
    return out


def dropless_moe_layer(cfg, p, x: jax.Array,
                       top_k: int = 2,
                       aux_loss_coef: float = 0.01,
                       norm_topk: bool = True,
                       ) -> Tuple[jax.Array, jax.Array]:
    """Dropless MoE via sort + ``lax.ragged_dot`` (MegaBlocks-style).

    TPU-native extra beyond the reference (which only has capacity-based
    dispatch, ``sharded_moe.py:_capacity``): no token is ever dropped and
    no capacity padding is computed. Tokens are stably sorted by assigned
    expert, the expert FFN runs as a grouped (ragged) matmul over the
    sorted buffer — ``lax.ragged_dot`` tiles each contiguous group onto
    the MXU — and outputs scatter-add back in token order weighted by
    the gate values. All shapes are static ([S*k]); only ``group_sizes``
    is data-dependent, which ragged_dot consumes as a runtime operand, so
    the whole layer stays jit-compatible.

    Routing math (softmax/top-k/aux) is elementwise and stays wherever
    GSPMD put the tokens; the sort + grouped matmul runs PER DATA SHARD
    inside a shard_map when batch axes are active — a token's output
    never depends on other tokens' grouping, so per-shard grouping is
    exact, and the global argsort's token allgather disappears (it is
    pure overhead, and an unordered collective next to the grad
    allreduce can deadlock XLA's CPU thunk runtime).

    Scope: single expert shard (EP=1). Under EP>1 a dropless all-to-all
    would need dynamic per-shard counts (not jit-static); the capacity
    path (``moe_layer``) is the EP>1 answer, exactly as MegaBlocks is
    single-GPU-group scoped. ``select_moe`` enforces this.
    """
    b, t, d = x.shape
    e = p["router"].shape[-1]
    s = b * t
    xf = x.reshape(s, d)
    # the ENTIRE routing chain runs transposed — [E, S] / [k, S],
    # tokens on lanes. The [S, E] orientation puts E (8ish) on lanes
    # and measured ~2 ms/layer of layout waste at the 16K-token bench
    # (topk_gates_t docstring); the thin matmul below has M=E on
    # sublanes instead of lanes, which XLA tiles fine.
    logits_t = jnp.einsum("de,sd->es", p["router"].astype(jnp.float32),
                          xf.astype(jnp.float32))             # [E,S]
    gates_t = jax.nn.softmax(logits_t, axis=0)                # [E,S]
    topv, topi = topk_gates_t(gates_t, top_k)                 # [k,S]
    if norm_topk:
        topv = topv / jnp.maximum(topv.sum(0, keepdims=True), 1e-9)

    # aux loss — identical formulation to the capacity path (global
    # means over all tokens, GSPMD-reduced)
    mask1_t = (jnp.arange(e, dtype=jnp.int32)[:, None]
               == topi[0][None, :]).astype(jnp.float32)       # [E,S]
    aux = jnp.sum(gates_t.mean(axis=1) * mask1_t.mean(axis=1)) * e

    # routing-health taps (telemetry/health.py): per-expert top-1 load
    # fraction + mean token routing entropy. Static flag on the model
    # config — serving configs never set it, so the 2-tuple return
    # contract of every inference caller is untouched.
    stats = None
    if getattr(cfg, "health_taps", False):
        stats = {"expert_load": mask1_t.mean(axis=1),
                 "router_entropy": -jnp.mean(jnp.sum(
                     gates_t * jnp.log(gates_t + 1e-9), axis=0))}

    batch_axes: Tuple[str, ...] = ()
    from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
    mesh = get_mesh() if has_mesh() else None
    if mesh is not None:
        batch_axes = tuple(
            a for a in ("data", "data_inner", "expert")
            if a in mesh.shape and mesh.shape[a] > 1)
        bdiv = 1
        for a in batch_axes:
            bdiv *= mesh.shape[a]
        if batch_axes and s % bdiv:
            batch_axes = ()

    if batch_axes:
        ax = batch_axes if len(batch_axes) > 1 else batch_axes[0]
        spec = P(ax, None)
        spec_t = P(None, ax)    # [k, S] — tokens are the SECOND axis
        fn = jax.shard_map(
            partial(_dropless_ffn, top_k=top_k),
            mesh=mesh, in_specs=(P(), spec, spec_t, spec_t),
            out_specs=spec, axis_names=set(batch_axes), check_vma=False)
        out = fn(p, xf, topv, topi)
    else:
        out = _dropless_ffn(p, xf, topv, topi, top_k)
    if stats is not None:
        return out.reshape(b, t, d), aux * aux_loss_coef, stats
    return out.reshape(b, t, d), aux * aux_loss_coef


#: token count above which dropless beats the capacity dispatch at
#: serving. The no-drop capacity path builds an [S,E,C=S] dispatch mask —
#: O(S²·E) — so its cost grows quadratically with prefill size (measured
#: on a 1.15B 8-expert MoE, one v5e: 2.0x dropless at S=4096, parity at
#: S≈512–2048, slight capacity edge at decode's S=8 where weight
#: streaming dominates and ragged_dot's dynamic grouping breaks fusion).
DROPLESS_MIN_TOKENS = 1024


def serving_moe_fn(model, weight_quant, params, ep: bool):
    """The ONE selection point for both inference engines' ``moe_fn``.

    Serving routes every token deterministically (full capacity, no
    dropping — reference MoE inference EP, inference/engine.py:260).
    Dropless is the fast path for large token counts (linear dispatch
    vs the capacity path's quadratic [S,E,S] mask) but reads raw weight
    leaves, so quantized expert weights (startup ``weight_quant`` OR a
    pre-quantized dstpu_quantize tree) and EP>1 (expert-sharded
    capacity buffers) always use the capacity path's scale-aware
    qmatmul dispatch. Token count is static at trace time, so the
    prefill shapes jit through dropless and the decode shapes through
    capacity — each engine's shape-keyed jit cache keeps both.
    """
    from deepspeed_tpu.inference.engine import _is_quantized_tree
    quantized = bool(weight_quant) or _is_quantized_tree(params)
    capacity_fn = partial(moe_layer, top_k=model.num_experts_per_tok,
                          drop_tokens=False, aux_loss_coef=0.0,
                          ep_axis="expert" if ep else None,
                          norm_topk=model.norm_topk_prob)
    if ep or quantized:
        return capacity_fn
    dropless_fn = partial(dropless_moe_layer,
                          top_k=model.num_experts_per_tok,
                          aux_loss_coef=0.0,
                          norm_topk=model.norm_topk_prob)

    def by_token_count(cfg, p, x, **kw):
        if x.shape[0] * x.shape[1] >= DROPLESS_MIN_TOKENS:
            return dropless_fn(cfg, p, x, **kw)
        return capacity_fn(cfg, p, x, **kw)
    return by_token_count


def moe_layer(cfg, p, x: jax.Array,
              top_k: int = 2,
              capacity_factor: float = 1.0,
              min_capacity: int = 4,
              drop_tokens: bool = True,
              aux_loss_coef: float = 0.01,
              ep_axis: Optional[str] = "expert",
              norm_topk: bool = True,
              rts_key: Optional[jax.Array] = None
              ) -> Tuple[jax.Array, jax.Array]:
    """The ``moe_fn`` consumed by models.transformer.decoder_block.

    p: {"router": [d,E], "wg": [E,d,h], "wi": [E,d,h], "wo": [E,h,d]},
    plus optionally "shared" {wg/wi/wo [d,hs]/[hs,d], gate [d,1]} — the
    Qwen2-MoE/DeepSeek shared expert that runs densely on every token.
    x: [B,T,d] → (out [B,T,d], scaled aux loss).
    """
    b, t, d = x.shape
    e = p["router"].shape[-1]
    s = b * t
    xf = x.reshape(s, d)
    logits = jnp.einsum("sd,de->se", xf.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    # drop_tokens=False → static worst-case capacity (reference raises
    # capacity to the max expert load dynamically; shapes must be static
    # under jit, so we provision for S)
    cap = _capacity(s, e, top_k, capacity_factor, min_capacity) \
        if drop_tokens else s
    dispatch, combine, aux = topk_gating(logits, top_k, cap,
                                         norm_probs=norm_topk,
                                         rts_key=rts_key)

    # routing-health taps — see dropless_moe_layer. Load is the top-1
    # assignment fraction from the raw logits (pre-RTS-noise, matching
    # the aux loss's ce term); entropy is the mean token routing entropy.
    stats = None
    if getattr(cfg, "health_taps", False):
        gates = jax.nn.softmax(logits, axis=-1)               # [S,E]
        top1 = jax.nn.one_hot(jnp.argmax(logits, axis=-1), e,
                              dtype=jnp.float32)
        stats = {"expert_load": top1.mean(axis=0),
                 "router_entropy": -jnp.mean(jnp.sum(
                     gates * jnp.log(gates + 1e-9), axis=-1))}

    ep_mesh = None
    if ep_axis is not None:
        from deepspeed_tpu.parallel.mesh import get_mesh
        mesh = get_mesh()
        if mesh.shape[ep_axis] > 1:
            ep_mesh = mesh

    # token → expert-buffer regroup; the 'expert' sharding on the E dim
    # makes XLA emit the EP all-to-all (reference _AllToAll:96)
    buf = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), xf)
    if ep_mesh is not None:
        comms_logger.append("all_to_all", buf.size * buf.dtype.itemsize,
                            ep_axis)
        buf = lax.with_sharding_constraint(
            buf, NamedSharding(ep_mesh, P(ep_axis, None, None)))

    # expert FFN (SwiGLU family; per-expert weights on the E dim); a
    # wg_scale leaf (ops/quantized_linear.py suffix convention, attached
    # by the engines' weight_quant config) routes the grouped matmuls
    # through the Pallas batched dequant kernel — int8/fp8 expert
    # weights at half the HBM (serving-only). Under EP>1
    # qmatmul_batched_ep shard_maps the kernel over 'expert' so each
    # shard streams only its local experts' weights (packed int4/fp6
    # stay single-shard, as does the engine guard for them).
    from deepspeed_tpu.ops.quantized_linear import SCALE_SUFFIX
    if "wg" + SCALE_SUFFIX in p:
        from deepspeed_tpu.ops.quantized_linear import qmatmul_batched_ep
        gate = qmatmul_batched_ep(buf, p["wg"], p["wg_scale"],
                                  out_dtype=buf.dtype)
        up = qmatmul_batched_ep(buf, p["wi"], p["wi_scale"],
                                out_dtype=buf.dtype)
        hidden = jax.nn.silu(gate) * up
        out_buf = qmatmul_batched_ep(hidden, p["wo"], p["wo_scale"],
                                     out_dtype=buf.dtype)
    else:
        gate = jnp.einsum("ecd,edh->ech", buf, p["wg"])
        up = jnp.einsum("ecd,edh->ech", buf, p["wi"])
        hidden = jax.nn.silu(gate) * up
        out_buf = jnp.einsum("ech,ehd->ecd", hidden, p["wo"])

    if ep_mesh is not None:
        out_buf = lax.with_sharding_constraint(
            out_buf, NamedSharding(ep_mesh, P(ep_axis, None, None)))

    out = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out_buf)

    if "shared" in p:   # Qwen2-MoE/DeepSeek: dense expert on every token
        out = out + _shared_expert(p["shared"], xf)
    if stats is not None:
        return out.reshape(b, t, d), aux * aux_loss_coef, stats
    return out.reshape(b, t, d), aux * aux_loss_coef
