"""Ulysses sequence parallelism — TPU-native.

Reference: ``deepspeed/sequence/layer.py`` (``single_all_to_all``:221,
``_SeqAllToAll``:277, ``DistributedAttention``:331). The reference wraps a
local attention with two explicit all-to-alls: scatter heads / gather
sequence before attention, and the inverse after. On TPU the same data
movement is expressed as two sharding constraints: activations arrive
sequence-sharded ``[B, T/sp, H, D]`` and are *resharded* to head-sharded
``[B, T, H/sp, D]`` — XLA lowers that transposed resharding to exactly the
ICI all-to-all of the reference, fused and overlapped by its scheduler.

Composes with tensor parallelism (heads sharded over ('model','seq')
jointly) and GQA (KV heads shard only when divisible; the reference's
uneven-head path `sequence/layer.py` get_num_kv_heads — here: replicate
when indivisible).

ALST (reference runtime/sequence_parallel/ulysses_sp.py) mapping:
``UlyssesSPDataLoaderAdapter``:471 is SUBSUMED — the engine's batch
sharding already places the sequence dim on the 'seq' axis
(engine._batch_sharding), so each device holds its T/sp slice without a
host-side adapter; ``TiledMLP``:838 → runtime/tiling.tiled_linear +
parallel/fpdt.fpdt_ffn; ``TiledFusedLogitsLoss``:960 →
models/transformer.chunked_cross_entropy.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.comms_logger import comms_logger
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.models.transformer import dot_product_attention
from deepspeed_tpu.parallel.mesh import ZERO_AXES, get_mesh


def _head_sharding(n_heads_axis_size: int, mesh, axis_name: str,
                   with_tp: bool):
    """Pick the head-dim sharding for attention time; None if indivisible
    (logged — a silent fallback hides a mis-sized mesh, VERDICT r1 #8)."""
    total = mesh.shape[axis_name] * (mesh.shape["model"] if with_tp else 1)
    if n_heads_axis_size % total == 0:
        return ("model", axis_name) if with_tp else axis_name
    if with_tp and n_heads_axis_size % mesh.shape["model"] == 0:
        logger.warning(
            f"ulysses: {n_heads_axis_size} heads not divisible by "
            f"model×seq={total}; sharding heads over 'model' only")
        return "model"
    logger.warning(
        f"ulysses: {n_heads_axis_size} heads not divisible by "
        f"{'model×' if with_tp else ''}{axis_name}={total}; replicating "
        f"heads (attention loses the SP/TP split — resize the mesh)")
    return None


def distributed_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool = True,
                          q_offset: int = 0,
                          axis_name: str = "seq",
                          inner=dot_product_attention) -> jax.Array:
    """Drop-in ``attn_fn``: q [B,T,H,D], k/v [B,T,KvH,D] (global view,
    sequence dim sharded over ``axis_name`` by the batch input sharding).

    Reference call structure (DistributedAttention.forward:331):
    all_to_all(q,k,v) → local attn → all_to_all(out).
    """
    mesh = get_mesh()
    sp = mesh.shape[axis_name]
    if sp == 1:
        return inner(q, k, v, causal=causal, q_offset=q_offset)
    with_tp = mesh.shape["model"] > 1

    h_shard = _head_sharding(q.shape[2], mesh, axis_name, with_tp)
    kv_shard = _head_sharding(k.shape[2], mesh, axis_name, with_tp)

    comms_logger.append("all_to_all",
                        q.size * q.dtype.itemsize, axis_name)

    # scatter heads / gather sequence (reference single_all_to_all:221)
    q = jax.lax.with_sharding_constraint(
        q, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, h_shard, None)))
    k = jax.lax.with_sharding_constraint(
        k, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, kv_shard, None)))
    v = jax.lax.with_sharding_constraint(
        v, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, kv_shard, None)))

    out = inner(q, k, v, causal=causal, q_offset=q_offset)

    # gather heads / scatter sequence back (the inverse all-to-all)
    out = jax.lax.with_sharding_constraint(
        out, jax.sharding.NamedSharding(
            mesh, P(ZERO_AXES, axis_name, "model" if with_tp else None, None)))
    return out
