"""Ulysses sequence parallelism — TPU-native.

Reference: ``deepspeed/sequence/layer.py`` (``single_all_to_all``:221,
``_SeqAllToAll``:277, ``DistributedAttention``:331). The reference wraps a
local attention with two explicit all-to-alls: scatter heads / gather
sequence before attention, and the inverse after. On TPU the same data
movement is expressed as two sharding constraints: activations arrive
sequence-sharded ``[B, T/sp, H, D]`` and are *resharded* to head-sharded
``[B, T, H/sp, D]`` — XLA lowers that transposed resharding to exactly the
ICI all-to-all of the reference, fused and overlapped by its scheduler.

Composes with tensor parallelism (heads sharded over ('model','seq')
jointly) and GQA. Indivisible head counts (the reference's uneven-head
path, `sequence/layer.py:111` ``uneven_heads_all2all``) keep the full
SP split here via static head padding / minimal KV replication — the
SPMD answer to the reference's per-rank uneven split lists, which need
dynamic shapes JAX/XLA cannot trace:

* KV heads not divisible by the head-axis size (GQA with few KV heads,
  THE common case): each KV head is replicated ``total/gcd(KvH,total)``
  times — the minimal factor making the count divisible — with the GQA
  group mapping exactly preserved; cotangents of replicated heads sum
  back onto the original, so gradients are exact.
* Q heads not divisible: zero-pad query heads to the next multiple and
  slice the output back; sliced-off outputs carry zero cotangent, so
  K/V gradients are exact too.

ALST (reference runtime/sequence_parallel/ulysses_sp.py) mapping:
``UlyssesSPDataLoaderAdapter``:471 is SUBSUMED — the engine's batch
sharding already places the sequence dim on the 'seq' axis
(engine._batch_sharding), so each device holds its T/sp slice without a
host-side adapter; ``TiledMLP``:838 → runtime/tiling.tiled_linear +
parallel/fpdt.fpdt_ffn; ``TiledFusedLogitsLoss``:960 →
models/transformer.chunked_cross_entropy.
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.comms_logger import comms_logger
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.models.transformer import dot_product_attention
from deepspeed_tpu.parallel.mesh import ZERO_AXES, get_mesh


def _even_heads(q: jax.Array, k: jax.Array, v: jax.Array, total: int):
    """Make both head counts divisible by ``total`` (the head-axis mesh
    extent) so the Ulysses head-scatter keeps its full split — the static
    SPMD equivalent of the reference's uneven per-rank head lists
    (sequence/layer.py:111). Returns ``(q, k, v, orig_q_heads)`` or
    ``None`` when no exact static layout exists (caller falls back)."""
    H, KvH = q.shape[2], k.shape[2]
    orig_h = H
    if H % total:
        if KvH != H:
            # padded-Q GQA would skew the q→kv group mapping; exotic
            # (uneven q heads AND grouped kv) — no exact static layout
            return None
        pad = (-H) % total
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        H += pad
        KvH += pad
    if KvH % total:
        if H % KvH:
            return None               # not a valid GQA grouping anyway
        g = H // KvH                  # q heads per kv group
        r = total // math.gcd(KvH, total)   # minimal replication factor
        if g % r:
            return None
        # kv'[j] = kv[j // r]: new group size g/r, so q head h maps to
        # kv' head h//(g/r), and (h//(g/r))//r == h//g — the original
        # grouping, exactly
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    return q, k, v, orig_h


def distributed_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool = True,
                          q_offset: int = 0,
                          axis_name: str = "seq",
                          inner=dot_product_attention) -> jax.Array:
    """Drop-in ``attn_fn``: q [B,T,H,D], k/v [B,T,KvH,D] (global view,
    sequence dim sharded over ``axis_name`` by the batch input sharding).

    Reference call structure (DistributedAttention.forward:331):
    all_to_all(q,k,v) → local attn → all_to_all(out).
    """
    mesh = get_mesh()
    sp = mesh.shape[axis_name]
    if sp == 1:
        return inner(q, k, v, causal=causal, q_offset=q_offset)
    with_tp = mesh.shape["model"] > 1
    total = sp * (mesh.shape["model"] if with_tp else 1)

    evened = _even_heads(q, k, v, total)
    if evened is None:
        logger.warning(
            f"ulysses: no exact static head layout for q_heads={q.shape[2]} "
            f"kv_heads={k.shape[2]} over {'model×' if with_tp else ''}"
            f"{axis_name}={total}; replicating heads (attention loses the "
            f"SP split — resize the mesh)")
        h_shard = kv_shard = None
        orig_h = q.shape[2]
    else:
        q, k, v, orig_h = evened
        h_shard = kv_shard = ("model", axis_name) if with_tp else axis_name

    comms_logger.append("all_to_all",
                        q.size * q.dtype.itemsize, axis_name)

    # scatter heads / gather sequence (reference single_all_to_all:221)
    q = jax.lax.with_sharding_constraint(
        q, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, h_shard, None)))
    k = jax.lax.with_sharding_constraint(
        k, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, kv_shard, None)))
    v = jax.lax.with_sharding_constraint(
        v, jax.sharding.NamedSharding(mesh, P(ZERO_AXES, None, kv_shard, None)))

    out = inner(q, k, v, causal=causal, q_offset=q_offset)

    # gather heads / scatter sequence back (the inverse all-to-all)
    out = jax.lax.with_sharding_constraint(
        out, jax.sharding.NamedSharding(
            mesh, P(ZERO_AXES, axis_name, "model" if with_tp else None, None)))
    if out.shape[2] != orig_h:
        out = out[:, :, :orig_h, :]   # drop padded query heads
    return out
