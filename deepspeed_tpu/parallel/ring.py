"""Ring attention — ICI-idiomatic context parallelism.

The reference has NO ring attention (SURVEY.md §2.3: long context is
Ulysses all-to-all + FPDT chunking); this is the TPU-native addition the
survey calls for: K/V blocks rotate around the 'seq' ring via
``lax.ppermute`` (nearest-neighbour ICI traffic, bandwidth-optimal) while
each device keeps its query block resident, accumulating attention with an
online-softmax (flash-style) update in fp32.

Comm cost per device: (sp-1) ppermutes of the local KV block — O(T/sp)
bytes per hop on a physical ring, vs Ulysses' all-to-all O(T/sp) with
full bisection. Ring wins when sp exceeds the all-to-all-efficient pod
slice or when heads < sp (Ulysses can't shard).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.comms_logger import comms_logger
from deepspeed_tpu.parallel.mesh import get_mesh

_NEG_INF = -1e30


def _block_attend(q, k, v, qpos, kpos, causal):
    """One q-block × kv-block partial attention.

    q: [B,Tq,H,D] k/v: [B,Tk,KvH,D]; returns (scores-exp sum stats).
    GQA via head grouping (no materialized repeat). fp32 throughout.
    """
    b, tq, h, d = q.shape
    _, tk, kvh, _ = k.shape
    groups = h // kvh
    qg = q.reshape(b, tq, kvh, groups, d)
    s = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                   preferred_element_type=jnp.float32)
    s = s / jnp.sqrt(jnp.float32(d))
    if causal:
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None, None], s, _NEG_INF)
    return s  # [B,KvH,G,Tq,Tk]


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         causal: bool = True,
                         q_offset: int = 0,
                         axis_name: str = "seq") -> jax.Array:
    """Per-shard body: q/k/v are LOCAL blocks [B, T/sp, H|KvH, D].

    Must run inside shard_map/pmap with ``axis_name`` manual.
    """
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    _, tk, kvh, _ = k.shape
    groups = h // kvh
    qpos = idx * tq + jnp.arange(tq) + q_offset

    o0 = jnp.zeros((b, kvh, groups, tq, d), jnp.float32)
    m0 = jnp.full((b, kvh, groups, tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, groups, tq), jnp.float32)
    # mark the constants as device-varying over the ring axis (jax VMA)
    o0, m0, l0 = (lax.pcast(x, (axis_name,), to="varying")
                  for x in (o0, m0, l0))
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % sp                     # chunk id currently held
        kpos = src * tk + jnp.arange(tk)
        s = _block_attend(q, k_cur, v_cur, qpos, kpos, causal)
        blk_max = jnp.max(s, axis=-1)            # [B,KvH,G,Tq]
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked blocks (max = -inf): contribute nothing
        alive = new_m > _NEG_INF / 2
        p = jnp.exp(s - jnp.where(alive, new_m, 0.0)[..., None])
        p = jnp.where(alive[..., None], p, 0.0)
        corr = jnp.where(alive, jnp.exp(m - jnp.where(alive, new_m, 0.0)), 0.0)
        corr = jnp.where(m > _NEG_INF / 2, corr, 0.0)
        pv = jnp.einsum("bkgts,bskd->bkgtd", p,
                        v_cur.astype(jnp.float32))
        o = o * corr[..., None] + pv
        l = l * corr + jnp.sum(p, axis=-1)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, new_m, l, k_nxt, v_nxt)

    o, m, l, _, _ = lax.fori_loop(0, sp, body, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, tq, h, d)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   q_offset: int = 0,
                   axis_name: str = "seq") -> jax.Array:
    """Drop-in ``attn_fn`` over GLOBAL arrays [B,T,H,D]: wraps the local
    ring body in a partial-manual shard_map over the 'seq' axis (other
    mesh axes stay automatic, so ZeRO/TP shardings pass through)."""
    mesh = get_mesh()
    sp = mesh.shape[axis_name]
    if sp == 1:
        from deepspeed_tpu.models.transformer import dot_product_attention
        return dot_product_attention(q, k, v, causal=causal,
                                     q_offset=q_offset)
    comms_logger.append("ppermute",
                        (k.size + v.size) * k.dtype.itemsize * (sp - 1),
                        axis_name)
    fn = jax.shard_map(
        partial(ring_attention_local, causal=causal, q_offset=q_offset,
                axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(None, axis_name, None, None),) * 3,
        out_specs=P(None, axis_name, None, None),
        axis_names={axis_name})
    return fn(q, k, v)
