"""Compression primitives — QAT fake-quant (straight-through estimator),
magnitude/structured pruning masks.

Reference: ``compression/basic_layer.py`` (LinearLayer_Compress:
quantization :372–420, sparse/head/channel pruning :200–330) and
``compression/utils.py`` quantizers. The reference rewrites nn.Modules;
here every transform is a pure function applied to weights/activations
inside the loss function — XLA fuses the fake-quant into the surrounding
matmuls, so QAT costs almost nothing on TPU.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _ste(x: jax.Array, qx: jax.Array) -> jax.Array:
    """Straight-through estimator: forward = qx, gradient = identity
    (reference utils.py SymQuantizer.forward's detach trick)."""
    return x + lax.stop_gradient(qx - x)


def weight_fake_quant(w: jax.Array, bits: int = 8, groups: int = 1
                     ) -> jax.Array:
    """Symmetric per-group QAT fake quantization of a weight tensor."""
    if bits >= 16:
        return w
    qmax = 2.0 ** (bits - 1) - 1
    flat = w.reshape(groups, -1)
    scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(flat / safe), -qmax, qmax) * safe
    return _ste(w, q.reshape(w.shape).astype(w.dtype))


def activation_fake_quant(x: jax.Array, bits: int = 8) -> jax.Array:
    """Dynamic-range symmetric activation fake quant (reference
    activation_quantization 'dynamic' calibration)."""
    if bits >= 16:
        return x
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x)) / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / safe), -qmax, qmax) * safe
    return _ste(x, q.astype(x.dtype))


def magnitude_prune_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Keep the top ``dense_ratio`` fraction of |w| (reference l1-method
    sparse pruning). Returns a {0,1} mask of w's shape."""
    k = max(1, int(round(w.size * dense_ratio)))
    flat = jnp.abs(w.reshape(-1))
    # threshold = k-th largest magnitude
    thresh = lax.top_k(flat, k)[0][-1]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def head_prune_mask(wo_like: jax.Array, num_heads: int, keep: int
                    ) -> jax.Array:
    """Structured head pruning for an attention output projection whose
    leading dim is [H * Dh] (reference head_pruning on attn.out_proj):
    score heads by L2 norm, keep the top ``keep``. Returns a [H] {0,1}
    mask."""
    h = num_heads
    per_head = wo_like.reshape(h, -1)
    scores = jnp.sqrt(jnp.sum(jnp.square(per_head.astype(jnp.float32)),
                              axis=1))
    if keep >= h:
        return jnp.ones((h,), wo_like.dtype)
    thresh = lax.top_k(scores, keep)[0][-1]
    return (scores >= thresh).astype(wo_like.dtype)


def channel_prune_mask(w: jax.Array, dense_ratio: float, axis: int = 0
                       ) -> jax.Array:
    """Structured channel pruning: L2-score along ``axis``, keep the top
    fraction (reference channel_pruning). Mask broadcastable to w."""
    moved = jnp.moveaxis(w, axis, 0)
    scores = jnp.sqrt(jnp.sum(
        jnp.square(moved.reshape(moved.shape[0], -1).astype(jnp.float32)),
        axis=1))
    keep = max(1, int(round(scores.shape[0] * dense_ratio)))
    thresh = lax.top_k(scores, keep)[0][-1]
    mask1d = (scores >= thresh).astype(w.dtype)
    shape = [1] * w.ndim
    shape[axis] = w.shape[axis]
    return mask1d.reshape(shape)
