"""Compression config (reference: compression/config.py +
``get_compression_config`` runtime/config.py:794 — same JSON schema keys,
flattened to the knobs the TPU path implements)."""

from typing import List, Optional

from deepspeed_tpu.config.config_utils import TPUConfigModel


class WeightQuantizationConfig(TPUConfigModel):
    enabled: bool = False
    start_bits: int = 8
    target_bits: int = 8
    quantize_period: int = 100          #: steps between bit reductions
    quantize_groups: int = 1            #: per-tensor groups
    schedule_offset: int = 0            #: step at which QAT starts
    modules: List[str] = ["*"]          #: leaf-name glob filter


class ActivationQuantizationConfig(TPUConfigModel):
    enabled: bool = False
    bits: int = 8
    range_calibration: str = "dynamic"  #: dynamic absmax per batch
    schedule_offset: int = 0
    modules: List[str] = ["*"]


class SparsePruningConfig(TPUConfigModel):
    enabled: bool = False
    method: str = "l1"                  #: magnitude pruning
    dense_ratio: float = 0.5            #: fraction of weights KEPT
    frequency: int = 100                #: mask refresh period (steps)
    schedule_offset: int = 0
    modules: List[str] = ["*"]


class HeadPruningConfig(TPUConfigModel):
    enabled: bool = False
    num_heads: int = 0                  #: heads to KEEP (0 = all)
    dense_ratio: float = 1.0
    schedule_offset: int = 0
    modules: List[str] = ["*"]


class LayerReductionConfig(TPUConfigModel):
    enabled: bool = False
    keep_number_layer: int = 0
    teacher_layer: List[int] = []


class CompressionConfig(TPUConfigModel):
    """Reference compression JSON block (compression/constants.py names)."""
    weight_quantization: WeightQuantizationConfig = \
        WeightQuantizationConfig()
    activation_quantization: ActivationQuantizationConfig = \
        ActivationQuantizationConfig()
    sparse_pruning: SparsePruningConfig = SparsePruningConfig()
    head_pruning: HeadPruningConfig = HeadPruningConfig()
    layer_reduction: LayerReductionConfig = LayerReductionConfig()

    @property
    def any_enabled(self) -> bool:
        return (self.weight_quantization.enabled or
                self.activation_quantization.enabled or
                self.sparse_pruning.enabled or
                self.head_pruning.enabled or
                self.layer_reduction.enabled)
