"""Compression top-level API (reference: compression/compress.py —
``init_compression``:*, ``redundancy_clean``).

The reference walks an nn.Module and swaps layers for compressed variants;
here compression is a pure params→params transform composed into the loss
function:

    ccfg = CompressionConfig(**ds_config["compression_training"])
    state = init_compression(params, ccfg)
    sched = CompressionScheduler(ccfg)

    def loss_fn(params, batch, rng):
        sched_w = sched.weight_quant()            # host-side, static
        p = apply_compression(params, state, wq_bits=sched_w.bits if
                              sched_w.active else None, prune=True)
        return base_loss(p, batch, rng)

Masks live OUTSIDE the optimizer state (the reference keeps them as module
buffers): gradients flow through the masked forward via the straight-
through estimator, the optimizer updates dense weights, and
``redundancy_clean`` bakes the masks in at export time.
"""

import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.compression.transforms import (magnitude_prune_mask,
                                                  weight_fake_quant)
from deepspeed_tpu.utils.pytree import leaf_items as _leaf_items
from deepspeed_tpu.utils.pytree import path_key as _path_key

Pytree = Any


@dataclass
class CompressionState:
    """Per-leaf pruning masks + which leaves each method touches."""
    masks: Dict[str, jax.Array] = field(default_factory=dict)
    wq_keys: tuple = ()
    prune_keys: tuple = ()


def _matches(key: str, patterns) -> bool:
    return any(fnmatch.fnmatch(key, pat) or pat == "*" for pat in patterns)


def _eligible(leaf) -> bool:
    return jnp.ndim(leaf) >= 2 and jnp.issubdtype(
        jnp.asarray(leaf).dtype, jnp.floating)


def init_compression(params: Pytree, config: CompressionConfig
                     ) -> CompressionState:
    """Select target leaves and build initial masks (reference
    init_compression layer-swap walk)."""
    wq_keys, prune_keys, masks = [], [], {}
    for key, leaf in _leaf_items(params):
        if not _eligible(leaf):
            continue
        if config.weight_quantization.enabled and \
                _matches(key, config.weight_quantization.modules):
            wq_keys.append(key)
        if config.sparse_pruning.enabled and \
                _matches(key, config.sparse_pruning.modules):
            prune_keys.append(key)
            masks[key] = jnp.ones(jnp.shape(leaf),
                                  jnp.asarray(leaf).dtype)
    return CompressionState(masks=masks, wq_keys=tuple(wq_keys),
                            prune_keys=tuple(prune_keys))


def update_masks(params: Pytree, state: CompressionState,
                 config: CompressionConfig) -> CompressionState:
    """Recompute magnitude masks from current weights (called when the
    scheduler reports refresh_due; reference frequency semantics)."""
    ratio = config.sparse_pruning.dense_ratio
    new = dict(state.masks)
    lookup = dict(_leaf_items(params))
    for key in state.prune_keys:
        new[key] = magnitude_prune_mask(lookup[key], ratio)
    return CompressionState(masks=new, wq_keys=state.wq_keys,
                            prune_keys=state.prune_keys)


def apply_compression(params: Pytree, state: CompressionState,
                      wq_bits: Optional[int] = None, wq_groups: int = 1,
                      prune: bool = False) -> Pytree:
    """Forward-time transform: mask pruned weights, fake-quant QAT
    weights. jit-safe (activity is static per trace)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        key = _path_key(path)
        x = leaf
        if prune and key in state.prune_keys and key in state.masks:
            x = x * state.masks[key]
        if wq_bits is not None and key in state.wq_keys:
            x = weight_fake_quant(x, bits=wq_bits, groups=wq_groups)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def redundancy_clean(params: Pytree, state: CompressionState) -> Pytree:
    """Bake masks into the weights for export (reference
    redundancy_clean)."""
    return apply_compression(params, state, wq_bits=None, prune=True)
