"""Compression subsystem (reference: deepspeed/compression/ — 2,444 LoC:
``compress.py`` init_compression/redundancy_clean, ``basic_layer.py``
QAT/pruning layer rewrites, ``scheduler.py`` compression scheduler)."""

from deepspeed_tpu.compression.compress import (CompressionState,
                                                apply_compression,
                                                init_compression,
                                                redundancy_clean,
                                                update_masks)
from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.compression.transforms import (activation_fake_quant,
                                                  head_prune_mask,
                                                  magnitude_prune_mask,
                                                  weight_fake_quant)

__all__ = ["CompressionConfig", "CompressionScheduler", "CompressionState",
           "init_compression", "apply_compression", "redundancy_clean",
           "update_masks", "weight_fake_quant", "activation_fake_quant",
           "magnitude_prune_mask", "head_prune_mask"]
