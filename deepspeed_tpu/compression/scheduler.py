"""Compression scheduler (reference: compression/scheduler.py —
``CompressionScheduler`` drives schedule_offset / frequency / progressive
bit reduction per compression method)."""

from dataclasses import dataclass

from deepspeed_tpu.compression.config import CompressionConfig


@dataclass
class MethodState:
    active: bool = False
    bits: int = 32          #: current quantization bits (progressive)
    refresh_due: bool = False


class CompressionScheduler:
    """Tracks the training step and answers, per method: is it active,
    at what strength, and is a mask refresh due this step."""

    def __init__(self, config: CompressionConfig):
        self.config = config
        self.step = 0

    def advance(self, step: int) -> None:
        self.step = int(step)

    # -- per-method queries --------------------------------------------------

    def weight_quant(self) -> MethodState:
        c = self.config.weight_quantization
        if not c.enabled or self.step < c.schedule_offset:
            return MethodState()
        # progressive bit reduction: start_bits → target_bits, one bit
        # every quantize_period steps (reference quantize_period semantics)
        steps_in = self.step - c.schedule_offset
        drop = min(c.start_bits - c.target_bits,
                   steps_in // max(c.quantize_period, 1))
        return MethodState(active=True, bits=c.start_bits - drop)

    def activation_quant(self) -> MethodState:
        c = self.config.activation_quantization
        if not c.enabled or self.step < c.schedule_offset:
            return MethodState()
        return MethodState(active=True, bits=c.bits)

    def sparse_prune(self) -> MethodState:
        c = self.config.sparse_pruning
        if not c.enabled or self.step < c.schedule_offset:
            return MethodState()
        due = (self.step - c.schedule_offset) % max(c.frequency, 1) == 0
        return MethodState(active=True, refresh_due=due)

    def head_prune(self) -> MethodState:
        c = self.config.head_pruning
        if not c.enabled or self.step < c.schedule_offset:
            return MethodState()
        return MethodState(active=True)
