"""Inference engines: v1 padded KV-cache generation
(:mod:`deepspeed_tpu.inference.engine`), the ragged paged-KV engine
(:mod:`deepspeed_tpu.inference.engine_v2`, the FastGen-core analogue),
and the encoder scoring engine
(:mod:`deepspeed_tpu.inference.encoder`, the BERT-container analogue)."""

from deepspeed_tpu.inference.engine import (DeepSpeedTPUInferenceConfig,
                                            InferenceEngineTPU,
                                            init_inference)
from deepspeed_tpu.inference.engine_v2 import (RaggedInferenceConfig,
                                               RaggedInferenceEngineTPU)
from deepspeed_tpu.inference.encoder import (EncoderInferenceTPU,
                                             init_encoder_inference)
from deepspeed_tpu.inference.ragged import (BlockedAllocator, DSStateManager,
                                            RaggedScheduler)

__all__ = ["init_inference", "InferenceEngineTPU",
           "DeepSpeedTPUInferenceConfig", "RaggedInferenceEngineTPU",
           "RaggedInferenceConfig", "EncoderInferenceTPU",
           "init_encoder_inference", "BlockedAllocator", "DSStateManager",
           "RaggedScheduler"]
