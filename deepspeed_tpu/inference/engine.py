"""Inference engine v1 — TP-sharded KV-cached generation.

Reference: ``deepspeed.init_inference`` (deepspeed/__init__.py:302) →
``InferenceEngine`` (inference/engine.py:40). The reference swaps modules
for fused CUDA kernels and captures CUDA graphs; here the forward is one
jitted cached-decode function (jit *is* the graph capture — reference
_create_cuda_graph:496 is subsumed), TP sharding comes from the model's
partition specs over the 'model' mesh axis, and the KV cache is a
static-shape pytree updated in place with buffer donation.

Sampling: greedy, temperature, top-k, top-p (reference relies on HF
generate; serving loops here need it built in).
"""

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple, Union

import os

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.config.config_utils import TPUConfigModel
from deepspeed_tpu.models.transformer import (DecoderConfig,
                                              forward_with_cache,
                                              init_kv_cache, init_params,
                                              partition_specs)
from deepspeed_tpu.parallel.mesh import build_mesh, get_mesh, has_mesh
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedTPUInferenceConfig(TPUConfigModel):
    """Reference: inference/config.py:DeepSpeedInferenceConfig (subset)."""
    tensor_parallel: Dict[str, Any] = {}
    dtype: str = "bfloat16"
    max_out_tokens: int = 1024
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = False   # parity no-op: jit fuses
    min_out_tokens: int = 1
    #: "int8" | "fp8" | "int4" | "fp6" = weight-only quantized serving:
    #: matmul weights stored int8 (uniform grid), float8_e4m3fn, two
    #: int4 nibbles per byte, or four fp6-e3m2 values per three bytes,
    #: with per-channel scales, dequantized in VMEM inside the Pallas
    #: qmatmul kernels. Weight HBM vs bf16: 1/2 (int8/fp8), 3/8 (fp6),
    #: 1/4 (int4); see ops/quantized_linear.py for measured tradeoffs
    weight_quant: Optional[str] = None

    @property
    def tp_size(self) -> int:
        return int(self.tensor_parallel.get("tp_size", 1) or 1)


def _is_quantized_tree(params) -> bool:
    """True when the pytree carries serving-quantization leaves
    (``<name>_scale`` / ``lm_head_q``) — e.g. a bin/dstpu_quantize
    output reloaded from disk."""
    from deepspeed_tpu.ops.quantized_linear import SCALE_SUFFIX

    def walk(d):
        for k, v in d.items():
            if isinstance(k, str) and (k.endswith(SCALE_SUFFIX)
                                       or k == "lm_head_q"):
                return True
            if isinstance(v, dict) and walk(v):
                return True
        return False

    return isinstance(params, dict) and walk(params)


def _has_packed_leaves(params) -> bool:
    """True when the tree carries PACKED quantized leaves (int4 nibble /
    fp6 plane storage, uint8 dtype) — the formats whose planes cannot
    be TP/EP-sharded. int8 leaves are ``int8``, fp8 are
    ``float8_e4m3fn``; only packed formats use uint8."""
    return any(getattr(v, "dtype", None) == jnp.uint8
               for v in jax.tree.leaves(params))


def _divides(sh: NamedSharding, shape) -> bool:
    """True when every dim of ``shape`` divides its mesh-axis product
    under ``sh`` — uneven placement would raise at device_put, whereas
    the qmatmul kernels handle non-divisible shapes by falling back to
    the replicated path (qmatmul_tp / qmatmul_batched_ep guards)."""
    for dim, names in zip(shape, sh.spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        size = 1
        for n in names:
            size *= sh.mesh.shape[n]
        if dim % size:
            return False
    return True


def _strip_axis(spec: P, axis: str) -> P:
    out = []
    for e in spec:
        if e == axis:
            out.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a != axis)
            out.append(kept if kept else None)
        else:
            out.append(e)
    return P(*out)


def _shard_like(qtree, sh_tree, mesh, in_moe=False):
    """Sharding tree for a quantized param tree: quantized weight leaves
    keep their original partition spec (int8/fp8 leaves are elementwise
    replacements, same shape); the tied-embedding logits copy
    ``lm_head_q`` [D, V] shards on V over 'model' (qmatmul_tp's col
    layout); per-channel ``_scale`` leaves replicate (tiny, and the
    scale commutes with the shard reduction). MoE expert leaves keep
    only their 'expert' sharding — the grouped quantized kernel
    (qmatmul_batched_ep) has no TP path, so a 'model'-sharded expert
    weight would be allgathered at every use. Any leaf whose spec
    doesn't divide its shape replicates — the kernels' non-divisible
    fallback then runs exactly as before."""
    rep = NamedSharding(mesh, P())
    head_sh = NamedSharding(mesh, P(None, "model"))
    out = {}
    for k, v in qtree.items():
        sub = sh_tree.get(k) if isinstance(sh_tree, dict) else None
        if isinstance(v, dict):
            out[k] = _shard_like(v, sub if isinstance(sub, dict) else {},
                                 mesh, in_moe=in_moe or k == "moe")
            continue
        sh = sub if isinstance(sub, NamedSharding) else \
            (head_sh if k == "lm_head_q" else rep)
        if in_moe and isinstance(sh, NamedSharding):
            sh = NamedSharding(mesh, _strip_axis(sh.spec, "model"))
        out[k] = sh if _divides(sh, v.shape) else rep
    return out


def setup_engine_params(model: DecoderConfig, config, mesh, params, rng):
    """Shared serving-engine bring-up (v1 generator + encoder engine):
    mesh resolution, dtype policy, TP/EP weight-quant guards, GSPMD
    sharding from ``partition_specs``, init-or-device_put with dtype
    cast, and weight-only quantization. Returns
    ``(mesh, dtype, params, param_shardings)``."""
    from deepspeed_tpu.ops.quantized_linear import validate_weight_quant
    validate_weight_quant(config.weight_quant)
    if mesh is None:
        mesh = get_mesh() if has_mesh() else build_mesh(model=config.tp_size)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[config.dtype]
    tp = mesh.shape["model"] > 1
    if config.weight_quant in ("int4", "fp6") and tp:
        raise ValueError(
            f"weight_quant={config.weight_quant} requires tp_size=1 / a "
            "mesh with model axis 1: the packed nibble/6-bit planes "
            "cannot be sharded (int8/fp8 DO support TP via qmatmul_tp)")
    if config.weight_quant in ("int4", "fp6") and model.num_experts and \
            mesh.shape["expert"] > 1:
        raise ValueError(
            f"weight_quant={config.weight_quant} requires an expert "
            "mesh axis of 1: the packed nibble/6-bit expert planes "
            "cannot shard over EP (int8/fp8 DO support EP via "
            "qmatmul_batched_ep)")
    specs = partition_specs(model, zero_stage=0, tp=tp)
    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def cast(x):
        return x.astype(dtype) \
            if jnp.issubdtype(x.dtype, jnp.floating) else x

    if params is None:
        if config.weight_quant:
            # init + quantize on HOST, ship only the quantized tree: a
            # model can be servable quantized (int4 llama-8B ≈ 5 GB) yet
            # far larger than HBM in bf16 (16 GB) — materializing full
            # precision on device first would OOM before the memory win.
            # The reference streams+quantizes checkpoints host-side the
            # same way (module_inject load_checkpoint + module_quantize).
            # NOTE: random init stays on jax PRNG for weight parity with
            # the on-device path — slow for 8B-scale demos (single-core
            # threefry); real large models load checkpoints (hf_loader)
            # or pre-quantized bin/dstpu_quantize trees instead.
            from deepspeed_tpu.ops.quantized_linear import \
                quantize_param_tree
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                host = jax.tree.map(cast, init_params(model, rng))
                host = quantize_param_tree(host, mode=config.weight_quant)
            rep = NamedSharding(mesh, P())
            # int8/fp8 under TP/EP: place quantized leaves with their
            # matching partition specs so per-chip weight HBM shrinks by
            # tp× instead of replicating (packed int4/fp6 planes can't
            # shard and are guarded to tp=ep=1 above, where rep == spec)
            sh = jax.tree.map(lambda _: rep, host) \
                if _has_packed_leaves(host) else \
                _shard_like(host, param_sh, mesh)
            return mesh, dtype, jax.device_put(host, sh), param_sh
        init = jax.jit(lambda r: jax.tree.map(cast, init_params(model, r)),
                       out_shardings=param_sh)
        params = init(rng)
    elif _is_quantized_tree(params):
        # pre-quantized tree (bin/dstpu_quantize output): int8/fp8
        # weight leaves place with their original partition specs
        # (_shard_like; scales and non-divisible leaves replicate);
        # packed int4/fp6 planes cannot shard and replicate wholesale
        if tp and _has_packed_leaves(params):
            raise ValueError(
                "pre-quantized packed (int4/fp6) params require "
                "tp_size=1 / a mesh with model axis 1: the packed "
                "nibble/6-bit planes cannot be sharded. Pre-quantized "
                "int8/fp8 trees DO serve under TP (their leaves place "
                "TP-sharded and route through qmatmul_tp)")
        if model.num_experts and mesh.shape["expert"] > 1 and \
                _has_packed_leaves(params):
            raise ValueError(
                "pre-quantized packed (int4/fp6) MoE params require an "
                "expert mesh axis of 1: the packed expert planes cannot "
                "shard over EP. Pre-quantized int8/fp8 MoE trees DO "
                "serve under EP (qmatmul_batched_ep)")
        if config.weight_quant:
            raise ValueError(
                "params are already quantized (scale leaves present); "
                "drop weight_quant from the config")
        rep = NamedSharding(mesh, P())
        from deepspeed_tpu.ops.quantized_linear import cast_quantized_tree
        host = cast_quantized_tree(params, dtype)
        sh = jax.tree.map(lambda _: rep, host) \
            if _has_packed_leaves(host) else \
            _shard_like(host, param_sh, mesh)
        return mesh, dtype, jax.device_put(host, sh), param_sh
    else:
        params = jax.device_put(jax.tree.map(cast, params), param_sh)
    if config.weight_quant:
        from deepspeed_tpu.ops.quantized_linear import quantize_param_tree
        params = quantize_param_tree(params, mode=config.weight_quant)
    return mesh, dtype, params, param_sh


def _sample(logits: jax.Array, rng: jax.Array, temperature: float,
            top_k: int, top_p: float) -> jax.Array:
    """logits [B, V] → token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceEngineTPU:
    """KV-cached generation over a mesh (reference inference/engine.py:40)."""

    def __init__(self, model: DecoderConfig,
                 config: Union[Dict[str, Any], DeepSpeedTPUInferenceConfig, None] = None,
                 params=None, rng: Optional[jax.Array] = None,
                 mesh=None):
        if isinstance(config, dict) or config is None:
            config = DeepSpeedTPUInferenceConfig(**(config or {}))
        if not model.causal:
            raise ValueError(
                "InferenceEngineTPU generates autoregressively; "
                "encoder (bidirectional) models have no decode loop — "
                "use EncoderInferenceTPU for BERT-class models")
        self.model_config = model
        self.config = config
        self.mesh, self.dtype, self.params, self._param_sh = \
            setup_engine_params(model, config, mesh, params, rng)

        # KV cache sharded over batch (DP axes) and kv heads (model axis
        # when divisible)
        tp = self.mesh.shape["model"] > 1
        kv_h = "model" if (tp and model.kv_heads % self.mesh.shape["model"]
                           == 0) else None
        self._cache_sh = NamedSharding(
            self.mesh, P(None, ("data", "data_inner", "expert"), None,
                         kv_h, None))

        # MoE models route every token deterministically at inference
        # (full capacity, no dropping — reference MoE inference EP,
        # inference/engine.py:260 _create_ep_parallel_group)
        self._moe_fn = None
        if model.num_experts:
            from deepspeed_tpu.parallel.moe import serving_moe_fn
            self._moe_fn = serving_moe_fn(
                model, config.weight_quant, self.params,
                ep=self.mesh.shape["expert"] > 1)
        self._step = jax.jit(
            partial(forward_with_cache, model, moe_fn=self._moe_fn),
            donate_argnums=(2,))
        self._samplers: Dict[Tuple[float, int, float], Any] = {}
        #: fused decode-loop jit cache; scan lengths bucket to 32s so
        #: varying max_new_tokens share compiles
        self._fused_fns: Dict[Any, Any] = {}
        log_dist(f"inference engine ready: tp={self.mesh.shape['model']} "
                 f"dtype={config.dtype} max_out={config.max_out_tokens}")

    _FUSED_STEP_BUCKET = 32

    def _fused_gen_fn(self, sb: int, mode):
        """jit: up to `sb` decode iterations in ONE device program (same
        trick as the ragged engine's fused loop — kills the 2+ host
        round-trips per token of the stepwise path). `mode` is the STATIC
        sampling shape; temperature/top_p are traced operands so
        per-request values don't recompile. NOTE: iterations beyond the
        requested step count (bucket padding) still run; their clamped
        `dynamic_update_slice` writes land IN the final cache slot — the
        cache is CORRUPT after this fn and must be discarded (outputs are
        correct because the live ys are emitted before those writes)."""
        key = (sb, mode)
        if key in self._fused_fns:
            return self._fused_fns[key]
        from deepspeed_tpu.inference.engine_v2 import _sample_tokens
        model = self.model_config
        moe_fn = self._moe_fn

        def fn(params, first, cache, start_len, temp, top_p, rng):
            def body(carry, i):
                tokens, cache, rng = carry
                logits, cache = forward_with_cache(
                    model, params, tokens[:, None], cache, start_len + i,
                    moe_fn=moe_fn)
                nxt, rng = _sample_tokens(logits, mode, temp, top_p, rng)
                return (nxt, cache, rng), nxt

            (_, cache, _), ys = lax.scan(
                body, (first, cache, rng),
                jnp.arange(sb, dtype=jnp.int32))
            return ys

        jitted = jax.jit(fn, donate_argnums=(2,))
        self._fused_fns[key] = jitted
        return jitted

    def _first_sampler(self, mode):
        """Sample the prefill logits with traced temperature/top_p (one
        compile per static mode, not per value)."""
        key = ("first", mode)
        if key not in self._fused_fns:
            from deepspeed_tpu.inference.engine_v2 import _sample_tokens
            self._fused_fns[key] = jax.jit(
                lambda lg, t, p, r: _sample_tokens(lg, mode, t, p, r)[0])
        return self._fused_fns[key]

    def _sampler(self, temperature: float, top_k: int, top_p: float):
        """jit cache keyed on sampling params (a fresh jit(partial(...))
        per call would re-trace every request)."""
        key = (temperature, top_k, top_p)
        if key not in self._samplers:
            self._samplers[key] = jax.jit(partial(
                _sample, temperature=temperature, top_k=top_k, top_p=top_p))
        return self._samplers[key]

    def _new_cache(self, batch: int, max_len: int):
        cache = init_kv_cache(self.model_config, batch, max_len, self.dtype)
        sh = self._cache_sh
        dp = self.mesh.shape["data"] * self.mesh.shape["data_inner"] * \
            self.mesh.shape["expert"]
        if batch % dp:
            # batch doesn't divide the DP axes (e.g. serving a single
            # prompt on a training mesh): replicate the batch dim
            spec = sh.spec
            sh = NamedSharding(self.mesh,
                               P(None, None, None, *spec[3:]))
        return jax.device_put(cache, {"k": sh, "v": sh})

    def generate(self, input_ids, max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None) -> np.ndarray:
        """input_ids: [B, T] int32 → [B, T + max_new_tokens] (right side
        fills with eos after termination when eos_token_id given)."""
        input_ids = np.asarray(input_ids, np.int32)
        b, t = input_ids.shape
        max_len = t + max_new_tokens
        cache = self._new_cache(b, max_len)
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        tokens = jnp.asarray(input_ids)
        logits, cache = self._step(self.params, tokens, cache,
                                   jnp.int32(0))
        if max_new_tokens > 1 and \
                not os.environ.get("DSTPU_NO_FUSED_DECODE"):
            return self._generate_fused(input_ids, logits, cache,
                                        max_new_tokens, temperature,
                                        top_k, top_p, eos_token_id, rng)
        out = [input_ids]
        done = np.zeros((b,), bool)
        cur_len = t
        sampler = self._sampler(temperature, top_k, top_p)
        for i in range(max_new_tokens):
            rng, sub = jax.random.split(rng)
            nxt = sampler(logits, sub)
            nxt_np = np.asarray(jax.device_get(nxt))
            if eos_token_id is not None:
                nxt_np = np.where(done, eos_token_id, nxt_np)
                done |= nxt_np == eos_token_id
            out.append(nxt_np[:, None])
            last = (i == max_new_tokens - 1) or \
                (eos_token_id is not None and done.all())
            if last:    # the next forward's logits would never be sampled
                break
            logits, cache = self._step(
                self.params, jnp.asarray(nxt_np[:, None]), cache,
                jnp.int32(cur_len))
            cur_len += 1
        result = np.concatenate(out, axis=1)
        if result.shape[1] < max_len:
            # early EOS exit: pad to the documented [B, T+max_new_tokens]
            pad = np.full((b, max_len - result.shape[1]),
                          eos_token_id if eos_token_id is not None else 0,
                          np.int32)
            result = np.concatenate([result, pad], axis=1)
        return result

    def _generate_fused(self, input_ids, logits, cache, max_new_tokens,
                        temperature, top_k, top_p, eos_token_id, rng):
        """Decode loop as one device program; eos handled by host-side
        truncation of the fetched token matrix (the full window runs on
        device — latency traded for the removed per-token round-trips)."""
        b, t = input_ids.shape
        steps = max_new_tokens - 1
        sb = -(-steps // self._FUSED_STEP_BUCKET) * self._FUSED_STEP_BUCKET
        mode = ("argmax",) if temperature == 0.0 \
            else ("sample", int(top_k), top_p < 1.0)
        temp = jnp.float32(temperature if temperature else 1.0)
        tp = jnp.float32(top_p)
        rng, sub, loop_rng = jax.random.split(rng, 3)
        first = self._first_sampler(mode)(logits, temp, tp, sub)
        ys = self._fused_gen_fn(sb, mode)(
            self.params, first, cache, jnp.int32(t), temp, tp, loop_rng)
        gen = np.concatenate(
            [np.asarray(jax.device_get(first))[None],
             np.asarray(jax.device_get(ys))[:steps]], axis=0).T  # [B, new]
        if eos_token_id is not None:
            seen = np.cumsum(gen == eos_token_id, axis=1)
            # positions strictly after the first eos become eos
            gen = np.where(seen - (gen == eos_token_id) > 0,
                           eos_token_id, gen)
        return np.concatenate([input_ids, gen.astype(np.int32)], axis=1)

    def forward(self, input_ids) -> jax.Array:
        """Full-sequence logits (no cache) — parity with engine forward."""
        if not hasattr(self, "_full_forward"):
            from deepspeed_tpu.models.transformer import forward
            self._full_forward = jax.jit(partial(forward, self.model_config))
        return self._full_forward(self.params,
                                  jnp.asarray(input_ids, jnp.int32))


def init_inference(model: DecoderConfig, config=None, **kwargs
                   ) -> InferenceEngineTPU:
    """Reference deepspeed/__init__.py:302."""
    return InferenceEngineTPU(model, config, **kwargs)
