"""Encoder (BERT-class) serving engine.

Reference analogue: DeepSpeed v1 inference served encoders through the
kernel-injection containers (module_inject/containers/bert.py,
distil_bert.py) — batched scoring, no decode loop. Here the engine owns
the same concerns as `InferenceEngineTPU` minus the KV cache: TP-aware
parameter sharding (GSPMD from `partition_specs`), dtype policy,
weight-only quantization, and shape-bucketed jit so variable-length
batches reuse compiles.

Padding is handled INSIDE the engine: inputs are padded to (batch
bucket, 64·k sequence bucket) and a key mask covers the pad — callers
can pass ragged python lists and correctness does not depend on them
building the attention_mask themselves (for padded bidirectional
attention the mask is correctness-critical, not an optimization).
"""

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.transformer import (DecoderConfig, forward,
                                              forward_hidden)
from deepspeed_tpu.utils.logging import log_dist


class EncoderInferenceTPU:
    """Batched encoder scoring: ``__call__`` returns MLM logits (or
    final hidden states) for right-padded batches of any length."""

    _SEQ_BUCKET = 64

    def __init__(self, model: DecoderConfig,
                 config: Union[Dict[str, Any], None] = None,
                 params=None, rng: Optional[jax.Array] = None,
                 mesh=None):
        from deepspeed_tpu.inference.engine import DeepSpeedTPUInferenceConfig
        if isinstance(config, dict) or config is None:
            config = DeepSpeedTPUInferenceConfig(**(config or {}))
        if model.causal:
            raise ValueError(
                "EncoderInferenceTPU is for bidirectional (causal=False) "
                "models; use InferenceEngineTPU / the ragged engine for "
                "decoder models")
        self.model_config = model
        self.config = config
        from deepspeed_tpu.inference.engine import setup_engine_params
        self.mesh, self.dtype, self.params, self._param_sh = \
            setup_engine_params(model, config, mesh, params, rng)
        self._data_sh = NamedSharding(
            self.mesh, P(("data", "data_inner", "expert"), None))
        self._fns: Dict[Any, Any] = {}
        log_dist(f"encoder engine ready: tp={self.mesh.shape['model']} "
                 f"dtype={config.dtype}")

    def _fn(self, b: int, t: int, hidden: bool):
        key = (b, t, hidden)
        if key not in self._fns:
            cfg = self.model_config

            def run(params, tokens, mask, types):
                if hidden:
                    out, _ = forward_hidden(cfg, params, tokens,
                                            token_type_ids=types,
                                            attention_mask=mask)
                    return out
                return forward(cfg, params, tokens, token_type_ids=types,
                               attention_mask=mask)

            self._fns[key] = jax.jit(run)
        return self._fns[key]

    def __call__(self, input_ids: Union[np.ndarray, Sequence[Sequence[int]]],
                 attention_mask: Optional[np.ndarray] = None,
                 token_type_ids: Optional[np.ndarray] = None,
                 output: str = "logits") -> List[np.ndarray]:
        """Score a batch. ``input_ids``: [B, T] array OR a ragged list of
        token lists (engine right-pads + masks). Returns a list of B
        arrays, each trimmed to its true length: [t_i, V] logits
        (``output='logits'``) or [t_i, D] hidden (``output='hidden'``).
        """
        if output not in ("logits", "hidden"):
            raise ValueError(f"output must be 'logits'|'hidden', "
                             f"got '{output}'")
        ragged = not isinstance(input_ids, np.ndarray)
        if ragged:
            lens = [len(s) for s in input_ids]
            tmax = max(lens)
            ids = np.zeros((len(lens), tmax), np.int32)
            mask = np.zeros((len(lens), tmax), np.int32)
            for i, s in enumerate(input_ids):
                ids[i, :len(s)] = np.asarray(s, np.int32)
                if attention_mask is not None:
                    # honor a caller mask row-by-row (a sequence may
                    # itself contain pad tokens the caller masks out)
                    mask[i, :len(s)] = np.asarray(attention_mask[i],
                                                  np.int32)[:len(s)]
                else:
                    mask[i, :len(s)] = 1
            # lens stay the GIVEN sequence lengths: outputs are trimmed
            # to what the caller passed, masked-out positions included
            # (an interior pad still occupies its slot)
            if token_type_ids is not None:
                tt = np.zeros((len(lens), tmax), np.int32)
                for i, s in enumerate(token_type_ids):
                    tt[i, :len(s)] = np.asarray(s, np.int32)
                token_type_ids = tt
            input_ids, attention_mask = ids, mask
        else:
            input_ids = np.asarray(input_ids, np.int32)
            lens = [input_ids.shape[1]] * input_ids.shape[0] \
                if attention_mask is None else \
                [int(m.sum()) for m in np.asarray(attention_mask)]
        b, t = input_ids.shape
        if t > self.model_config.max_seq_len:
            raise ValueError(f"sequence length {t} exceeds model "
                             f"max_seq_len {self.model_config.max_seq_len}")

        # bucket shapes so variable-length batches share compiles
        tb = min(-(-t // self._SEQ_BUCKET) * self._SEQ_BUCKET,
                 self.model_config.max_seq_len)
        bb = 1 << (b - 1).bit_length()
        dp = (self.mesh.shape["data"] * self.mesh.shape["data_inner"]
              * self.mesh.shape["expert"])
        bb = -(-bb // dp) * dp
        ids = np.zeros((bb, tb), np.int32)
        ids[:b, :t] = input_ids
        mask = np.zeros((bb, tb), np.int32)
        if attention_mask is not None:
            mask[:b, :t] = np.asarray(attention_mask, np.int32)
        else:
            mask[:b, :t] = 1
        types = np.zeros((bb, tb), np.int32)
        if token_type_ids is not None:
            types[:b, :t] = np.asarray(token_type_ids, np.int32)

        put = partial(jax.device_put, device=self._data_sh)
        out = self._fn(bb, tb, output == "hidden")(
            self.params, put(jnp.asarray(ids)), put(jnp.asarray(mask)),
            put(jnp.asarray(types)))
        out = np.asarray(out)
        return [out[i, :lens[i]] for i in range(b)]


def init_encoder_inference(model: DecoderConfig, config=None, **kw
                           ) -> EncoderInferenceTPU:
    """Parity-named constructor (reference ``deepspeed.init_inference``
    routed encoders through the same entrypoint)."""
    return EncoderInferenceTPU(model, config, **kw)
