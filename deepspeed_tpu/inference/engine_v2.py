"""Ragged-batching inference engine — the FastGen-core engine.

Reference: ``InferenceEngineV2`` (deepspeed/inference/v2/engine_v2.py:30) —
``put(batch_uids, batch_tokens)`` runs one forward over a ragged batch,
``query``/``can_schedule`` expose capacity, ``flush`` releases finished
sequences. The reference's paged-KV CUDA kernels (kernels/ragged_ops/) map
to :mod:`deepspeed_tpu.ops.paged_attention`: a Pallas kernel whose KV DMAs
are addressed by a scalar-prefetched page table, plus an XLA gather path
for prefill chunks and non-TPU backends.

Scheduling is Dynamic-SplitFuse style (RaggedScheduler): each engine step
mixes prefill chunks and single-token decodes into one ragged batch, so
decode latency is bounded while prefill throughput stays high. Shapes are
bucketed (batch rows to powers of two, chunk width to {1, prefill_chunk})
so jit traces a handful of programs, not one per batch composition.
"""

import os
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.config.config_utils import TPUConfigModel
from deepspeed_tpu.inference.ragged import (DSStateManager, RaggedBatch,
                                            RaggedScheduler)
from deepspeed_tpu.models.transformer import (DecoderConfig, _mlp, _norm,
                                              block_combine,
                                              attn_out_project, embed_tokens,
                                              init_params,
                                              lm_logits, qkv_project,
                                              rope_table)
from deepspeed_tpu.ops import paged_attention as pa
from deepspeed_tpu.utils.logging import log_dist


class RaggedInferenceConfig(TPUConfigModel):
    """Reference: inference/v2/config_v2.py (RaggedInferenceEngineConfig)."""
    dtype: str = "bfloat16"
    max_sequences: int = 64          #: concurrent sequences (state slots)
    num_blocks: int = 512            #: KV arena pages
    block_size: int = 128            #: tokens per page
    max_seq_len: int = 4096          #: page-table width = ceil(/block_size)
    max_batch_tokens: int = 2048     #: scheduler token budget per step
    prefill_chunk: int = 256         #: SplitFuse chunk width
    use_pallas: Optional[bool] = None  #: None = auto (TPU only)
    weight_quant: Optional[str] = None  #: "int8"|"fp8"|"int4"|"fp6" weight-only


def ragged_forward(cfg: DecoderConfig, params, arena, tokens: jax.Array,
                   counts: jax.Array, starts: jax.Array,
                   page_table: jax.Array, use_pallas: bool = False,
                   moe_fn=None,
                   fresh_prefill: Union[bool, str] = False):
    """One forward over a ragged batch against the paged KV arena.

    tokens: [n, c] (row i valid for j < counts[i]); starts: [n] tokens
    already cached; page_table: [n, mb]. Returns (last-token logits [n, V]
    fp32, updated arena). Rows with counts == 0 produce garbage logits the
    caller ignores.

    ``fresh_prefill`` (STATIC): False → every chunk attends through the
    paged arena (the original path). "fresh" → promise that every row
    has starts == 0: attention runs causally WITHIN the chunk and never
    reads the arena. "split" → history attends the PRE-write arena and
    the within-chunk causal part is merged by logsumexp. Both variants
    remove the per-layer write→read dependency on the ~GB arena, which
    XLA otherwise serializes (measured 395 → ~200 ms on a 16x512
    prefill step, v5e 1.27B).
    """
    if fresh_prefill is True:   # pre-three-mode boolean API
        fresh_prefill = "fresh"
    if cfg.pos_emb == "alibi":
        # the paged kernels have no score-bias port; serving BLOOM-class
        # models needs the v1 cached engine (forward_with_cache applies
        # alibi internally)
        raise NotImplementedError(
            "ragged/paged inference does not support ALiBi models; use "
            "InferenceEngineTPU (v1 KV-cache path) for BLOOM-class models")
    n, c = tokens.shape
    positions = starts[:, None] + jnp.broadcast_to(
        jnp.arange(c, dtype=jnp.int32)[None], (n, c))
    if cfg.pos_emb == "learned":
        emb_pos = jnp.minimum(positions, params["embed"]["pos"].shape[0] - 1)
    else:
        emb_pos = positions
    x = embed_tokens(cfg, params["embed"], tokens, emb_pos,
                     params.get("embed_norm"))
    if cfg.pos_emb == "rope":
        sin, cos = rope_table(cfg, positions)
    else:
        sin = cos = jnp.zeros((n, c, 0), x.dtype)

    attend = pa.paged_attention if use_pallas else pa.paged_attention_xla
    # per-layer page stride in the FLAT block pool (init_arena docstring:
    # the pool is a scan CARRY so decode updates it in place; a stacked
    # per-layer arena would be copied wholesale every step)
    num_layers = cfg.num_layers
    stride = arena["k"].shape[1] // num_layers          # num_blocks + 1

    def body(carry, layer):
        x, ak, av = carry
        lp, l_idx = layer
        off = l_idx * stride
        pt_l = page_table + off       # padded entries → this layer's trash
        h_in = _norm(cfg, lp["ln1"], x)
        q, k, v = qkv_project(cfg, lp["attn"], h_in, sin, cos)
        split = fresh_prefill == "split" and c > 1
        if split:
            # continuation / SplitFuse-mixed chunk: the history part
            # reads the PRE-write arena — computed BEFORE the write so
            # no write→read serialization. Fresh rows mixed in have
            # empty history (lse ≈ -1e30 → weight 0); decode rows ride
            # along as width-1 chunks.
            out_h, lse_h = pa.paged_attention_hist_xla(
                q, ak, av, pt_l, starts)
        ak, av = pa.write_kv(ak, av, k, v, pt_l, starts, counts,
                             trash_block=off + stride - 1)
        if fresh_prefill == "fresh":
            # starts == 0 everywhere: the chunk IS the whole history —
            # plain causal attention over it; padded-tail rows produce
            # garbage outputs nothing reads (their KV went to trash)
            if use_pallas:
                from deepspeed_tpu.ops.flash_attention import flash_attention
                out = flash_attention(q, k, v, causal=True)
            else:
                from deepspeed_tpu.models.transformer import \
                    dot_product_attention
                out = dot_product_attention(q, k, v, causal=True)
        elif split:
            if use_pallas:
                from deepspeed_tpu.ops.flash_attention import \
                    flash_attention_with_lse
                out_c, lse_c = flash_attention_with_lse(q, k, v,
                                                        causal=True)
            else:
                out_c, lse_c = pa.causal_attention_with_lse(q, k, v)
            out = pa.merge_attention(out_h, lse_h, out_c,
                                     lse_c).astype(q.dtype)
        else:
            out = attend(q, ak, av, pt_l, starts, counts)
        attn_out = attn_out_project(cfg, lp["attn"], out)
        h_out, _aux = block_combine(cfg, lp, x, h_in, attn_out, moe_fn)
        return (h_out, ak, av), None

    (x, ak, av), _ = lax.scan(
        body, (x, arena["k"], arena["v"]),
        (params["layers"], jnp.arange(num_layers, dtype=jnp.int32)))
    x = _norm(cfg, params["final_norm"], x)
    last = jnp.maximum(counts - 1, 0)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
    logits = lm_logits(cfg, params, x_last)[:, 0]
    return logits, {"k": ak, "v": av}


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _dispatch_count(name: str, by: int = 1) -> None:
    """Bump a ``dispatch/*`` counter (lazy import: telemetry pulls in the
    whole diagnostics stack, which must not load at engine-import time)."""
    from deepspeed_tpu.telemetry.registry import registry
    registry.counter(name).inc(by)


def _sample_tokens(logits, mode, temperature, top_p, rng):
    """Shared on-device sampling (mode is STATIC: ('argmax',) or
    ('sample', top_k, use_top_p); temperature/top_p are traced scalars so
    per-request changes don't recompile)."""
    if mode[0] == "argmax":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
    _, top_k, use_top_p = mode
    lg = logits / temperature
    if top_k > 0:
        kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
        lg = jnp.where(lg < kth, -1e30, lg)
    if use_top_p:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_lg, cutoff_idx, axis=-1)
        lg = jnp.where(lg < cutoff, -1e30, lg)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, lg, axis=-1).astype(jnp.int32), rng


class FusedDecodeUnavailable(RuntimeError):
    """Raised when the fused decode fast path can't serve a request.
    ``doomed=True`` means the stepwise loop would ALSO fail (the decode
    window overruns max_seq_len with no early exit possible), so the
    caller should error out cleanly instead of falling back."""

    def __init__(self, msg: str, doomed: bool = False):
        super().__init__(msg)
        self.doomed = doomed


class RaggedInferenceEngineTPU:
    """Continuous-batching engine over the paged arena (reference
    inference/v2/engine_v2.py:30)."""

    def __init__(self, model: DecoderConfig,
                 config: Union[Dict[str, Any], RaggedInferenceConfig,
                               None] = None,
                 params=None, rng: Optional[jax.Array] = None):
        if isinstance(config, dict) or config is None:
            config = RaggedInferenceConfig(**(config or {}))
        if not model.causal or model.layer_window_pattern is not None:
            # the paged kernels are full-causal per layer: encoders have
            # no decode loop at all, and GPT-Neo's local layers would
            # silently attend beyond their window
            raise NotImplementedError(
                "ragged/paged inference supports full-causal decoder "
                "models only (got "
                f"causal={model.causal}, layer_window_pattern="
                f"{model.layer_window_pattern}); use InferenceEngineTPU "
                "for GPT-Neo-class models")
        if model.sliding_window is not None and \
                config.max_seq_len > model.sliding_window:
            # the paged kernels attend the full page table; beyond the
            # window that silently diverges from the training forward
            raise NotImplementedError(
                f"ragged/paged inference has no sliding-window mask: "
                f"max_seq_len {config.max_seq_len} exceeds sliding_window "
                f"{model.sliding_window}; cap max_seq_len at the window "
                f"or use InferenceEngineTPU")
        self.model_config = model
        self.config = config
        from deepspeed_tpu.ops.quantized_linear import validate_weight_quant
        validate_weight_quant(config.weight_quant)
        from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
        if has_mesh() and get_mesh().shape.get("model", 1) > 1:
            # only UNPACKED quantization shards (qmatmul_tp); packed
            # int4/fp6 always run replicated, so they stay legal here.
            # Check the param tree too: pre-quantized dstpu_quantize
            # trees arrive with weight_quant unset.
            from deepspeed_tpu.inference.engine import (
                _has_packed_leaves, _is_quantized_tree)
            unpacked_q = config.weight_quant in ("int8", "fp8") or (
                params is not None and _is_quantized_tree(params)
                and not _has_packed_leaves(params))
            if unpacked_q:
                raise ValueError(
                    "RaggedInferenceEngineTPU is single-shard: int8/fp8 "
                    "quantized linears route through qmatmul_tp, which "
                    "would shard_map over the ambient mesh's model axis "
                    f"(size {get_mesh().shape['model']}). Build a mesh "
                    "with model=1 for the ragged engine, or use "
                    "InferenceEngineTPU for TP serving.")
        self.dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                      "float16": jnp.float16}[config.dtype]
        if config.use_pallas is None:
            self.use_pallas = pa.supported(model.head_dim,
                                           config.block_size)
        else:
            self.use_pallas = bool(config.use_pallas)

        self.state = DSStateManager(max_sequences=config.max_sequences,
                                    num_blocks=config.num_blocks,
                                    block_size=config.block_size)
        self.scheduler = RaggedScheduler(
            self.state, max_batch_tokens=config.max_batch_tokens,
            prefill_chunk=config.prefill_chunk)
        self.mb = -(-config.max_seq_len // config.block_size)

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        cast = lambda t: jax.tree.map(
            lambda x: x.astype(self.dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, t)
        from deepspeed_tpu.inference.engine import _is_quantized_tree
        from deepspeed_tpu.ops.quantized_linear import (
            cast_quantized_tree, quantize_param_tree)
        # explicit accelerator target: plain jax.device_put(x) is an
        # IDENTITY for already-placed arrays, so host-built trees would
        # silently stay CPU-resident and stream per step
        dev0 = jax.devices()[0]
        if params is None and config.weight_quant:
            # init + quantize on HOST, ship only the quantized tree (same
            # rationale as the v1 engine: int4 llama-8B serves in ~5 GB
            # but would OOM materialized bf16-first on a 16 GB chip).
            # NOTE: random init is kept on jax PRNG for weight parity with
            # the on-device path — slow for 8B-scale demos (single-core
            # threefry); real large models load checkpoints (hf_loader)
            # or pre-quantized trees instead.
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                host = quantize_param_tree(cast(init_params(model, rng)),
                                           mode=config.weight_quant)
            self.params = jax.tree.map(
                lambda v: jax.device_put(v, dev0), host)
        elif params is not None and _is_quantized_tree(params):
            # pre-quantized (bin/dstpu_quantize / host-quantized) tree:
            # dtype policy must not touch scales / fp8 / packed planes
            if config.weight_quant:
                raise ValueError(
                    "params are already quantized (scale leaves present); "
                    "drop weight_quant from the config")
            self.params = jax.tree.map(
                lambda v: jax.device_put(v, dev0),
                cast_quantized_tree(params, self.dtype))
        else:
            self.params = cast(params if params is not None
                               else init_params(model, rng))
            if config.weight_quant:
                self.params = quantize_param_tree(self.params,
                                                  mode=config.weight_quant)
        self.arena = pa.init_arena(model.num_layers, model.kv_heads,
                                   config.num_blocks, config.block_size,
                                   model.head_dim, self.dtype)
        moe_fn = None
        if model.num_experts:
            from deepspeed_tpu.parallel.moe import serving_moe_fn
            from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
            # same EP guard as the v1 engine: an ambient expert axis > 1
            # means capacity dispatch (the ragged engine itself is
            # single-shard, but the ambient mesh drives GSPMD layouts)
            ep = has_mesh() and get_mesh().shape.get("expert", 1) > 1
            moe_fn = serving_moe_fn(model, config.weight_quant,
                                    self.params, ep=ep)
        self._moe_fn = moe_fn
        #: jit cache keyed on (n_bucket, c_bucket, mode, fresh) — the
        #: fresh=True/False split legitimately doubles prefill-shape
        #: compiles (arena-reading vs within-chunk attention programs).
        #: The step takes
        #: ONE packed int32 vector (tokens|counts|starts|page_table): four
        #: separate small host→device uploads per decode step each pay a
        #: full dispatch round-trip on remote runtimes (measured 1.5 s vs
        #: 0.9 ms per step through the axon tunnel)
        self._step_fns: Dict[Any, Any] = {}
        #: fused decode-loop jit cache keyed on (n_bucket, steps, mode)
        self._fused_fns: Dict[Any, Any] = {}
        #: jit for prefix-cache copy-on-write page duplication
        self._copy_pages_fn = None
        self._rng_dev = rng          # defaulted to PRNGKey(0) above
        self._temperature = 1.0      # dynamic sampling scalars, packed
        self._top_p = 1.0            # into the step upload
        log_dist(f"ragged engine ready: blocks={config.num_blocks}x"
                 f"{config.block_size} pallas={self.use_pallas} "
                 f"dtype={config.dtype}")

    def _step_fn(self, nb: int, cb: int, mode, fresh: bool = False):
        """mode: None → raw logits; ("argmax",) → greedy token ids;
        ("sample", top_k, use_top_p) → sampled token ids. Token modes
        fetch [n] int32 instead of the [n, V] fp32 logits (8 MB per step
        for a 128k vocab); the sampling rng lives ON DEVICE and is split
        inside the step (no per-step key upload). Temperature/top_p are
        DYNAMIC scalars bitcast into the packed vector, so changing them
        per request does NOT recompile the model forward (only top_k and
        the top-p on/off switch are static)."""
        key = (nb, cb, mode, fresh)
        if key in self._step_fns:
            return self._step_fns[key]
        # jit-cache miss = one XLA compile; attribute it to the bucket
        # shape so a recompile storm names the drifting request shape
        from deepspeed_tpu.telemetry import compile_monitor
        compile_monitor.count_trace(
            "serving/step_fn", detail={"n_bucket": nb, "chunk": cb,
                                       "mode": str(mode), "fresh": fresh})
        mb = self.mb
        model = self.model_config

        def fn(params, arena, packed, rng):
            off = 0
            tokens = packed[off:off + nb * cb].reshape(nb, cb)
            off += nb * cb
            counts = packed[off:off + nb]
            off += nb
            starts = packed[off:off + nb]
            off += nb
            pt = packed[off:off + nb * mb].reshape(nb, mb)
            off += nb * mb
            logits, arena = ragged_forward(
                model, params, arena, tokens, counts, starts, pt,
                use_pallas=self.use_pallas, moe_fn=self._moe_fn,
                fresh_prefill=fresh)
            if mode is None:
                return logits, rng, arena
            temperature = lax.bitcast_convert_type(packed[off],
                                                   jnp.float32)
            top_p = lax.bitcast_convert_type(packed[off + 1], jnp.float32)
            out, rng = _sample_tokens(logits, mode, temperature, top_p,
                                      rng)
            return out, rng, arena

        jitted = jax.jit(fn, donate_argnums=(1,))
        self._step_fns[key] = jitted
        return jitted

    def cost_records(self, mode=("argmax",), refresh: bool = False):
        """Compile-time cost records for the prefill/decode bucket
        programs (telemetry/explain.py): per-program FLOPs / bytes /
        roofline ``predicted_s``. Lazily computed and cached — the first
        call costs two abstract XLA compiles; the frontend's SLO
        admission reads ``predicted_s`` from here (0.0 when the platform
        has no peak numbers, e.g. CPU)."""
        if refresh or getattr(self, "_cost_records", None) is None:
            from deepspeed_tpu.telemetry.explain import explain_serving
            self._cost_records = explain_serving(self, mode=mode)
        return self._cost_records

    def _page_table(self, uids: List[int], nb: int) -> np.ndarray:
        """[nb, mb] physical page ids; padding rows/entries point at the
        pool's trash sentinel (num_blocks)."""
        pt = np.full((nb, self.mb), self.config.num_blocks, np.int32)
        for i, uid in enumerate(uids):
            blocks = self.state.seqs[uid].blocks
            pt[i, :len(blocks)] = blocks
        return pt

    def _pack(self, batch: RaggedBatch, nb: int, cb: int) -> np.ndarray:
        n = len(batch.uids)
        tokens = np.zeros((nb, cb), np.int32)
        c = batch.token_ids.shape[1]
        tokens[:n, :c] = batch.token_ids
        counts = np.zeros((nb,), np.int32)
        counts[:n] = batch.token_counts
        starts = np.zeros((nb,), np.int32)
        starts[:n] = batch.start_positions
        pt = self._page_table(batch.uids, nb)
        sampling = np.asarray([self._temperature, self._top_p],
                              np.float32).view(np.int32)
        return np.concatenate([tokens.ravel(), counts, starts, pt.ravel(),
                               sampling])

    # -- capacity API (reference engine_v2.py:158–184) ----------------------

    def can_schedule(self, n_tokens: int) -> bool:
        return self.state.can_schedule(n_tokens)

    def query(self) -> Dict[str, int]:
        return {"free_blocks": self.state.allocator.free_blocks,
                "free_sequences": self.config.max_sequences -
                len(self.state.seqs),
                "block_size": self.config.block_size}

    def flush(self, uid: int) -> None:
        self.state.flush(uid)

    # -- the engine step (reference put():107) ------------------------------

    def _validate_put(self, uids: List[int], tokens_list) -> None:
        # enforce max_seq_len up front: past it the page table row would
        # overflow (and write_kv's index clamp would misroute KV silently).
        # Totals accumulate WITHIN this call too, so duplicate uids in one
        # put() can't slip past the check.
        pending: Dict[int, int] = {}
        for uid, toks in zip(uids, tokens_list):
            have = pending.get(
                uid, len(self.state.seqs[uid].tokens)
                if uid in self.state.seqs else 0)
            total = have + len(np.asarray(toks).reshape(-1))
            if total > self.config.max_seq_len:
                raise ValueError(
                    f"sequence {uid} would reach {total} tokens, over "
                    f"max_seq_len={self.config.max_seq_len}; flush it or "
                    f"raise max_seq_len")
            pending[uid] = total

    def put(self, uids: List[int], tokens_list) -> Dict[int, np.ndarray]:
        """Queue new tokens, then run engine steps until every queued token
        has been consumed; returns {uid: last-token logits} for sequences
        whose pending tokens were exhausted this call."""
        self._validate_put(uids, tokens_list)
        self.scheduler.put(uids, tokens_list)
        out: Dict[int, np.ndarray] = {}
        while True:
            res = self.step()
            if res is None:
                break
            out.update(res)
        return out

    def _put_tokens(self, uids: List[int], tokens_list,
                    mode=("argmax",)) -> Dict[int, int]:
        """put() for serving: samples ON DEVICE and returns
        {uid: next_token_id} — fetching [n] int32 per step instead of the
        [n, vocab] logits (8 MB/step for a 128k vocab)."""
        self._validate_put(uids, tokens_list)
        self.scheduler.put(uids, tokens_list)
        out: Dict[int, int] = {}
        while True:
            batch = self.scheduler.next_batch()
            if batch is None:
                break
            toks = self._run(batch, mode=mode)
            self.scheduler.mark_scheduled(batch)
            for i, uid in enumerate(batch.uids):
                if self.state.seqs[uid].pending == 0:
                    out[uid] = int(toks[i])
        return out

    def step(self) -> Optional[Dict[int, np.ndarray]]:
        """One ragged forward over the next scheduled batch; None when no
        work is pending."""
        batch = self.scheduler.next_batch()
        if batch is None:
            return None
        logits = self._run(batch)
        self.scheduler.mark_scheduled(batch)
        out: Dict[int, np.ndarray] = {}
        for i, uid in enumerate(batch.uids):
            if self.state.seqs[uid].pending == 0:
                out[uid] = logits[i]
        return out

    def step_with_budget(self, budget: Optional[int] = None,
                         mode=("argmax",), max_steps: int = 1,
                         row_limits: Optional[Dict[int, int]] = None,
                         eos_ids: Optional[Dict[int, int]] = None
                         ) -> Optional[Dict[int, Any]]:
        """One engine step packing at most ``budget`` tokens (None → the
        scheduler's max_batch_tokens). The serving frontend's entry point:
        the SplitFuse policy installed on ``self.scheduler`` decides the
        prefill/decode mix, this just runs whatever it packed. Returns
        {uid: next_token_id} (or {uid: logits} with mode=None) for rows
        whose pending tokens were exhausted; None when idle.

        ``max_steps > 1`` arms the decode MEGASTEP: when the scheduler's
        selection comes back decode-only, up to ``max_steps`` single-token
        iterations run in ONE device program (the host syncs once per K
        tokens instead of once per token) and the return value becomes
        ``{uid: [token, ...]}`` — a list per row, 1..K tokens, every one
        of them already backed by KV in the arena except the last (which
        the caller feeds back, exactly like the single-token contract).
        ``row_limits`` caps the tokens a row may emit (its remaining
        max_new_tokens budget); ``eos_ids`` maps uid → eos token id so a
        row retires mid-megastep without burning its tail. Mixed
        prefill/decode selections, ``mode=None`` (logits), and
        ``max_steps == 1`` all take the unchanged stepwise path (with
        lists still returned when ``max_steps > 1`` was requested, so
        callers see ONE shape).
        """
        batch = self.scheduler.next_batch(budget=budget)
        if batch is None:
            return None
        megastep = max_steps > 1 and mode is not None
        if megastep:
            out = self._try_megastep(batch, max_steps, mode, row_limits,
                                     eos_ids)
            if out is not None:
                return out
        res = self._run(batch, mode=mode)
        self.scheduler.mark_scheduled(batch)
        out = {}
        for i, uid in enumerate(batch.uids):
            if self.state.seqs[uid].pending == 0:
                if mode is None:
                    out[uid] = res[i]
                else:
                    out[uid] = [int(res[i])] if megastep else int(res[i])
        return out

    def _try_megastep(self, batch: RaggedBatch, k: int, mode,
                      row_limits: Optional[Dict[int, int]],
                      eos_ids: Optional[Dict[int, int]]
                      ) -> Optional[Dict[int, List[int]]]:
        """Run ``batch`` as one fused decode window of up to ``k`` tokens
        per row; None → not applicable (caller falls through to the
        stepwise path with the batch ALREADY selected — selecting twice
        would double-advance the SplitFuse round-robin).

        Applicable iff the selection is pure decode: every row is a
        single-token chunk covering its whole pending queue. Serving
        descriptors hold the fed token IN ``seq.tokens`` (the frontend
        extends before scheduling), so starts/page math here differs from
        ``_fused_decode``'s generate-path convention where the fed token
        lives outside the descriptor.
        """
        n = len(batch.uids)
        if n == 0 or batch.token_ids.shape[1] != 1:
            return None
        for i, uid in enumerate(batch.uids):
            if int(batch.token_counts[i]) != 1 or \
                    self.state.seqs[uid].pending != 1:
                return None
        # per-row window: requested k, clipped by the row's remaining
        # token budget and by max_seq_len headroom (len(tokens) already
        # counts the fed token, and a continuing row feeds one more)
        lim: List[int] = []
        for uid in batch.uids:
            seq = self.state.seqs[uid]
            r = k
            if row_limits is not None and uid in row_limits:
                r = min(r, int(row_limits[uid]))
            r = min(r, self.config.max_seq_len - len(seq.tokens))
            if r < 1:
                return None
            lim.append(r)
        limit = max(lim)
        if limit < 2:
            return None              # degenerate megastep — stepwise wins
        bs = self.state.allocator.block_size
        need: List[int] = []
        for uid, r in zip(batch.uids, lim):
            seq = self.state.seqs[uid]
            # KV high-water mark: seen_tokens rows exist, the window adds
            # up to r more (fed token + r-1 continuation feeds)
            need.append(-(-(seq.seen_tokens + r) // bs) - len(seq.blocks))
        if sum(need) > self.state.allocator.free_blocks:
            return None
        for uid, c in zip(batch.uids, need):
            if c > 0:
                self.state.seqs[uid].blocks.extend(
                    self.state.allocator.allocate(c))

        nb = _bucket(n)
        # pow2 scan buckets (not _FUSED_STEP_BUCKET multiples): the rng
        # splits once per scan slot incl. dead ones, so aligned pow2
        # windows keep sampled streams identical across K choices
        sb = _bucket(limit)
        tokens0 = np.zeros((nb,), np.int32)
        starts0 = np.zeros((nb,), np.int32)
        live = np.zeros((nb,), np.int32)
        bud = np.zeros((nb,), np.int32)
        eos = np.full((nb,), -1, np.int32)
        for i, uid in enumerate(batch.uids):
            seq = self.state.seqs[uid]
            tokens0[i] = seq.tokens[-1]
            starts0[i] = seq.seen_tokens
            live[i] = 1
            bud[i] = lim[i]
            if eos_ids is not None and eos_ids.get(uid) is not None:
                eos[i] = int(eos_ids[uid])
        pt = self._page_table(batch.uids, nb)
        mb_need = int(-(-(int(starts0.max()) + limit) // bs))
        mb_b = min(self.mb, -(-mb_need // 4) * 4)
        pt = pt[:, :mb_b]
        from deepspeed_tpu import telemetry
        with telemetry.tracer.span("serving/megastep", n=n, k=int(limit),
                                   scan_bucket=sb):
            ys, counts, self._rng_dev, self.arena = self._fused_decode_fn(
                nb, sb, mode)(
                    self.params, self.arena, jnp.asarray(tokens0),
                    jnp.asarray(starts0), jnp.asarray(live),
                    jnp.asarray(pt), jnp.int32(limit), jnp.asarray(bud),
                    jnp.asarray(eos), jnp.float32(self._temperature),
                    jnp.float32(self._top_p), self._rng_dev)
            ys, counts = jax.device_get((ys, counts))   # ONE sync for K
        ys = np.asarray(ys)
        counts = np.asarray(counts)
        _dispatch_count("dispatch/host_calls")
        _dispatch_count("dispatch/scan_steps", sb)
        _dispatch_count("dispatch/dead_steps", sb - limit)
        _dispatch_count("dispatch/megastep_launches")
        self.scheduler.mark_scheduled(batch)          # fed token consumed
        out: Dict[int, List[int]] = {}
        emitted_total = 0
        for j, uid in enumerate(batch.uids):
            c = int(counts[j])
            emitted = [int(t) for t in ys[:c, j]]
            emitted_total += c
            seq = self.state.seqs[uid]
            if c > 1:
                # every emitted token except the LAST has its KV in the
                # arena already; record them on the descriptor so
                # seen == len(tokens) == KV rows. The last token follows
                # the single-token contract: the caller decides whether
                # to feed it back (state.extend) or retire the row.
                seq.tokens.extend(emitted[:-1])
                seq.seen_tokens = len(seq.tokens)
            out[uid] = emitted
        _dispatch_count("dispatch/megastep_tokens", emitted_total)
        return out

    def cow_block(self, src_block: int) -> int:
        """Copy-on-write duplicate of one KV page across all layers.

        Prefix-cache handout of a shared PARTIAL last page: the new owner
        will append tokens into that page, so it gets a private copy; full
        shared pages are aliased in the page table instead (no copy).
        Returns the new physical page id (refcount 1, owned by caller).
        """
        dst = self.state.allocator.allocate(1)[0]
        if self._copy_pages_fn is None:
            self._copy_pages_fn = jax.jit(
                partial(pa.copy_pages,
                        num_layers=self.model_config.num_layers),
                donate_argnums=(0,))
        self.arena = self._copy_pages_fn(
            self.arena, jnp.asarray([src_block], jnp.int32),
            jnp.asarray([dst], jnp.int32))
        return dst

    def export_pages(self, blocks: List[int]) -> Dict[str, np.ndarray]:
        """Device→host gather of whole KV pages, every layer's region.

        The serialization half of prefill→decode page handoff
        (serving/handoff.py): ``blocks`` are layer-relative page ids
        (the same ids page tables hold); the flat pool stores layer
        ``l``'s copy of page ``b`` at ``l*(nb+1)+b``, so one fancy-index
        gather per {k, v} pulls all ``L`` copies at once. Returns
        ``{"k", "v"}`` as ``[kvh, L, m, bs, dh]`` host arrays — the
        importing engine must have identical model geometry (it checks).
        """
        L = self.model_config.num_layers
        stride = self.arena["k"].shape[1] // L          # nb + 1
        ids = np.asarray(blocks, np.int32)
        idx = (np.arange(L, dtype=np.int32)[:, None] * stride +
               ids[None, :]).reshape(-1)
        out = {}
        for key in ("k", "v"):
            kvh, _, bs, dh = self.arena[key].shape
            flat = np.asarray(self.arena[key][:, idx])  # [kvh, L*m, bs, dh]
            out[key] = flat.reshape(kvh, L, len(blocks), bs, dh)
        return out

    def import_pages(self, pages: Dict[str, np.ndarray],
                     blocks: List[int]) -> None:
        """Scatter pages from :meth:`export_pages` into this engine's
        arena at the (already-allocated, caller-owned) page ids
        ``blocks`` — the adoption half of page handoff. Raises
        ``ValueError`` on a geometry mismatch rather than silently
        writing garbage KV."""
        L = self.model_config.num_layers
        stride = self.arena["k"].shape[1] // L
        ids = np.asarray(blocks, np.int32)
        idx = (np.arange(L, dtype=np.int32)[:, None] * stride +
               ids[None, :]).reshape(-1)
        for key in ("k", "v"):
            kvh, _, bs, dh = self.arena[key].shape
            want = (kvh, L, len(blocks), bs, dh)
            got = tuple(pages[key].shape)
            if got != want:
                raise ValueError(
                    f"page bundle {key!r} shape {got} does not fit this "
                    f"arena (want {want}) — replicas must share model "
                    f"geometry")
            data = jnp.asarray(pages[key], self.arena[key].dtype) \
                .reshape(kvh, L * len(blocks), bs, dh)
            self.arena[key] = self.arena[key].at[:, idx].set(data)

    def kv_page_nbytes(self) -> int:
        """Host-side bytes of ONE exported KV page (all layers, k + v) —
        what a tier/handoff consumer budgets per page (the uncompressed
        ``export_pages`` payload size for a single block)."""
        L = self.model_config.num_layers
        total = 0
        for key in ("k", "v"):
            kvh, _, bs, dh = self.arena[key].shape
            total += kvh * L * bs * dh * self.arena[key].dtype.itemsize
        return total

    def _buckets(self, batch: RaggedBatch):
        nb = _bucket(len(batch.uids))
        c = batch.token_ids.shape[1]
        # exactly TWO chunk-width shapes — decode (1) and full prefill
        # chunk: every distinct (n, c) bucket is a fresh XLA compile, and
        # per-width pow2 buckets were costing multiple multi-second
        # compiles per serving session for marginal padding savings
        cb = 1 if c == 1 else self.config.prefill_chunk
        return nb, cb

    def _run(self, batch: RaggedBatch, mode=None) -> np.ndarray:
        n = len(batch.uids)
        nb, cb = self._buckets(batch)
        # chunk batches avoid the arena READ in attention (the write→read
        # on the ~GB arena serializes the whole layer scan): first-chunk-
        # only batches attend within the chunk ("fresh"); continuation /
        # SplitFuse-mixed batches split history (pre-write arena) +
        # within-chunk and merge by logsumexp ("split"). Env
        # DSTPU_NO_SPLIT_PREFILL restores the single paged read (A/B +
        # escape hatch).
        if cb == 1 or os.environ.get("DSTPU_NO_SPLIT_PREFILL"):
            fresh = False
        elif bool((batch.start_positions == 0).all()):
            fresh = "fresh"
        else:
            fresh = "split"
        packed = jnp.asarray(self._pack(batch, nb, cb))   # ONE upload
        out, self._rng_dev, self.arena = self._step_fn(nb, cb, mode,
                                                       fresh)(
            self.params, self.arena, packed, self._rng_dev)
        _dispatch_count("dispatch/host_calls")
        return np.asarray(jax.device_get(out))[:n]

    # -- fused decode loop (generate fast path) ----------------------------

    #: fused scan lengths are bucketed to multiples of this so distinct
    #: max_new_tokens values share compiles (each fused program is a
    #: full-model compile); iterations beyond the traced `limit` run with
    #: all rows dead (KV to trash, outputs discarded) — ≤31 wasted steps
    _FUSED_STEP_BUCKET = 32

    def _fused_decode_fn(self, nb: int, sb: int, mode):
        """jit: up to `sb` single-token decode iterations in ONE device
        program — the per-token host round-trips of the stepwise loop
        (2+ per token; ~20 ms each on tunneled runtimes) collapse to one
        upload + one [sb, nb] fetch.

        The arena stays OUT of the scan carry: new KV lands in a small
        per-loop decode buffer ([L, sb, nb, kvh, dh] — a few MB) and each
        step's attention = merge(history over the READ-ONLY arena,
        causal attention over the buffer so far) by logsumexp. The
        buffer is written back into the arena pages in one pass after
        the loop. Carrying the arena instead forces XLA to copy it every
        iteration (two ~33MB copies per layer-step profiled on v5e), and
        a read-only arena also lets the Pallas paged kernel serve the
        history part — it walks only each sequence's true pages, where
        the XLA gather path fetches the padded page-table width.

        Per-row dead-masking (all traced, no recompiles): a row goes
        dead past the scalar `limit`, past its own `budgets[row]`
        sampled tokens, or one step after sampling `eos_ids[row]`
        (-1 = no eos). Dead rows stop counting and their buffer slots
        are clipped by the per-row write-back counts, so finished rows
        never write KV past their true end — the returned ``counts``
        is exactly how many sampled tokens per row are valid AND how
        many KV entries landed in the arena. Dead iterations still
        split the sampling rng once per scan step, so a K-token window
        produces the same sample stream whether it runs as one program
        or several (megastep chunking invariance).

        Returns ``(ys [sb, nb], counts [nb], rng, arena)``."""
        key = (nb, sb, mode)
        if key in self._fused_fns:
            return self._fused_fns[key]
        if os.environ.get("DSTPU_FUSED_V1"):
            return self._fused_decode_fn_v1(nb, sb, mode)
        from deepspeed_tpu.telemetry import compile_monitor
        compile_monitor.count_trace(
            "serving/fused_decode_fn",
            detail={"n_bucket": nb, "steps": sb, "mode": str(mode)})
        model = self.model_config
        from deepspeed_tpu.ops.paged_attention import _masked_attention

        num_layers = model.num_layers
        kvh, dh = model.kv_heads, model.head_dim

        def fn(params, arena, tokens0, starts0, live, pt, limit, budgets,
               eos_ids, temp, top_p, rng):
            stride = arena["k"].shape[1] // num_layers
            ak_c, av_c = arena["k"], arena["v"]       # read-only in loop
            kbuf0 = jnp.zeros((num_layers, sb, nb, kvh, dh), self.dtype)
            vbuf0 = jnp.zeros_like(kbuf0)
            alive0 = live.astype(bool)
            counts0 = jnp.zeros((nb,), jnp.int32)

            def step(carry, i):
                tokens, rng, kbuf, vbuf, alive, counts = carry
                # a row alive at step i was alive at every step before
                # it, so counts == i for alive rows and starts0 + i is
                # its true position; dead rows produce garbage the
                # write-back clips (counts) and the host slices away
                step_live = alive & (i < limit)
                positions = (starts0 + i)[:, None]            # [nb, 1]
                x = embed_tokens(
                    model, params["embed"], tokens[:, None],
                    jnp.minimum(positions,
                                params["embed"]["pos"].shape[0] - 1)
                    if model.pos_emb == "learned" else positions,
                    params.get("embed_norm"))
                if model.pos_emb == "rope":
                    sin, cos = rope_table(model, positions)
                else:
                    sin = cos = jnp.zeros((nb, 1, 0), x.dtype)

                jdx = jnp.arange(sb, dtype=jnp.int32)
                dec_mask = (jdx[None, :] <= i)[None, None, None]

                def layer_body(carry_l, layer):
                    xl, kbuf, vbuf = carry_l
                    lp, l_idx = layer
                    pt_l = pt + l_idx * stride
                    h_in = _norm(model, lp["ln1"], xl)
                    q, k, v = qkv_project(model, lp["attn"], h_in, sin,
                                          cos)
                    # history: keys [0, starts0) straight from the
                    # arena. XLA gather-attend by default: the Pallas
                    # kernel's (seq, head) grid is launch-overhead-bound
                    # at decode widths (268 vs 70 us/layer-step profiled
                    # at n=16 on v5e); opt in via DSTPU_FUSED_PALLAS_HIST
                    # for wide-batch/long-context serving where walking
                    # only the true pages wins back the gather padding
                    if self.use_pallas and \
                            os.environ.get("DSTPU_FUSED_PALLAS_HIST"):
                        out_h, lse_h = pa.paged_attention_with_lse(
                            q, ak_c, av_c, pt_l, starts0,
                            jnp.zeros_like(starts0))
                    else:
                        out_h, lse_h = pa.paged_attention_hist_xla(
                            q, ak_c, av_c, pt_l, starts0)
                    # decode window: this loop's own tokens (incl. self)
                    kbuf = lax.dynamic_update_slice(
                        kbuf, k[:, 0][None, None].astype(kbuf.dtype),
                        (l_idx, i, 0, 0, 0))
                    vbuf = lax.dynamic_update_slice(
                        vbuf, v[:, 0][None, None].astype(vbuf.dtype),
                        (l_idx, i, 0, 0, 0))
                    kd = lax.dynamic_index_in_dim(
                        kbuf, l_idx, 0, keepdims=False)       # [sb,nb,..]
                    vd = lax.dynamic_index_in_dim(vbuf, l_idx, 0,
                                                  keepdims=False)
                    out_d, lse_d = _masked_attention(
                        q, kd.transpose(1, 2, 0, 3),
                        vd.transpose(1, 2, 0, 3), dec_mask, True)
                    out = pa.merge_attention(out_h, lse_h, out_d,
                                             lse_d).astype(q.dtype)
                    attn_out = attn_out_project(model, lp["attn"], out)
                    h_out, _aux = block_combine(model, lp, xl, h_in,
                                                attn_out, self._moe_fn)
                    return (h_out, kbuf, vbuf), None

                if os.environ.get("DSTPU_FUSED_SCAN_LAYERS"):
                    (x, kbuf, vbuf), _ = lax.scan(
                        layer_body, (x, kbuf, vbuf),
                        (params["layers"],
                         jnp.arange(num_layers, dtype=jnp.int32)))
                else:
                    # UNROLLED layer loop: under lax.scan every layer's
                    # (packed) weights are dynamic-sliced out of the
                    # stacked params into fresh buffers each step —
                    # pure copy traffic that roughly doubles the
                    # weight-bound decode cost. Unrolling lets XLA feed
                    # the kernels from the stacked arrays directly;
                    # compile time stays modest because the decode
                    # graph is small.
                    carry_l = (x, kbuf, vbuf)
                    for l in range(num_layers):
                        lp = jax.tree.map(lambda a: a[l],
                                          params["layers"])
                        carry_l, _ = layer_body(
                            carry_l, (lp, jnp.int32(l)))
                    x, kbuf, vbuf = carry_l
                x = _norm(model, params["final_norm"], x)
                logits = lm_logits(model, params, x)[:, 0]
                nxt, rng = _sample_tokens(logits, mode, temp, top_p, rng)
                # rows alive this step emit `nxt`; a row retires AFTER
                # emitting its eos / last-budget token, so counts ends at
                # exactly the number of valid tokens == KV rows written
                # (the eos token itself never writes KV — its KV slot
                # would belong to the NEXT step's fed token)
                counts = counts + step_live.astype(jnp.int32)
                alive = step_live & (nxt != eos_ids) & (counts < budgets)
                return (nxt, rng, kbuf, vbuf, alive, counts), nxt

            (_, rng, kbuf, vbuf, _, counts), ys = lax.scan(
                step, (tokens0, rng, kbuf0, vbuf0, alive0, counts0),
                jnp.arange(sb, dtype=jnp.int32))

            # one write-back pass: buffer rows [0, counts[r]) per row
            counts_wb = counts

            def wb(carry, inp):
                ak, av = carry
                kb, vb, l_idx = inp                  # kb [sb, nb, kvh, dh]
                pt_l = pt + l_idx * stride
                ak, av = pa.write_kv(
                    ak, av, kb.transpose(1, 0, 2, 3),
                    vb.transpose(1, 0, 2, 3), pt_l, starts0, counts_wb,
                    trash_block=l_idx * stride + stride - 1)
                return (ak, av), None

            (ak, av), _ = lax.scan(
                wb, (arena["k"], arena["v"]),
                (kbuf, vbuf, jnp.arange(num_layers, dtype=jnp.int32)))
            return ys, counts, rng, {"k": ak, "v": av}

        jitted = jax.jit(fn, donate_argnums=(1,))
        self._fused_fns[key] = jitted
        return jitted

    def _fused_decode_fn_v1(self, nb: int, sb: int, mode):
        """The r4 arena-carrying loop (XLA attend, arena copied per
        iteration) — kept for A/B via DSTPU_FUSED_V1. Signature-identical
        to :meth:`_fused_decode_fn` including the per-row budget/eos
        dead-masking (here dead rows write no KV at all: ragged_forward
        clips by the per-row counts)."""
        key = (nb, sb, mode, "v1")
        if key in self._fused_fns:
            return self._fused_fns[key]
        from deepspeed_tpu.telemetry import compile_monitor
        compile_monitor.count_trace(
            "serving/fused_decode_fn_v1",
            detail={"n_bucket": nb, "steps": sb, "mode": str(mode)})
        model = self.model_config

        def fn(params, arena, tokens0, starts0, live, pt, limit, budgets,
               eos_ids, temp, top_p, rng):
            alive0 = live.astype(bool)
            counts0 = jnp.zeros_like(starts0)

            def body(carry, i):
                tokens, starts, arena, rng, alive, counts = carry
                live_i = (alive & (i < limit)).astype(jnp.int32)
                logits, arena = ragged_forward(
                    model, params, arena, tokens[:, None], live_i, starts,
                    pt, use_pallas=False, moe_fn=self._moe_fn)
                nxt, rng = _sample_tokens(logits, mode, temp, top_p, rng)
                counts = counts + live_i
                alive = (live_i > 0) & (nxt != eos_ids) & \
                    (counts < budgets)
                return (nxt, starts + live_i, arena, rng, alive,
                        counts), nxt

            (_, _, arena, rng, _, counts), ys = lax.scan(
                body, (tokens0, starts0, arena, rng, alive0, counts0),
                jnp.arange(sb, dtype=jnp.int32))
            return ys, counts, rng, arena

        jitted = jax.jit(fn, donate_argnums=(1,))
        self._fused_fns[key] = jitted
        return jitted

    def _fused_decode(self, uids: List[int], first_tokens: List[int],
                      steps: int, mode,
                      budgets: Optional[List[int]] = None,
                      eos_token_id: Optional[int] = None,
                      sb: Optional[int] = None):
        """Pre-allocate KV pages for the decode window, then run the
        fused loop. Returns ``(tok_mat [steps, n], counts [n])`` — row
        ``j`` of the batch emitted ``counts[j]`` valid tokens
        (``tok_mat[:counts[j], j]``) and wrote exactly that many KV
        entries; rows stop early on their per-row ``budgets[j]`` or on
        sampling ``eos_token_id`` (both optional — default is the old
        run-out-the-window behavior). ``sb`` overrides the scan-length
        bucket (megastep uses pow2 buckets so chunked RNG streams line
        up; the generate path keeps ``_FUSED_STEP_BUCKET`` multiples).
        Raises FusedDecodeUnavailable when length (doomed=True — the
        stepwise loop would also overrun max_seq_len) or page capacity
        (doomed=False — fall back) can't cover the full decode."""
        n = len(uids)
        if n == 0:
            raise FusedDecodeUnavailable("empty batch")
        nb = _bucket(n)
        bs = self.state.allocator.block_size
        # per-row effective window: a row never runs past its own budget,
        # so pages (and the doomed check) only need to cover min(steps,
        # budget) — without this, per-row budgets shorter than the chunk
        # would pre-allocate pages the dead-masked tail never fills
        eff = [steps if budgets is None else min(steps, int(budgets[j]))
               for j in range(n)]
        need: List[int] = []
        for u, e in zip(uids, eff):
            seq = self.state.seqs[u]
            final = len(seq.tokens) + e
            if final > self.config.max_seq_len:
                raise FusedDecodeUnavailable(
                    f"sequence {u} would reach {final} tokens, over "
                    f"max_seq_len={self.config.max_seq_len}", doomed=True)
            need.append(-(-final // bs) - len(seq.blocks))
        if sum(need) > self.state.allocator.free_blocks:
            raise FusedDecodeUnavailable("KV arena too full to pre-"
                                         "allocate the decode window")
        for u, k in zip(uids, need):
            if k > 0:
                self.state.seqs[u].blocks.extend(
                    self.state.allocator.allocate(k))

        if sb is None:
            sb = -(-steps // self._FUSED_STEP_BUCKET) * \
                self._FUSED_STEP_BUCKET
        tokens0 = np.zeros((nb,), np.int32)
        tokens0[:n] = first_tokens
        starts0 = np.zeros((nb,), np.int32)
        live = np.zeros((nb,), np.int32)
        live[:n] = 1
        # padding rows carry budget 0 (they are dead from step 0 anyway);
        # eos -1 never matches a sampled id, so "no eos" needs no
        # separate compile
        bud = np.zeros((nb,), np.int32)
        bud[:n] = eff
        eos = np.full((nb,), -1, np.int32)
        if eos_token_id is not None:
            eos[:n] = int(eos_token_id)
        pt = self._page_table(uids, nb)
        for i, u in enumerate(uids):
            starts0[i] = len(self.state.seqs[u].tokens)
        # slice the page table to the pages this batch can actually
        # touch (bucketed to limit recompiles): the history gather
        # fetches mb*block_size keys per row, and the full max_seq_len
        # table width costs ~2x the true KV traffic on typical mixes
        mb_need = int(-(-(int(starts0.max()) + steps) // bs))
        mb_b = min(self.mb, -(-mb_need // 4) * 4)
        pt = pt[:, :mb_b]
        ys, counts, self._rng_dev, self.arena = self._fused_decode_fn(
            nb, sb, mode)(
                self.params, self.arena, jnp.asarray(tokens0),
                jnp.asarray(starts0), jnp.asarray(live), jnp.asarray(pt),
                jnp.int32(steps), jnp.asarray(bud), jnp.asarray(eos),
                jnp.float32(self._temperature),
                jnp.float32(self._top_p), self._rng_dev)
        _dispatch_count("dispatch/host_calls")
        _dispatch_count("dispatch/scan_steps", sb)
        # scan iterations past `limit` run with every row dead — pure
        # bucket-rounding waste dstpu-explain surfaces when it dominates
        _dispatch_count("dispatch/dead_steps", sb - steps)
        ys, counts = jax.device_get((ys, counts))    # ONE sync
        return np.asarray(ys)[:steps, :n], np.asarray(counts)[:n]

    # -- convenience serving loop ------------------------------------------

    def _consume_first(self, u: int, t: int, seqs, remaining, cur_tok,
                       active: List[int], eos_token_id) -> None:
        """Shared post-sample bookkeeping: append token t to sequence u,
        spend budget, retire (flush) on exhaustion/eos, else keep u
        active with t as the next fed token."""
        seqs[u].append(t)
        remaining[u] -= 1
        if remaining[u] <= 0 or (eos_token_id is not None
                                 and t == eos_token_id):
            self.flush(u)
        else:
            active.append(u)
            cur_tok[u] = t

    def _validate_lengths(self, prompts, budget_list, caller: str) -> None:
        """Fail BEFORE any compute when a request cannot fit max_seq_len
        even in principle — the chunked loop would otherwise burn most
        of the workload and then discard every sequence's output."""
        for i, (p, m) in enumerate(zip(prompts, budget_list)):
            total = len(np.asarray(p).reshape(-1)) + max(0, m)
            if total > self.config.max_seq_len:
                raise ValueError(
                    f"{caller}(): request {i} would reach {total} tokens,"
                    f" over max_seq_len={self.config.max_seq_len}; lower "
                    f"max_new_tokens or raise max_seq_len")

    def _run_fused_chunk(self, active: List[int], cur_tok: Dict[int, int],
                         remaining: Dict[int, int],
                         seqs: Dict[int, list], eos_token_id, mode):
        """One device-resident decode chunk over ``active`` rows:
        decode, consume, retire finished sequences (flush). Mutates
        cur_tok/remaining/seqs; returns (still_active, None) or
        (active, exc) when the fused path is unavailable."""
        chunk = min(self._FUSED_STEP_BUCKET,
                    max(remaining[u] for u in active))
        try:
            tok_mat, _counts = self._fused_decode(
                active, [cur_tok[u] for u in active], chunk, mode,
                budgets=[remaining[u] for u in active],
                eos_token_id=eos_token_id)
        except FusedDecodeUnavailable as e:
            return active, e
        still: List[int] = []
        for j, u in enumerate(active):
            take = min(chunk, remaining[u])
            done = remaining[u] <= chunk
            fed = cur_tok[u]
            for s_i in range(take):
                t = int(tok_mat[s_i, j])
                seqs[u].append(t)
                remaining[u] -= 1
                if eos_token_id is not None and t == eos_token_id:
                    done = True
                    break
            if done:
                self.flush(u)
            else:
                # the chunk's KV is already in the arena (pages
                # pre-allocated by _fused_decode): advance the host
                # descriptor to match — the tokens whose KV landed are
                # the fed token plus all but the last sampled one,
                # which seeds the next chunk
                seq = self.state.seqs[u]
                seq.tokens.extend([fed] + [int(t) for t in
                                           tok_mat[:chunk - 1, j]])
                seq.seen_tokens = len(seq.tokens)
                still.append(u)
                cur_tok[u] = int(tok_mat[chunk - 1, j])
        return still, None

    def serve(self, prompts, max_new_tokens: Union[int, List[int]] = 64,
              max_concurrency: int = 16,
              eos_token_id: Optional[int] = None,
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0) -> List[np.ndarray]:
        """Continuous-batching SERVER loop over a request stream.

        Processes ``prompts`` (any number) with at most
        ``max_concurrency`` sequences resident: queued requests are
        admitted the moment a slot frees, so the decode batch stays full
        while long-tail requests run out their budgets. This is the
        workload shape behind the reference FastGen throughput claim
        (blogs/deepspeed-fastgen: 2.3x effective throughput) — a padded
        static engine must run each batch to ITS longest request and
        only then start the next batch. Returns full sequences in input
        order.
        """
        from collections import deque
        if temperature == 0.0:
            mode = ("argmax",)
        else:
            mode = ("sample", int(top_k), top_p < 1.0)
            self._temperature = float(temperature)
            self._top_p = float(top_p)
        n = len(prompts)
        if isinstance(max_new_tokens, (int, np.integer)):
            budget_list = [int(max_new_tokens)] * n
        else:
            if len(max_new_tokens) != n:
                raise ValueError("per-sequence max_new_tokens must match "
                                 "the number of prompts")
            budget_list = [int(m) for m in max_new_tokens]
        self._validate_lengths(prompts, budget_list, "serve")
        base = max(self.state.seqs.keys(), default=-1) + 1
        # zero-budget requests pass through untouched
        queue = deque(i for i in range(n) if budget_list[i] > 0)
        seqs: Dict[int, list] = {
            base + i: list(np.asarray(prompts[i]).reshape(-1)
                           .astype(np.int32)) for i in range(n)}
        remaining: Dict[int, int] = {}
        cur_tok: Dict[int, int] = {}
        active: List[int] = []
        try:
            while queue or active:
                admit: List[int] = []
                while queue and len(active) + len(admit) < max_concurrency:
                    i = queue[0]
                    # admission is capacity-gated so one oversized
                    # request can't abort the stream mid-flight; it
                    # waits for retirements to free pages instead
                    if not self.state.can_schedule(len(seqs[base + i])):
                        break
                    queue.popleft()
                    u = base + i
                    remaining[u] = budget_list[i]
                    admit.append(u)
                if queue and not admit and not active:
                    i = queue[0]
                    raise ValueError(
                        f"serve(): request {i} ({len(seqs[base + i])} "
                        f"tokens) cannot be scheduled even on an empty "
                        f"engine; raise num_blocks/max_sequences")
                if admit:
                    pending = self._put_tokens(
                        admit, [seqs[u] for u in admit], mode)
                    for u in admit:
                        self._consume_first(u, pending[u], seqs,
                                            remaining, cur_tok, active,
                                            eos_token_id)
                if not active:
                    continue
                if os.environ.get("DSTPU_NO_FUSED_DECODE"):
                    err: Optional[Exception] = FusedDecodeUnavailable(
                        "disabled")
                else:
                    active, err = self._run_fused_chunk(
                        active, cur_tok, remaining, seqs, eos_token_id,
                        mode)
                if err is not None:
                    # stepwise fallback for one token per active row,
                    # then re-enter the loop (slots may free / arena
                    # pressure may ease)
                    pending = self._put_tokens(
                        active, [[cur_tok[u]] for u in active], mode)
                    still: List[int] = []
                    for u in active:
                        self._consume_first(u, pending[u], seqs,
                                            remaining, cur_tok, still,
                                            eos_token_id)
                    active = still
        except Exception:
            for u in list(self.state.seqs):
                if u >= base:
                    self.flush(u)
            raise
        return [np.asarray(seqs[base + i], np.int32) for i in range(n)]

    def generate(self, prompts, max_new_tokens: Union[int, List[int]] = 64,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0) -> List[np.ndarray]:
        """Continuous-batching generation (greedy by default; temperature/
        top-k/top-p sampled on device). ``prompts`` is a list of 1-D int
        arrays (ragged lengths); ``max_new_tokens`` may be per-sequence.
        Returns the full token sequences. Sequences join/leave the batch
        independently — the continuous batching the padded v1 engine
        can't do: the fused decode runs in device-resident CHUNKS and
        finished sequences RETIRE between chunks (budget exhausted or
        eos), so a long-tail generation mix only pays for the tokens it
        actually produces, while a padded static batch computes every
        row out to the longest request."""
        if temperature == 0.0:
            mode = ("argmax",)
        else:
            mode = ("sample", int(top_k), top_p < 1.0)
            self._temperature = float(temperature)
            self._top_p = float(top_p)
        # allocate uids that can't collide with sequences the streaming
        # put() API may already hold (review finding: generate() after
        # put([0], ...) silently extended sequence 0)
        base = max(self.state.seqs.keys(), default=-1) + 1
        uids = [base + i for i in range(len(prompts))]
        if isinstance(max_new_tokens, (int, np.integer)):
            budgets = {u: int(max_new_tokens) for u in uids}
        else:
            if len(max_new_tokens) != len(prompts):
                raise ValueError("per-sequence max_new_tokens must match "
                                 "the number of prompts")
            budgets = {u: int(m) for u, m in zip(uids, max_new_tokens)}
        if eos_token_id is None:
            # without eos there is no early exit: a request that cannot
            # fit max_seq_len must fail BEFORE any compute, not after
            # the chunked loop has burned most of the workload
            self._validate_lengths(prompts, [budgets[u] for u in uids],
                                   "generate")
        seqs = {u: list(np.asarray(p).reshape(-1).astype(np.int32))
                for u, p in zip(uids, prompts)}
        remaining = dict(budgets)
        pending = self._put_tokens(uids, [seqs[u] for u in uids], mode)
        # fast path: every sequence is now in pure decode — run
        # device-resident chunks (one upload + one fetch per chunk
        # instead of 2+ round-trips per token), retiring finished rows
        # between chunks. DSTPU_NO_FUSED_DECODE restores the stepwise
        # loop.
        if uids and len(pending) == len(uids) \
                and max(remaining.values(), default=0) > 1 \
                and not os.environ.get("DSTPU_NO_FUSED_DECODE"):
            active: List[int] = []
            cur_tok: Dict[int, int] = {}
            for u in uids:
                self._consume_first(u, pending[u], seqs, remaining,
                                    cur_tok, active, eos_token_id)
            fused_failed = False
            while active and not fused_failed:
                active, err = self._run_fused_chunk(
                    active, cur_tok, remaining, seqs, eos_token_id, mode)
                if err is not None:
                    if err.doomed and eos_token_id is None:
                        # the stepwise loop would hit the same wall mid-
                        # generation, after burning steps and LEAKING
                        # the sequences' pages — fail cleanly up front
                        for u in uids:
                            if u in self.state.seqs:
                                self.flush(u)
                        raise ValueError(
                            f"generate(): {err}; lower max_new_tokens or "
                            f"raise max_seq_len") from err
                    log_dist(f"fused decode unavailable ({err}); using "
                             f"the stepwise loop")
                    fused_failed = True
            if not fused_failed:
                for u in uids:
                    if u in self.state.seqs:
                        self.flush(u)
                return [np.asarray(seqs[u], np.int32) for u in uids]
            # stepwise continuation from the current chunked state: the
            # rows still active have their last sampled token NOT yet
            # fed — exactly the `pending` shape the loop below consumes.
            # (The first-token appends already happened above, so hand
            # the loop a pending map of the still-unfed tokens only.)
            pending = {u: cur_tok[u] for u in active}
            # the loop's first action is to append pending tokens; ours
            # are already appended — drop them from seqs to avoid the
            # double-append, keeping remaining consistent
            for u in active:
                seqs[u].pop()
                remaining[u] += 1
        try:
            while pending:
                active_uids, toks = [], []
                for u, t in list(pending.items()):
                    seqs[u].append(t)
                    remaining[u] -= 1
                    if remaining[u] <= 0 or (eos_token_id is not None
                                             and t == eos_token_id):
                        self.flush(u)
                        del pending[u]
                    else:
                        active_uids.append(u)
                        toks.append([t])
                if not active_uids:
                    break
                pending = self._put_tokens(active_uids, toks, mode)
        except Exception:
            # mid-loop failures (arena exhausted, over-length) must not
            # leak this call's sequences — their pages/slots would be
            # lost to every later request
            for u in uids:
                if u in self.state.seqs:
                    self.flush(u)
            raise
        return [np.asarray(seqs[u], np.int32) for u in uids]
