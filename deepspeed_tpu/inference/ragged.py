"""Ragged batching state — the FastGen-core state layer.

Reference: ``deepspeed/inference/v2/ragged/`` — ``DSStateManager``
(ragged_manager.py:19), ``BlockedAllocator`` (blocked_allocator.py:11),
``DSSequenceDescriptor`` (sequence_descriptor.py:59), ``RaggedBatchWrapper``
(ragged_wrapper.py:31). Host-side bookkeeping is a direct functional
analogue; the device side differs: rather than CUDA paged-KV kernels, the
scheduler packs sequences into a shared static-shape KV arena whose pages
are tracked here (a Pallas paged-attention kernel can later consume the
same page tables).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class BlockedAllocator:
    """Fixed pool of KV pages (reference blocked_allocator.py:11)."""

    def __init__(self, num_blocks: int, block_size: int = 128):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV arena exhausted: want {n} blocks, {len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b < 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
            self._free.append(b)


@dataclass
class SequenceDescriptor:
    """Reference sequence_descriptor.py:59."""
    uid: int
    tokens: List[int] = field(default_factory=list)
    seen_tokens: int = 0            # tokens already in KV
    blocks: List[int] = field(default_factory=list)
    slot: Optional[int] = None      # row in the packed decode batch
    done: bool = False

    @property
    def pending(self) -> int:
        return len(self.tokens) - self.seen_tokens


class DSStateManager:
    """Tracks live sequences + KV pages (reference ragged_manager.py:19)."""

    def __init__(self, max_sequences: int = 64, num_blocks: int = 512,
                 block_size: int = 128):
        self.max_sequences = max_sequences
        self.allocator = BlockedAllocator(num_blocks, block_size)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._slots: List[int] = list(range(max_sequences - 1, -1, -1))

    def get_or_create_sequence(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            if not self._slots:
                raise RuntimeError("max_sequences exceeded")
            self.seqs[uid] = SequenceDescriptor(uid=uid,
                                                slot=self._slots.pop())
        return self.seqs[uid]

    def extend(self, uid: int, token_ids) -> SequenceDescriptor:
        seq = self.get_or_create_sequence(uid)
        new = [int(t) for t in np.asarray(token_ids).reshape(-1)]
        total = len(seq.tokens) + len(new)
        needed = -(-total // self.allocator.block_size) - len(seq.blocks)
        # allocate BEFORE mutating so an exhausted arena leaves the
        # sequence untouched and the caller can retry safely
        if needed > 0:
            seq.blocks.extend(self.allocator.allocate(needed))
        seq.tokens.extend(new)
        return seq

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference engine_v2.py flush:242)."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self.allocator.free(seq.blocks)
            self._slots.append(seq.slot)

    def can_schedule(self, n_tokens: int) -> bool:
        """Capacity check (reference engine_v2.py can_schedule:158)."""
        blocks = -(-n_tokens // self.allocator.block_size)
        return blocks <= self.allocator.free_blocks and \
            len(self.seqs) < self.max_sequences


@dataclass
class RaggedBatch:
    """One scheduler step's work (reference ragged_wrapper.py:31)."""
    uids: List[int]
    token_ids: np.ndarray        # padded [n_seq, max_chunk]
    token_counts: np.ndarray     # [n_seq] actual new tokens
    start_positions: np.ndarray  # [n_seq] seen_tokens before this step
    slots: np.ndarray            # [n_seq] KV arena rows

    @property
    def total_tokens(self) -> int:
        return int(self.token_counts.sum())


class RaggedScheduler:
    """Continuous-batching scheduler: mixes prefill chunks and decode steps
    into one ragged batch per engine step (FastGen's Dynamic SplitFuse,
    reference inference/v2 engine put():107 semantics)."""

    def __init__(self, state: DSStateManager, max_batch_tokens: int = 2048,
                 prefill_chunk: int = 512):
        self.state = state
        self.max_batch_tokens = max_batch_tokens
        self.prefill_chunk = prefill_chunk

    def put(self, uids, tokens_list) -> None:
        for uid, toks in zip(uids, tokens_list):
            self.state.extend(uid, toks)

    def next_batch(self) -> Optional[RaggedBatch]:
        uids, chunks, counts, starts, slots = [], [], [], [], []
        budget = self.max_batch_tokens
        for uid, seq in self.state.seqs.items():
            if seq.done or seq.pending == 0:
                continue
            take = min(seq.pending, self.prefill_chunk, budget)
            if take <= 0:
                continue
            chunk = seq.tokens[seq.seen_tokens:seq.seen_tokens + take]
            uids.append(uid)
            chunks.append(chunk)
            counts.append(take)
            starts.append(seq.seen_tokens)
            slots.append(seq.slot)
            budget -= take
            if budget <= 0:
                break
        if not uids:
            return None
        width = max(counts)
        padded = np.zeros((len(uids), width), np.int32)
        for i, c in enumerate(chunks):
            padded[i, :len(c)] = c
        return RaggedBatch(uids=uids, token_ids=padded,
                           token_counts=np.asarray(counts, np.int32),
                           start_positions=np.asarray(starts, np.int32),
                           slots=np.asarray(slots, np.int32))

    def mark_scheduled(self, batch: RaggedBatch) -> None:
        for uid, n in zip(batch.uids, batch.token_counts):
            self.state.seqs[uid].seen_tokens += int(n)
