"""Ragged batching state — the FastGen-core state layer.

Reference: ``deepspeed/inference/v2/ragged/`` — ``DSStateManager``
(ragged_manager.py:19), ``BlockedAllocator`` (blocked_allocator.py:11),
``DSSequenceDescriptor`` (sequence_descriptor.py:59), ``RaggedBatchWrapper``
(ragged_wrapper.py:31). Host-side bookkeeping is a direct functional
analogue; the device side differs: rather than CUDA paged-KV kernels, the
scheduler packs sequences into a shared static-shape KV arena whose pages
are tracked here (a Pallas paged-attention kernel can later consume the
same page tables).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class BlockedAllocator:
    """Fixed pool of REF-COUNTED KV pages (reference blocked_allocator.py:11).

    Refcounts let one physical page back several logical owners at once —
    the prefix cache (deepspeed_tpu/serving/prefix_cache.py) plus any
    number of sequences whose prompts share that page. ``allocate`` hands
    out pages at refcount 1; ``incref`` adds an owner; ``free`` drops one
    owner and only returns the page to the pool when the LAST owner lets
    go. Freeing a page nobody holds is a hard error (double free), not a
    silent corruption of whoever re-allocated it.
    """

    def __init__(self, num_blocks: int, block_size: int = 128):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: List[int] = [0] * num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def live_blocks(self) -> int:
        """Pages with at least one owner (``num_blocks - free_blocks``)."""
        return self.num_blocks - len(self._free)

    def total_refs(self) -> int:
        """Sum of owners across every live page — with ``live_blocks``
        the exact-accounting pair eviction/adoption tests pin down (an
        alias adds a ref but not a live page; a tier capture must change
        neither until the last owner lets go)."""
        return sum(self._ref)

    def refcount(self, block: int) -> int:
        self._check(block)
        return self._ref[block]

    def _check(self, block: int) -> None:
        if block < 0 or block >= self.num_blocks:
            raise ValueError(f"bad block id {block}")

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV arena exhausted: want {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks: List[int]) -> None:
        """Add an owner to live pages (prefix-cache sharing)."""
        for b in blocks:
            self._check(b)
            if self._ref[b] <= 0:
                raise RuntimeError(
                    f"incref on free block {b}: the page is not live")
            self._ref[b] += 1

    def free(self, blocks: List[int]) -> int:
        """Drop one owner per page; returns how many pages actually went
        back to the pool (refcount reached zero)."""
        released = 0
        for b in blocks:
            self._check(b)
            if self._ref[b] <= 0:
                raise RuntimeError(
                    f"double free of block {b}: the page has no owners")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)
                released += 1
        return released


@dataclass
class SequenceDescriptor:
    """Reference sequence_descriptor.py:59."""
    uid: int
    tokens: List[int] = field(default_factory=list)
    seen_tokens: int = 0            # tokens already in KV
    blocks: List[int] = field(default_factory=list)
    slot: Optional[int] = None      # row in the packed decode batch
    done: bool = False

    @property
    def pending(self) -> int:
        return len(self.tokens) - self.seen_tokens


class DSStateManager:
    """Tracks live sequences + KV pages (reference ragged_manager.py:19)."""

    def __init__(self, max_sequences: int = 64, num_blocks: int = 512,
                 block_size: int = 128):
        self.max_sequences = max_sequences
        self.allocator = BlockedAllocator(num_blocks, block_size)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._slots: List[int] = list(range(max_sequences - 1, -1, -1))

    def get_or_create_sequence(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            if not self._slots:
                raise RuntimeError("max_sequences exceeded")
            self.seqs[uid] = SequenceDescriptor(uid=uid,
                                                slot=self._slots.pop())
        return self.seqs[uid]

    def extend(self, uid: int, token_ids) -> SequenceDescriptor:
        seq = self.get_or_create_sequence(uid)
        new = [int(t) for t in np.asarray(token_ids).reshape(-1)]
        total = len(seq.tokens) + len(new)
        needed = -(-total // self.allocator.block_size) - len(seq.blocks)
        # allocate BEFORE mutating so an exhausted arena leaves the
        # sequence untouched and the caller can retry safely
        if needed > 0:
            seq.blocks.extend(self.allocator.allocate(needed))
        seq.tokens.extend(new)
        return seq

    def adopt(self, uid: int, token_ids, blocks: List[int],
              seen_tokens: int) -> SequenceDescriptor:
        """Create a sequence that starts life with pre-attached KV pages.

        The prefix-cache handout path: ``blocks`` already hold the KV of
        the first ``seen_tokens`` tokens of ``token_ids`` (the caller owns
        one ref per page and that ref transfers to the sequence here, so
        ``flush`` releases it). Pages for the uncached tail are allocated
        as usual; if the arena is exhausted the sequence keeps its adopted
        pages and the caller should ``flush(uid)`` to hand the refs back.
        """
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already live; cannot adopt")
        seq = self.get_or_create_sequence(uid)
        seq.blocks.extend(blocks)
        seq.seen_tokens = seen_tokens
        try:
            self.extend(uid, token_ids)
        except RuntimeError:
            self.flush(uid)
            raise
        return seq

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference engine_v2.py flush:242)."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self.allocator.free(seq.blocks)
            self._slots.append(seq.slot)

    def can_schedule(self, n_tokens: int) -> bool:
        """Capacity check (reference engine_v2.py can_schedule:158)."""
        blocks = -(-n_tokens // self.allocator.block_size)
        return blocks <= self.allocator.free_blocks and \
            len(self.seqs) < self.max_sequences


@dataclass
class RaggedBatch:
    """One scheduler step's work (reference ragged_wrapper.py:31)."""
    uids: List[int]
    token_ids: np.ndarray        # padded [n_seq, max_chunk]
    token_counts: np.ndarray     # [n_seq] actual new tokens
    start_positions: np.ndarray  # [n_seq] seen_tokens before this step
    slots: np.ndarray            # [n_seq] KV arena rows

    @property
    def total_tokens(self) -> int:
        return int(self.token_counts.sum())


class RaggedScheduler:
    """Continuous-batching scheduler: mixes prefill chunks and decode steps
    into one ragged batch per engine step (FastGen's Dynamic SplitFuse,
    reference inference/v2 engine put():107 semantics)."""

    def __init__(self, state: DSStateManager, max_batch_tokens: int = 2048,
                 prefill_chunk: int = 512, policy=None):
        self.state = state
        self.max_batch_tokens = max_batch_tokens
        self.prefill_chunk = prefill_chunk
        # Optional selection policy: any object with
        # ``select(state, budget, prefill_chunk) -> List[(uid, take)]``.
        # None keeps the original insertion-order sweep. The serving layer
        # plugs its SplitFuse token-budget policy in here
        # (deepspeed_tpu/serving/scheduler.py) without the engine knowing.
        self.policy = policy

    def put(self, uids, tokens_list) -> None:
        for uid, toks in zip(uids, tokens_list):
            self.state.extend(uid, toks)

    def _default_select(self, budget: int) -> List[Tuple[int, int]]:
        picks: List[Tuple[int, int]] = []
        for uid, seq in self.state.seqs.items():
            if seq.done or seq.pending == 0:
                continue
            take = min(seq.pending, self.prefill_chunk, budget)
            if take <= 0:
                continue
            picks.append((uid, take))
            budget -= take
            if budget <= 0:
                break
        return picks

    def next_batch(self, budget: Optional[int] = None) -> Optional[RaggedBatch]:
        budget = self.max_batch_tokens if budget is None else budget
        if self.policy is not None:
            picks = self.policy.select(self.state, budget, self.prefill_chunk)
        else:
            picks = self._default_select(budget)
        uids, chunks, counts, starts, slots = [], [], [], [], []
        for uid, take in picks:
            seq = self.state.seqs[uid]
            chunk = seq.tokens[seq.seen_tokens:seq.seen_tokens + take]
            uids.append(uid)
            chunks.append(chunk)
            counts.append(take)
            starts.append(seq.seen_tokens)
            slots.append(seq.slot)
        if not uids:
            return None
        width = max(counts)
        padded = np.zeros((len(uids), width), np.int32)
        for i, c in enumerate(chunks):
            padded[i, :len(c)] = c
        return RaggedBatch(uids=uids, token_ids=padded,
                           token_counts=np.asarray(counts, np.int32),
                           start_positions=np.asarray(starts, np.int32),
                           slots=np.asarray(slots, np.int32))

    def mark_scheduled(self, batch: RaggedBatch) -> None:
        for uid, n in zip(batch.uids, batch.token_counts):
            self.state.seqs[uid].seen_tokens += int(n)
