"""FLOPs profiler.

Reference: ``profiling/flops_profiler/profiler.py:30`` — the reference
monkey-patches torch.nn.functional with counting wrappers. On TPU the
compiler already knows: ``jax.jit(fn).lower(...).compile().cost_analysis()``
returns XLA's own flop/byte counts for the exact compiled program,
including fusion effects — strictly more accurate than op-level patching.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

# THE cost-analysis helpers live in telemetry/explain.py (one place that
# handles dict-vs-list cost_analysis() shapes across jax versions and
# empty returns on CPU backends); re-exported here for API continuity.
from deepspeed_tpu.telemetry.explain import _cost, analyze_fn  # noqa: F401
from deepspeed_tpu.utils.logging import log_dist


class FlopsProfiler:
    """Step-granular profiler attached to an engine (reference
    profiler.py API: start_profile/stop_profile/print_model_profile)."""

    def __init__(self, engine=None, config=None):
        self.engine = engine
        self.config = config
        self._t0: Optional[float] = None
        self._steps = 0
        self.flops_per_step: Optional[float] = None
        self.last_tflops: Optional[float] = None

    def start_profile(self) -> None:
        self._t0 = time.perf_counter()
        self._steps = 0

    def step(self) -> None:
        self._steps += 1

    def stop_profile(self) -> Dict[str, float]:
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        result = {"seconds": dt, "steps": self._steps}
        if self.engine is not None and self.engine.model.flops_per_token:
            tokens = self._steps * int(self.engine.config.train_batch_size) \
                * (self.engine.model.tokens_per_sample or 1)
            flops = self.engine.model.flops_per_token * tokens
            result["tflops"] = flops / max(dt, 1e-9) / 1e12
            self.last_tflops = result["tflops"]
            # interval MFU through the shared peak-FLOPs table; unlike the
            # engine's per-step host-time gauge this window is explicitly
            # opened/closed by the caller, so it can bracket a synced region
            from deepspeed_tpu.telemetry import registry
            from deepspeed_tpu.telemetry.sampler import mfu
            result["mfu"] = mfu(flops, dt, n_devices=jax.device_count())
            registry.gauge(
                "train/mfu_profiled",
                help="MFU over the last start/stop_profile window").set(
                result["mfu"])
        return result

    def print_profile(self) -> None:
        log_dist(f"flops profiler: {self.stop_profile()}")


def _abstract(tree):
    """Pytree of arrays/shapes → ShapeDtypeStructs (lower() takes them
    directly, so nothing is ever allocated — 70B profiles are free)."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def module_profile(dec_cfg, batch_size: int = 1,
                   seq_len: Optional[int] = None,
                   dtype=None, top_k: int = 10,
                   measure: bool = False,
                   measure_iters: int = 8) -> Dict[str, Any]:
    """Per-module forward flops/bytes/params breakdown (reference
    flops_profiler builds this tree by monkey-patching every torch module,
    profiler.py:511-861; here each named component is lowered separately
    over ABSTRACT shapes and XLA's own cost analysis is read back —
    fusion-accurate per component, nothing allocated or executed).

    Returns a tree ``{name, flops, bytes, params, pct, children: [...]}``
    plus ``top`` — the top-k leaf cost centers with percentages. The
    per-layer row is measured once and multiplied by num_layers (layers
    are homogeneous by construction — one stacked scan block).

    ``measure=True`` additionally RUNS each component jitted on the
    current backend with random concrete inputs and attaches measured
    wall time (``ms`` per row, iteration-chained inside one jit with a
    scalar fetch so remote-runtime dispatch noise does not pollute the
    number — the reference profiler's measured per-module duration,
    profiler.py:511). Costs one compile + ``measure_iters`` runs per
    component.
    """
    import jax.numpy as jnp
    from deepspeed_tpu.models import transformer as T

    cfg = dec_cfg
    t = seq_len or cfg.max_seq_len
    b = batch_size
    dt = dtype or jnp.float32
    abstract_params = jax.eval_shape(
        lambda r: T.init_params(cfg, r, dtype=dt), jax.random.PRNGKey(0))
    layer0 = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype),
        abstract_params["layers"])
    tokens = jax.ShapeDtypeStruct((b, t), np.int32)
    x = jax.ShapeDtypeStruct((b, t, cfg.hidden_size), dt)
    positions = jax.ShapeDtypeStruct((b, t), np.int32)

    def n_params(tree):
        return int(sum(int(np.prod(s.shape))
                       for s in jax.tree.leaves(tree)))

    def sincos(pos):
        if cfg.pos_emb == "rope":
            return T.rope_table(cfg, pos)
        return (jnp.zeros((b, t, 0), jnp.float32),) * 2

    def embed_fn(em, tok):
        return T.embed_tokens(cfg, em, tok,
                              jnp.broadcast_to(jnp.arange(t)[None], (b, t)))

    def attn_fn_(p, xx, pos):
        sin, cos = sincos(pos)
        return T._attention_block(cfg, p, xx, sin, cos,
                                  T.default_attention(cfg))

    def mlp_fn(p, xx):
        if cfg.num_experts:
            from functools import partial
            from deepspeed_tpu.parallel.moe import moe_layer
            fn = partial(moe_layer, top_k=cfg.num_experts_per_tok,
                         ep_axis=None)
            return fn(cfg, p, xx)
        return T._mlp(cfg, p, xx)

    def norm_fn(p, xx):
        return T._norm(cfg, p, xx)

    def head_fn(params, xx):
        xn = T._norm(cfg, params["final_norm"], xx)
        return T.lm_logits(cfg, params, xn)

    mlp_key = "moe" if cfg.num_experts else "mlp"
    rows = [
        ("embed", embed_fn, (abstract_params["embed"], tokens),
         n_params(abstract_params["embed"])),
        ("layer.attention", attn_fn_, (layer0["attn"], x, positions),
         n_params(layer0["attn"])),
        (f"layer.{mlp_key}", mlp_fn, (layer0[mlp_key], x),
         n_params(layer0[mlp_key])),
        ("layer.norms", norm_fn, (layer0["ln1"], x),
         n_params({k: v for k, v in layer0.items()
                   if k.startswith("ln")})),
        ("head(norm+logits)", head_fn,
         ({"final_norm": abstract_params["final_norm"],
           "embed": abstract_params["embed"],
           **({"lm_head": abstract_params["lm_head"]}
              if "lm_head" in abstract_params else {})}, x),
         0 if cfg.tie_embeddings else
         n_params(abstract_params.get("lm_head", {}))),
    ]

    def _measure_ms(fn, abstract_args) -> float:
        """Wall ms per call: concrete random inputs, one jit whose body
        chains `measure_iters` dependent calls, scalar fetched."""
        import time as _time
        from jax import lax as _lax

        def _concrete(s):
            if np.issubdtype(s.dtype, np.integer):
                return jnp.zeros(s.shape, s.dtype)
            return jnp.full(s.shape, 0.01, s.dtype)

        args_c = jax.tree.map(_concrete, tuple(abstract_args))

        def chained(*a):
            def step(_, carry):
                # thread the carry into the inputs as a runtime ~0 so
                # XLA cannot hoist the body out of the loop
                eps = carry * 1e-30

                def bump(l):
                    if jnp.issubdtype(l.dtype, jnp.floating):
                        return l + eps.astype(l.dtype)
                    return l
                out = fn(jax.tree.map(bump, a[0]), *a[1:])
                out0 = out[0] if isinstance(out, tuple) else out
                return jnp.sum(out0.astype(jnp.float32)) * 1e-9

            return _lax.fori_loop(0, measure_iters, step, jnp.float32(0.0))
        jf = jax.jit(chained)
        float(jf(*args_c))                       # compile + warm
        t0 = _time.perf_counter()
        float(jf(*args_c))
        return (_time.perf_counter() - t0) / measure_iters * 1e3

    leaves = []
    for name, fn, args, params in rows:
        c = _cost(fn, *args)
        mult = cfg.num_layers if name.startswith("layer.") else 1
        row = {"name": name + (f" x{mult}" if mult > 1 else ""),
               "flops": c["flops"] * mult,
               "bytes": c["bytes"] * mult,
               "params": params * mult}
        if measure:
            row["ms"] = _measure_ms(fn, args) * mult
        leaves.append(row)
    total_fl = sum(r["flops"] for r in leaves) or 1.0
    for r in leaves:
        r["pct"] = 100.0 * r["flops"] / total_fl
    tree = {"name": f"model(b={b}, t={t})",
            "flops": sum(r["flops"] for r in leaves),
            "bytes": sum(r["bytes"] for r in leaves),
            "params": sum(r["params"] for r in leaves),
            "children": leaves,
            "top": sorted(leaves,
                          key=lambda r: -r.get("ms", r["flops"]))[:top_k]}
    if measure:
        tree["ms"] = sum(r["ms"] for r in leaves)
    return tree


def format_module_profile(tree: Dict[str, Any]) -> str:
    """Human-readable table (reference print_model_profile analogue)."""
    lines = [f"{tree['name']}: {tree['flops'] / 1e9:.2f} GFLOPs fwd, "
             f"{tree['bytes'] / 2**30:.2f} GiB moved, "
             f"{tree['params'] / 1e6:.1f}M params"]
    for r in sorted(tree["children"], key=lambda r: -r["flops"]):
        lines.append(
            f"  {r['name']:<24s} {r['flops'] / 1e9:10.2f} GF "
            f"{r['pct']:5.1f}%  {r['bytes'] / 2**20:10.1f} MiB  "
            f"{r['params'] / 1e6:8.2f}M"
            + (f"  {r['ms']:8.2f} ms" if "ms" in r else ""))
    return "\n".join(lines)


def get_model_profile(fn: Callable, args: Tuple,
                      print_profile: bool = True) -> Tuple[float, float, int]:
    """Reference get_model_profile API: returns (flops, macs, params).

    'macs' ≈ flops/2 (XLA counts multiply-adds as 2 flops); params counted
    from the first arg when it is a pytree of arrays.
    """
    cost = analyze_fn(fn, *args)
    flops = cost["flops"]
    params = 0
    if args:
        try:
            params = sum(int(np.prod(x.shape))
                         for x in jax.tree.leaves(args[0]))
        except Exception:
            params = 0
    if print_profile:
        log_dist(f"model profile: flops={flops:.3e} macs={flops / 2:.3e} "
                 f"params={params / 1e6:.1f}M "
                 f"bytes={cost.get('bytes_accessed', 0):.3e}")
    return flops, flops / 2, params
