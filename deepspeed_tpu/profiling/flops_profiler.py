"""FLOPs profiler.

Reference: ``profiling/flops_profiler/profiler.py:30`` — the reference
monkey-patches torch.nn.functional with counting wrappers. On TPU the
compiler already knows: ``jax.jit(fn).lower(...).compile().cost_analysis()``
returns XLA's own flop/byte counts for the exact compiled program,
including fusion effects — strictly more accurate than op-level patching.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def analyze_fn(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """Compile ``fn`` for the current devices and return XLA cost analysis:
    {'flops': ..., 'bytes accessed': ..., 'optimal_seconds': ...} (keys as
    XLA reports them, normalized a bit)."""
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(*args).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):          # per-device list on some backends
        cost = cost[0] if cost else {}
    out = {"flops": float(cost.get("flops", 0.0)),
           "bytes_accessed": float(cost.get("bytes accessed", 0.0))}
    try:
        mem = compiled.memory_analysis()
        out["peak_bytes"] = float(
            getattr(mem, "temp_size_in_bytes", 0) +
            getattr(mem, "argument_size_in_bytes", 0) +
            getattr(mem, "output_size_in_bytes", 0))
    except Exception:
        pass
    return out


class FlopsProfiler:
    """Step-granular profiler attached to an engine (reference
    profiler.py API: start_profile/stop_profile/print_model_profile)."""

    def __init__(self, engine=None, config=None):
        self.engine = engine
        self.config = config
        self._t0: Optional[float] = None
        self._steps = 0
        self.flops_per_step: Optional[float] = None
        self.last_tflops: Optional[float] = None

    def start_profile(self) -> None:
        self._t0 = time.perf_counter()
        self._steps = 0

    def step(self) -> None:
        self._steps += 1

    def stop_profile(self) -> Dict[str, float]:
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        result = {"seconds": dt, "steps": self._steps}
        if self.engine is not None and self.engine.model.flops_per_token:
            tokens = self._steps * int(self.engine.config.train_batch_size) \
                * (self.engine.model.tokens_per_sample or 1)
            flops = self.engine.model.flops_per_token * tokens
            result["tflops"] = flops / max(dt, 1e-9) / 1e12
            self.last_tflops = result["tflops"]
        return result

    def print_profile(self) -> None:
        log_dist(f"flops profiler: {self.stop_profile()}")


def get_model_profile(fn: Callable, args: Tuple,
                      print_profile: bool = True) -> Tuple[float, float, int]:
    """Reference get_model_profile API: returns (flops, macs, params).

    'macs' ≈ flops/2 (XLA counts multiply-adds as 2 flops); params counted
    from the first arg when it is a pytree of arrays.
    """
    cost = analyze_fn(fn, *args)
    flops = cost["flops"]
    params = 0
    if args:
        try:
            params = sum(int(np.prod(x.shape))
                         for x in jax.tree.leaves(args[0]))
        except Exception:
            params = 0
    if print_profile:
        log_dist(f"model profile: flops={flops:.3e} macs={flops / 2:.3e} "
                 f"params={params / 1e6:.1f}M "
                 f"bytes={cost.get('bytes_accessed', 0):.3e}")
    return flops, flops / 2, params
