"""Elastic batch-size scheduling.

Reference: ``deepspeed/elasticity/elasticity.py`` —
``compute_elastic_config``:233 with the v0.2 candidate-batch algorithm
(:126): enumerate micro_batch × accumulation products, keep batch sizes
with the widest device-count compatibility, prefer larger batches. On TPU
"gpus" are chips; preemption-driven slice resizes are the motivating
event instead of node failures.
"""

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger


def _candidate_batches(max_batch: int, micro_batches: List[int]) -> List[int]:
    candidates = set()
    for mb in micro_batches:
        acc = 1
        while mb * acc <= max_batch:
            candidates.add(mb * acc)
            acc += 1
    return sorted(candidates)


def _valid_device_counts(batch: int, micro_batches: List[int],
                         min_devices: int, max_devices: int) -> List[int]:
    out = set()
    for mb in micro_batches:
        if batch % mb:
            continue
        slots = batch // mb        # micro × dp combinations
        for dp in range(min_devices, min(max_devices, slots) + 1):
            if slots % dp == 0:
                out.add(dp)
    return sorted(out)


def get_compatible_gpus(micro_batches: List[int], max_train_batch_size: int,
                        min_gpus: int = 1, max_gpus: int = 10000,
                        prefer_larger: bool = True
                        ) -> Tuple[int, List[int], Dict[int, List[int]]]:
    """v0.2 algorithm (reference elasticity.py:126): returns
    (best_batch, valid_device_counts, all_candidates)."""
    candidates = _candidate_batches(max_train_batch_size, micro_batches)
    table: Dict[int, List[int]] = {}
    for b in candidates:
        counts = _valid_device_counts(b, micro_batches, min_gpus, max_gpus)
        if counts:
            table[b] = counts
    if not table:
        raise ValueError(
            f"no compatible batch size for micro_batches={micro_batches} "
            f"max={max_train_batch_size} devices=[{min_gpus},{max_gpus}]")
    best = max(table.items(),
               key=lambda kv: (len(kv[1]), kv[0] if prefer_larger else -kv[0]))
    return best[0], best[1], table


def compute_elastic_config(ds_config: dict, target_deltas=None,
                           world_size: int = 0
                           ) -> Tuple[int, int, int]:
    """Reference compute_elastic_config:233: returns
    (final_batch_size, valid_gpus, micro_batch) for the current world."""
    e = ds_config.get("elasticity", {})
    if not e.get("enabled", False):
        raise ValueError("elasticity not enabled in config")
    micro_batches = e.get("micro_batch_sizes", [2, 4, 6])
    best_batch, valid, _ = get_compatible_gpus(
        micro_batches, e.get("max_train_batch_size", 2000),
        e.get("min_gpus", 1), e.get("max_gpus", 10000),
        e.get("prefer_larger_batch", True))
    micro = None
    if world_size:
        if world_size not in valid:
            raise ValueError(
                f"world size {world_size} incompatible with elastic batch "
                f"{best_batch} (valid: {valid})")
        per_rank = best_batch // world_size
        for mb in sorted(micro_batches, reverse=True):
            if per_rank % mb == 0:
                micro = mb
                break
        micro = micro or micro_batches[0]
        logger.info(f"elasticity: batch={best_batch} world={world_size} "
                    f"micro={micro} gas={per_rank // micro}")
    return best_batch, valid, micro
