"""Elastic agent — preemption-aware checkpoint/resume.

Reference: ``elasticity/elastic_agent.py:32`` (``DSElasticAgent`` plugging
into torchelastic: monitors workers, restarts within ``max_restarts``).
TPU pods get PREEMPTED (maintenance events / spot reclaims deliver
SIGTERM), so the TPU-native agent's job is: catch the signal, commit a
checkpoint at the next step boundary, exit cleanly, and on relaunch resume
from `latest` — plus an in-process restart loop for transient failures
(the analogue of torchelastic's worker-group restarts; multi-host
relaunch itself is the launcher's job, launcher/runner.py).
"""

import os
import signal
import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple, Type

from deepspeed_tpu.utils.logging import log_dist, logger


class Preempted(SystemExit):
    """Raised at a step boundary after SIGTERM; carries the saved tag and
    the flight-recorder black-box path (when one was written)."""

    def __init__(self, tag: Optional[str],
                 blackbox_path: Optional[str] = None):
        self.tag = tag
        self.blackbox_path = blackbox_path
        super().__init__(143)


class DSElasticAgent:
    """Wrap an engine with signal-driven checkpointing.

    Usage::

        agent = DSElasticAgent(engine, save_dir)
        agent.install()                 # SIGTERM/SIGUSR1 handlers
        agent.resume()                  # load `latest` if present
        for batch in data:
            engine.train_batch(...)
            agent.step_boundary()       # raises Preempted after a signal
    """

    def __init__(self, engine, save_dir: str,
                 save_on: tuple = (signal.SIGTERM,)):
        self.engine = engine
        self.save_dir = save_dir
        self.save_on = save_on
        self._signaled = False
        self._committing = False
        self._prev_handlers: Dict[int, Any] = {}

    def install(self) -> None:
        for sig in self.save_on:
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        log_dist(f"elastic agent armed on signals "
                 f"{[signal.Signals(s).name for s in self.save_on]}")

    def uninstall(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()

    def _handler(self, signum, frame) -> None:
        logger.warning(f"elastic agent: received "
                       f"{signal.Signals(signum).name}; will checkpoint "
                       f"at the next step boundary")
        self._signaled = True
        # chain to whatever was installed before us (a launcher's own
        # handler, a test harness) — installing the agent must not
        # silently disconnect someone else's signal logic
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)

    @property
    def preemption_pending(self) -> bool:
        return self._signaled

    def step_boundary(self) -> None:
        """Call once per training step; commits + raises on a pending
        signal (the reference agent stops the worker group the same
        way)."""
        if not self._signaled:
            return
        # re-entrancy guard: a SECOND SIGTERM landing while the commit
        # below runs re-enters here via the chained handler / a nested
        # boundary call; committing the same tag twice would race the
        # fragment writes against themselves
        if self._committing:
            return
        self._committing = True
        tag = f"preempt_step{self.engine.global_steps}"
        self.engine.save_checkpoint(self.save_dir, tag=tag)
        # dump the flight recorder next to the checkpoint: the relaunch
        # operator gets BOTH artifacts (what to resume from + what the
        # last steps looked like) from this one exit line
        blackbox = None
        try:
            from deepspeed_tpu.telemetry import flight_recorder
            flight_recorder.record_event(
                "preemption", checkpoint_tag=tag,
                step=self.engine.global_steps)
            blackbox = flight_recorder.dump(
                os.path.join(self.save_dir, f"blackbox_{tag}.json"),
                reason="preemption")
        except Exception as e:
            logger.warning(f"elastic agent: flight-recorder dump failed: "
                           f"{e}")
        log_dist(f"elastic agent: checkpoint '{tag}' committed, "
                 f"flight-recorder dump "
                 f"{blackbox or 'unavailable'}, exiting")
        raise Preempted(tag, blackbox_path=blackbox)

    def resume(self) -> Optional[str]:
        """Load the newest checkpoint if one exists (relaunch path)."""
        tag, _ = self.engine.load_checkpoint(self.save_dir)
        if tag:
            log_dist(f"elastic agent: resumed from '{tag}' at step "
                     f"{self.engine.global_steps}")
            if tag.startswith("preempt_"):
                # closes the loop on an injected (or real) preemption:
                # the fault is recovered once training restarts from
                # the boundary checkpoint it forced
                from deepspeed_tpu.resilience.faults import record_recovery
                record_recovery("elastic_resume", tag=tag,
                                step=self.engine.global_steps)
        return tag


def elastic_resume(model, ds_config: Dict[str, Any], save_dir: str,
                   world_size: int, devices=None, rng=None):
    """Re-form training at a NEW world size from the latest checkpoint.

    The reference agent restarts its worker group through a rendezvous at
    whatever world size re-admits (elasticity/elastic_agent.py:127 +
    compute_elastic_config:233); the TPU analogue: solve the elastic
    batch triple for ``world_size``, rebuild the mesh over that many
    devices, initialize a fresh engine, and resume from the universal
    checkpoint (which is layout-free by construction — any dp/tp
    topology can load it). Returns (engine, agent, resumed_tag).

    ``ds_config`` must carry an enabled ``elasticity`` block; its batch
    triple is OVERWRITTEN with the solver's choice for the new world.
    """
    import copy

    import jax

    from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import initialize

    cfg = copy.deepcopy(ds_config if isinstance(ds_config, dict)
                        else ds_config.to_dict())
    batch, _valid, micro = compute_elastic_config(cfg,
                                                  world_size=world_size)
    cfg["train_batch_size"] = batch
    cfg["train_micro_batch_size_per_gpu"] = micro
    cfg.pop("gradient_accumulation_steps", None)   # solver-derived
    devs = devices if devices is not None else jax.devices()[:world_size]
    build_mesh(data=world_size, devices=devs)
    engine, *_ = initialize(model=model, config=cfg, rng=rng)
    agent = DSElasticAgent(engine, save_dir)
    agent.install()
    tag = agent.resume()
    log_dist(f"elastic_resume: world={world_size} batch={batch} "
             f"micro={micro} resumed={tag or 'fresh start'}")
    return engine, agent, tag


#: exception types a restart cannot fix — a bad config or a coding bug
#: fails identically on every attempt; retrying only delays the report
NON_TRANSIENT: Tuple[Type[BaseException], ...] = (
    ValueError, TypeError, KeyError, NotImplementedError, AssertionError)


def run_elastic(train_fn: Callable[[int], Any], max_restarts: int = 3,
                backoff_s: float = 1.0, max_backoff_s: float = 30.0,
                _sleep=time.sleep) -> Any:
    """In-process restart loop (reference DSElasticAgent._invoke_run:127
    restart-on-failure semantics). ``train_fn(attempt)`` should build its
    engine, ``resume()``, and train; transient exceptions trigger a
    restart (with capped exponential backoff) up to ``max_restarts``.

    What does NOT restart: ``Preempted`` exits cleanly (the relaunch is
    the launcher's job); ``KeyboardInterrupt``/``SystemExit`` propagate —
    an operator's Ctrl-C must stop the job, not schedule attempt 2; and
    :data:`NON_TRANSIENT` types re-raise immediately — deterministic
    failures never earn a retry."""
    last: Optional[BaseException] = None
    for attempt in range(max_restarts + 1):
        try:
            return train_fn(attempt)
        except Preempted:
            raise
        except (KeyboardInterrupt, SystemExit):
            raise
        except NON_TRANSIENT:
            raise
        except Exception as e:          # noqa: BLE001 — restart policy
            last = e
            if attempt >= max_restarts:
                break
            delay = min(backoff_s * (2 ** attempt), max_backoff_s)
            logger.warning(f"elastic restart {attempt + 1}/{max_restarts} "
                           f"after: {e} (backoff {delay:.1f}s)")
            if delay > 0:
                _sleep(delay)
    raise RuntimeError(
        f"training failed after {max_restarts} restarts") from last
