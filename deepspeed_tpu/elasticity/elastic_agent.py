"""Elastic agent — preemption-aware checkpoint/resume.

Reference: ``elasticity/elastic_agent.py:32`` (``DSElasticAgent`` plugging
into torchelastic: monitors workers, restarts within ``max_restarts``).
TPU pods get PREEMPTED (maintenance events / spot reclaims deliver
SIGTERM), so the TPU-native agent's job is: catch the signal, commit a
checkpoint at the next step boundary, exit cleanly, and on relaunch resume
from `latest` — plus an in-process restart loop for transient failures
(the analogue of torchelastic's worker-group restarts; multi-host
relaunch itself is the launcher's job, launcher/runner.py).
"""

import os
import signal
import sys
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


class Preempted(SystemExit):
    """Raised at a step boundary after SIGTERM; carries the saved tag and
    the flight-recorder black-box path (when one was written)."""

    def __init__(self, tag: Optional[str],
                 blackbox_path: Optional[str] = None):
        self.tag = tag
        self.blackbox_path = blackbox_path
        super().__init__(143)


class DSElasticAgent:
    """Wrap an engine with signal-driven checkpointing.

    Usage::

        agent = DSElasticAgent(engine, save_dir)
        agent.install()                 # SIGTERM/SIGUSR1 handlers
        agent.resume()                  # load `latest` if present
        for batch in data:
            engine.train_batch(...)
            agent.step_boundary()       # raises Preempted after a signal
    """

    def __init__(self, engine, save_dir: str,
                 save_on: tuple = (signal.SIGTERM,)):
        self.engine = engine
        self.save_dir = save_dir
        self.save_on = save_on
        self._signaled = False
        self._prev_handlers: Dict[int, Any] = {}

    def install(self) -> None:
        for sig in self.save_on:
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        log_dist(f"elastic agent armed on signals "
                 f"{[signal.Signals(s).name for s in self.save_on]}")

    def uninstall(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()

    def _handler(self, signum, frame) -> None:
        logger.warning(f"elastic agent: received "
                       f"{signal.Signals(signum).name}; will checkpoint "
                       f"at the next step boundary")
        self._signaled = True

    @property
    def preemption_pending(self) -> bool:
        return self._signaled

    def step_boundary(self) -> None:
        """Call once per training step; commits + raises on a pending
        signal (the reference agent stops the worker group the same
        way)."""
        if not self._signaled:
            return
        tag = f"preempt_step{self.engine.global_steps}"
        self.engine.save_checkpoint(self.save_dir, tag=tag)
        # dump the flight recorder next to the checkpoint: the relaunch
        # operator gets BOTH artifacts (what to resume from + what the
        # last steps looked like) from this one exit line
        blackbox = None
        try:
            from deepspeed_tpu.telemetry import flight_recorder
            flight_recorder.record_event(
                "preemption", checkpoint_tag=tag,
                step=self.engine.global_steps)
            blackbox = flight_recorder.dump(
                os.path.join(self.save_dir, f"blackbox_{tag}.json"),
                reason="preemption")
        except Exception as e:
            logger.warning(f"elastic agent: flight-recorder dump failed: "
                           f"{e}")
        log_dist(f"elastic agent: checkpoint '{tag}' committed, "
                 f"flight-recorder dump "
                 f"{blackbox or 'unavailable'}, exiting")
        raise Preempted(tag, blackbox_path=blackbox)

    def resume(self) -> Optional[str]:
        """Load the newest checkpoint if one exists (relaunch path)."""
        tag, _ = self.engine.load_checkpoint(self.save_dir)
        if tag:
            log_dist(f"elastic agent: resumed from '{tag}' at step "
                     f"{self.engine.global_steps}")
        return tag


def elastic_resume(model, ds_config: Dict[str, Any], save_dir: str,
                   world_size: int, devices=None, rng=None):
    """Re-form training at a NEW world size from the latest checkpoint.

    The reference agent restarts its worker group through a rendezvous at
    whatever world size re-admits (elasticity/elastic_agent.py:127 +
    compute_elastic_config:233); the TPU analogue: solve the elastic
    batch triple for ``world_size``, rebuild the mesh over that many
    devices, initialize a fresh engine, and resume from the universal
    checkpoint (which is layout-free by construction — any dp/tp
    topology can load it). Returns (engine, agent, resumed_tag).

    ``ds_config`` must carry an enabled ``elasticity`` block; its batch
    triple is OVERWRITTEN with the solver's choice for the new world.
    """
    import copy

    import jax

    from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import initialize

    cfg = copy.deepcopy(ds_config if isinstance(ds_config, dict)
                        else ds_config.to_dict())
    batch, _valid, micro = compute_elastic_config(cfg,
                                                  world_size=world_size)
    cfg["train_batch_size"] = batch
    cfg["train_micro_batch_size_per_gpu"] = micro
    cfg.pop("gradient_accumulation_steps", None)   # solver-derived
    devs = devices if devices is not None else jax.devices()[:world_size]
    build_mesh(data=world_size, devices=devs)
    engine, *_ = initialize(model=model, config=cfg, rng=rng)
    agent = DSElasticAgent(engine, save_dir)
    agent.install()
    tag = agent.resume()
    log_dist(f"elastic_resume: world={world_size} batch={batch} "
             f"micro={micro} resumed={tag or 'fresh start'}")
    return engine, agent, tag


def run_elastic(train_fn: Callable[[int], Any], max_restarts: int = 3
                ) -> Any:
    """In-process restart loop (reference DSElasticAgent._invoke_run:127
    restart-on-failure semantics). ``train_fn(attempt)`` should build its
    engine, ``resume()``, and train; transient exceptions trigger a
    restart up to ``max_restarts``; ``Preempted`` exits cleanly."""
    last: Optional[BaseException] = None
    for attempt in range(max_restarts + 1):
        try:
            return train_fn(attempt)
        except Preempted:
            raise
        except BaseException as e:      # noqa: BLE001 — restart policy
            last = e
            logger.warning(f"elastic restart {attempt + 1}/{max_restarts} "
                           f"after: {e}")
    raise RuntimeError(
        f"training failed after {max_restarts} restarts") from last
