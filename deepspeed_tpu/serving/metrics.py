"""Serving observability: latency histograms → monitor events.

TTFT (time-to-first-token), TPOT (time-per-output-token), queue depth and
prefix-cache hit rate are the four numbers an operator actually pages on;
they are kept as fixed-bucket histograms host-side (no device traffic) and
flushed through :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster` as
``serving/*`` events so whatever writer stack training already configured
(TensorBoard/W&B/Comet/CSV) picks them up unchanged.
"""

import bisect
import math
from typing import Dict, List, Optional, Tuple


class Histogram:
    """Fixed log-spaced buckets; O(log B) record, exact count/sum."""

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 n_buckets: int = 40):
        ratio = (hi / lo) ** (1.0 / (n_buckets - 1))
        self.bounds = [lo * ratio ** i for i in range(n_buckets)]
        self.counts = [0] * (n_buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def record(self, v: float) -> None:
        if not math.isfinite(v):
            return
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket holding the p-th percentile sample."""
        if not self.count:
            return 0.0
        target = p / 100.0 * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean,
                "p50": self.percentile(50), "p99": self.percentile(99),
                "min": self.vmin or 0.0, "max": self.vmax or 0.0}


class ServingMetrics:
    """Aggregates the frontend's counters + histograms and emits them."""

    def __init__(self):
        self.ttft = Histogram()
        self.tpot = Histogram(lo=1e-5, hi=10.0)
        self.queue_depth = Histogram(lo=1.0, hi=4096.0, n_buckets=13)
        self.counters: Dict[str, int] = {
            "admitted": 0, "completed": 0, "cancelled": 0, "shed": 0,
            "rejected_queue_full": 0, "rejected_kv_exhausted": 0,
            "rejected_too_long": 0, "tokens_out": 0,
            "prefix_tokens_reused": 0, "engine_steps": 0,
        }

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def events(self, cache=None, step: int = 0
               ) -> List[Tuple[str, float, int]]:
        ev: List[Tuple[str, float, int]] = []
        for key, h in (("ttft", self.ttft), ("tpot", self.tpot),
                       ("queue_depth", self.queue_depth)):
            if h.count:
                ev.append((f"serving/{key}_mean", h.mean, step))
                ev.append((f"serving/{key}_p99", h.percentile(99), step))
        for name, val in self.counters.items():
            ev.append((f"serving/{name}", float(val), step))
        if cache is not None:
            ev.append(("serving/prefix_hit_rate", cache.hit_rate, step))
            ev.append(("serving/prefix_pages_cached",
                       float(cache.pages_cached), step))
        return ev

    def emit(self, monitor, cache=None, step: int = 0) -> None:
        """Flush to a MonitorMaster (no-op when monitoring is disabled)."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        monitor.write_events(self.events(cache, step))
