"""Serving observability: latency histograms → monitor events.

TTFT (time-to-first-token), TPOT (time-per-output-token), queue depth and
prefix-cache hit rate are the four numbers an operator actually pages on;
they are kept as fixed-bucket histograms host-side (no device traffic) and
flushed through :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster` as
``serving/*`` events so whatever writer stack training already configured
(TensorBoard/W&B/Comet/CSV) picks them up unchanged.

The histogram implementation lives in
:mod:`deepspeed_tpu.telemetry.registry` (one bucketing implementation for
the repo); each :class:`ServingMetrics` also publishes its histograms into
the process-wide registry under ``serving/ttft_seconds`` /
``serving/tpot_seconds`` / ``serving/queue_depth`` and mirrors its
counters, so ``telemetry.metrics_text()`` exposes them in Prometheus
format alongside the ``train/*`` series.
"""

from typing import Dict, List, Optional, Tuple

# Histogram moved to the unified registry; re-exported here so existing
# `from deepspeed_tpu.serving.metrics import Histogram` imports keep working
from deepspeed_tpu.telemetry.registry import Histogram  # noqa: F401
from deepspeed_tpu.telemetry.registry import registry as _registry


class ServingMetrics:
    """Aggregates the frontend's counters + histograms and emits them.

    Instance-local (one per frontend, tests assert exact counts) but
    registered process-wide with ``replace=True`` so the registry always
    exposes the most recently constructed frontend's histograms.
    """

    def __init__(self):
        self.ttft = Histogram()
        self.tpot = Histogram(lo=1e-5, hi=10.0)
        self.queue_depth = Histogram(lo=1.0, hi=4096.0, n_buckets=13)
        # decode megastep window sizes actually run (tokens per fused
        # launch) — the K-selection policy's observable output
        self.megastep_k = Histogram(lo=1.0, hi=1024.0, n_buckets=11)
        _registry.register("serving/ttft_seconds", self.ttft,
                           help="time to first token (s)", replace=True)
        _registry.register("serving/tpot_seconds", self.tpot,
                           help="time per output token (s)", replace=True)
        _registry.register("serving/queue_depth", self.queue_depth,
                           help="admission queue depth at step start",
                           replace=True)
        _registry.register("serving/megastep_k", self.megastep_k,
                           help="decode megastep window size (tokens)",
                           replace=True)
        self.counters: Dict[str, int] = {
            "admitted": 0, "completed": 0, "cancelled": 0, "shed": 0,
            "rejected_queue_full": 0, "rejected_kv_exhausted": 0,
            "rejected_too_long": 0, "rejected_slo": 0, "tokens_out": 0,
            "prefix_tokens_reused": 0, "engine_steps": 0, "megasteps": 0,
        }

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by
        if by > 0:   # registry counters are process-wide and monotonic
            _registry.counter(f"serving/{name}").inc(by)

    def events(self, cache=None, step: int = 0
               ) -> List[Tuple[str, float, int]]:
        ev: List[Tuple[str, float, int]] = []
        for key, h in (("ttft", self.ttft), ("tpot", self.tpot),
                       ("queue_depth", self.queue_depth),
                       ("megastep_k", self.megastep_k)):
            if h.count:
                ev.append((f"serving/{key}_mean", h.mean, step))
                ev.append((f"serving/{key}_p99", h.percentile(99), step))
        for name, val in self.counters.items():
            ev.append((f"serving/{name}", float(val), step))
        if cache is not None:
            ev.append(("serving/prefix_hit_rate", cache.hit_rate, step))
            ev.append(("serving/prefix_pages_cached",
                       float(cache.pages_cached), step))
        return ev

    def emit(self, monitor, cache=None, step: int = 0) -> None:
        """Flush to a MonitorMaster (no-op when monitoring is disabled)."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        monitor.write_events(self.events(cache, step))
