"""SLO-driven autoscaler for the serving fleet (``autoscale.*``).

Sizes the router's replica pools — ``prefill`` and ``decode``
separately on a disaggregated fleet, the single ``any`` pool otherwise —
from three live signals:

- **admission pressure**: mean in-flight load per live replica against
  ``queue_high`` (the queueing-theory knee: past it, TTFT grows faster
  than linearly and hedging only burns capacity);
- **SLO burn rate**: the ``slo/worst_burn`` gauge from the burn-rate
  engine — a fast-window breach means the error budget is burning NOW,
  so capacity is added even before the queue shows it;
- **sustained idle**: a pool at zero load for ``idle_s`` shrinks toward
  its floor — diurnal troughs give capacity back.

Scale-up calls ``spawn_fn(pool)`` (which builds a replica and
``router.add_replica``\\ s it — locally an in-process engine, in a real
fleet a :class:`~deepspeed_tpu.launcher.agent.ReplicaPoolAgent` spawn).
Scale-down is SEQUENCED so no stream and no KV page is dropped:
``router.drain(name, deadline_s)`` stops admissions → in-flight decodes
finish (stragglers past the deadline fail over with the token fold) →
the router removes the replica and ``close()`` releases its KV → only
then does ``drain_fn(name)`` let the process owner SIGTERM it. A
replica killed mid-scale-down is just a ``replica_kill`` fault: its
streams fail over and the ledger still closes.

A per-pool ``cooldown_s`` guards against flapping (a scale action
freezes further actions on that pool until the new capacity has had
time to move the signals). All decisions publish ``autoscale/*``
metrics and flight-recorder events so ``dstpu-doctor`` can replay the
elasticity timeline.
"""

import math
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry.registry import registry as _registry
from deepspeed_tpu.utils.logging import logger


class Autoscaler:
    """Watches a :class:`~deepspeed_tpu.serving.router.Router` and asks
    for replicas to be spawned or drained, per pool.

    Pure decision logic over an injectable ``clock`` — the tests drive
    it on a fake clock; the bench drives it from the request loop.
    """

    def __init__(self, router, *,
                 spawn_fn: Callable[[str], Any],
                 drain_fn: Optional[Callable[[str], Any]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 prefill_min: int = 1, prefill_max: int = 4,
                 decode_min: int = 1, decode_max: int = 8,
                 queue_high: float = 4.0,
                 idle_s: float = 5.0,
                 cooldown_s: float = 10.0,
                 evaluate_every_s: float = 1.0,
                 burn_threshold: float = 1.0,
                 burn_fn: Optional[Callable[[], float]] = None,
                 drain_deadline_s: float = 30.0):
        self.router = router
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self.clock = clock
        self.queue_high = float(queue_high)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        self.evaluate_every_s = float(evaluate_every_s)
        self.burn_threshold = float(burn_threshold)
        self.burn_fn = burn_fn
        self.drain_deadline_s = float(drain_deadline_s)
        self.floors = {"prefill": int(prefill_min),
                       "decode": int(decode_min),
                       "any": int(max(1, min(prefill_min, decode_min)))}
        self.ceilings = {"prefill": int(prefill_max),
                         "decode": int(decode_max),
                         "any": int(max(prefill_max, decode_max))}
        for p in ("prefill", "decode", "any"):
            if self.floors[p] > self.ceilings[p]:
                raise ValueError(
                    f"autoscale pool {p!r}: floor {self.floors[p]} > "
                    f"ceiling {self.ceilings[p]}")
        self._idle_since: Dict[str, Optional[float]] = {}
        self._last_action: Dict[str, float] = {}
        self._last_eval: Optional[float] = None

    # -- signals ------------------------------------------------------------

    def _burn(self) -> float:
        if self.burn_fn is not None:
            return float(self.burn_fn())
        v = _registry.gauge("slo/worst_burn").value
        return float(v) if v is not None else 0.0

    def _pools(self) -> List[str]:
        return (["prefill", "decode"] if self.router.disaggregated
                else ["any"])

    # -- decision -----------------------------------------------------------

    def _desired(self, pool: str, members, now: float) -> int:
        n = len(members)
        if n == 0:
            return self.floors[pool]
        load = sum(r.load() for r in members)
        target = n
        if load / n > self.queue_high:
            # enough replicas that mean load sits at the knee again
            target = max(target, math.ceil(load / self.queue_high))
        if self._burn() >= self.burn_threshold:
            # the error budget is burning: add capacity even before the
            # queue depth says so (burn leads queue by a fast window)
            target = max(target, n + 1)
        if load == 0:
            t0 = self._idle_since.get(pool)
            if t0 is None:
                self._idle_since[pool] = now
            elif now - t0 >= self.idle_s and target <= n:
                # shrink only when nothing wants capacity: an SLO burn
                # against an empty queue (latency, not depth) must win
                target = min(target, n - 1)
        else:
            self._idle_since[pool] = None
        return max(self.floors[pool], min(self.ceilings[pool], target))

    def _scale_down_victim(self, pool: str, members):
        # the least-loaded live member drains fastest and strands the
        # fewest streams behind the drain deadline
        return min(members, key=lambda r: (r.load(), r.name))

    # -- driver -------------------------------------------------------------

    def maybe_evaluate(self) -> int:
        """Evaluate at most every ``evaluate_every_s``; returns replicas
        added minus replicas put into drain (0 when off-cadence)."""
        now = self.clock()
        if self._last_eval is not None and \
                now - self._last_eval < self.evaluate_every_s:
            return 0
        return self.evaluate()

    def evaluate(self) -> int:
        """One scaling decision per pool. Returns net replica delta."""
        now = self.clock()
        self._last_eval = now
        _registry.counter(
            "autoscale/evaluations",
            help="autoscaler decision passes").inc()
        delta = 0
        for pool in self._pools():
            members = self.router.pool_members(pool)
            n = len(members)
            target = self._desired(pool, members, now)
            _registry.gauge(
                f"autoscale/target/{pool}",
                help="autoscaler's desired replica count").set(target)
            _registry.gauge(
                f"autoscale/replicas/{pool}",
                help="live non-draining replicas in the pool").set(n)
            if target == n:
                continue
            last = self._last_action.get(pool)
            if last is not None and now - last < self.cooldown_s:
                continue         # flapping guard: let the last move land
            if target > n:
                added = 0
                for _ in range(target - n):
                    try:
                        self.spawn_fn(pool)
                    except Exception as e:   # noqa: BLE001 — capacity may
                        logger.warning(      # genuinely be exhausted
                            "autoscale: spawn for pool %s failed: %s",
                            pool, e)
                        break
                    added += 1
                if not added:
                    continue
                delta += added
                self._last_action[pool] = now
                _registry.counter(
                    "autoscale/scale_ups",
                    help="replicas added by the autoscaler").inc(added)
                telemetry.flight_recorder.record_event(
                    "autoscale_up", pool=pool, added=added,
                    target=target)
                logger.warning("autoscale: pool %s %d→%d (+%d)",
                               pool, n, n + added, added)
            else:
                # shrink ONE replica per action — drain is asynchronous
                # and the next evaluation sees the smaller pool
                victim = self._scale_down_victim(pool, members)
                self.router.drain(victim.name,
                                  deadline_s=self.drain_deadline_s)
                if self.drain_fn is not None:
                    try:
                        self.drain_fn(victim.name)
                    except Exception as e:   # noqa: BLE001
                        logger.warning(
                            "autoscale: drain callback for %s failed: "
                            "%s", victim.name, e)
                delta -= 1
                self._last_action[pool] = now
                _registry.counter(
                    "autoscale/scale_downs",
                    help="replicas drained by the autoscaler").inc()
                telemetry.flight_recorder.record_event(
                    "autoscale_down", pool=pool, replica=victim.name,
                    target=target)
                logger.warning("autoscale: pool %s %d→%d (draining %s)",
                               pool, n, n - 1, victim.name)
        return delta
