"""KV-page handoff between disaggregated prefill and decode replicas.

Prefill and decode sit on opposite corners of the roofline (compute-bound
ragged prefill vs bandwidth-bound decode), so the router can run them on
separate replica pools — but only if a finished prefill's KV pages can
move. This module is that move: serialize the radix-cached pages covering
a prompt out of the prefill replica's arena (``engine.export_pages``),
ship them as a checksummed :class:`PageBundle`, and adopt them into the
decode replica's arena + radix cache (``engine.import_pages`` +
``PrefixCache.insert``), where the decode leg's normal ``adopt_cached``
admission aliases them and re-prefills only the folded first token.

The failure domain is deliberately boring: a bundle that is torn
(checksum mismatch — ``handoff_torn``), timed out (``handoff_stall``),
or simply absent adopts ZERO pages, and the decode replica re-prefills
the folded prompt from scratch. Tokens are never carried in the bundle —
they ride the router's fold — so a failed handoff costs recompute, never
correctness.

Ownership protocol (the accounting the round-trip test pins down):
``adopt_bundle`` allocates destination pages (refcount 1, ours), imports
the KV, offers them to the destination cache (``insert`` increfs what it
keeps), then drops its own ref — pages the cache kept end at refcount 1
owned by the cache; pages it declined (already cached, page-cap) return
to the pool. The source side then ``invalidate``s the shipped subtree, so
neither arena leaks a page and no page is double-freed.
"""

import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class PageBundle:
    """One prefill's cached KV pages in transit.

    ``tokens`` are the prompt tokens the pages cover (full pages first,
    then the partial last page's span); ``pages`` is the
    ``engine.export_pages`` payload (``{"k","v"}: [kvh, L, m, bs, dh]``);
    ``checksum`` is CRC32 over the payload bytes — :func:`verify_bundle`
    is the torn-transfer detector."""
    tokens: List[int]
    block_size: int
    pages: Dict[str, np.ndarray] = field(repr=False)
    checksum: int = 0

    @property
    def num_pages(self) -> int:
        return int(self.pages["k"].shape[2]) if self.pages else 0

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.pages.values())


def _checksum(pages: Dict[str, np.ndarray]) -> int:
    crc = 0
    for key in sorted(pages):
        crc = zlib.crc32(np.ascontiguousarray(pages[key]).tobytes(), crc)
    return crc


def verify_bundle(bundle: PageBundle) -> bool:
    """True when the payload still matches its checksum (not torn)."""
    return bundle.checksum == _checksum(bundle.pages)


def export_bundle(frontend, prompt: List[int]) -> Optional[PageBundle]:
    """Serialize the radix-cached pages covering ``prompt`` from a
    prefill replica. Returns ``None`` when nothing is cached (no prefix
    cache, or the prompt's pages were already evicted) — the caller
    falls back to decode-side re-prefill.

    Read-only on the source: pages stay cached (and refcounted) until
    the caller invalidates the subtree after the ship."""
    cache = getattr(frontend, "cache", None)
    if cache is None:
        return None
    bs = cache.block_size
    m = cache.match(prompt)
    blocks = list(m.full_blocks)
    covered = len(blocks) * bs
    if m.partial_block is not None:
        blocks.append(m.partial_block)
        covered += m.partial_len
    if not blocks:
        return None
    pages = frontend.engine.export_pages(blocks)
    return PageBundle(tokens=[int(t) for t in prompt[:covered]],
                      block_size=bs, pages=pages,
                      checksum=_checksum(pages))


def adopt_bundle(frontend, bundle: PageBundle) -> int:
    """Adopt a shipped bundle into a decode replica's arena + radix
    cache; returns pages the destination cache now holds (0 → caller
    falls back to plain re-prefill). Never leaks: destination pages are
    allocated, imported, offered to the cache, and this function's own
    ref is dropped whether or not the cache kept them."""
    cache = getattr(frontend, "cache", None)
    n = bundle.num_pages
    if cache is None or n == 0:
        return 0
    alloc = frontend.engine.state.allocator
    if n > alloc.free_blocks:
        cache.evict(n - alloc.free_blocks)
    if n > alloc.free_blocks:
        return 0
    blocks = alloc.allocate(n)
    try:
        frontend.engine.import_pages(bundle.pages, blocks)
        added = cache.insert(bundle.tokens, blocks)
    finally:
        alloc.free(blocks)
    return added
