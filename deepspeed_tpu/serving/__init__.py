"""deepspeed_tpu.serving — prefix-cached, SLO-aware serving frontend.

The layer the reference ships as DeepSpeed-MII on top of FastGen
(mii/batching/ragged_batching.py): request lifecycle + admission control,
a radix prefix cache over ref-counted KV pages, a SplitFuse token-budget
scheduling policy, and per-token streaming with TTFT/TPOT observability.
Here it drives :class:`~deepspeed_tpu.inference.engine_v2.
RaggedInferenceEngineTPU` through its ``step_with_budget`` entry point —
the engine stays a pure batch machine; everything traffic-shaped lives in
this package. See docs/serving.md.
"""

from deepspeed_tpu.serving.autoscaler import Autoscaler  # noqa: F401
from deepspeed_tpu.serving.frontend import ServingFrontend, adopt_cached  # noqa: F401
from deepspeed_tpu.serving.handoff import (PageBundle, adopt_bundle,  # noqa: F401
                                           export_bundle, verify_bundle)
from deepspeed_tpu.serving.kvtier import KVTier, TornSpill  # noqa: F401
from deepspeed_tpu.serving.metrics import Histogram, ServingMetrics  # noqa: F401
from deepspeed_tpu.serving.prefix_cache import PrefixCache, PrefixMatch  # noqa: F401
from deepspeed_tpu.serving.queue import AdmissionError, AdmissionQueue  # noqa: F401
from deepspeed_tpu.serving.request import Request, RequestState  # noqa: F401
from deepspeed_tpu.serving.router import (CircuitBreaker, LocalReplica,  # noqa: F401
                                          Router, RouterRequest)
from deepspeed_tpu.serving.scheduler import TokenBudgetPolicy  # noqa: F401

__all__ = ["ServingFrontend", "adopt_cached", "Request", "RequestState",
           "AdmissionQueue", "AdmissionError", "PrefixCache", "PrefixMatch",
           "TokenBudgetPolicy", "ServingMetrics", "Histogram",
           "Router", "RouterRequest", "LocalReplica", "CircuitBreaker",
           "PageBundle", "export_bundle", "adopt_bundle", "verify_bundle",
           "KVTier", "TornSpill", "Autoscaler"]
