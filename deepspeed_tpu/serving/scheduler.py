"""SplitFuse token-budget scheduling policy.

Generalizes the selection logic that ``RaggedScheduler.next_batch`` /
``engine_v2._run_fused_chunk`` hard-coded into a policy object the
frontend installs on the engine's scheduler (``scheduler.policy = ...``).
Each engine step packs a fixed token budget mixing single-token decodes of
running sequences with prefill chunks of newly admitted ones (Dynamic
SplitFuse, arXiv:2401.08671): decode rows ride every step (bounded TPOT)
while leftover budget drains prefill FIFO (bounded, starvation-free TTFT).
"""

from typing import List, Tuple


class TokenBudgetPolicy:
    """select() contract: ``(state, budget, prefill_chunk) →
    [(uid, take), ...]`` over ``state.seqs``.

    Decode rows (pending == 1) are packed first, rotated round-robin so a
    budget smaller than the decode population still serves every row
    within a bounded number of steps. Remaining budget goes to prefill
    (pending > 1) in arrival order — strict FIFO means the oldest prefill
    always drains first, so no request waits forever behind a stream of
    later arrivals (starvation-freedom; tested in test_serving.py).
    """

    def __init__(self, decode_priority: bool = True):
        self.decode_priority = decode_priority
        self._arrival: dict = {}
        self._next_arrival = 0
        self._rr = 0                 # decode round-robin offset

    def decode_backlog(self, state) -> Tuple[int, int]:
        """``(decode_rows, prefill_rows)`` over the live selectable
        sequences — the frontend's megastep K policy keys off this view
        of the NEXT selection: any prefill row means the coming batch is
        mixed (megastep inapplicable, K=1), while a pure decode backlog's
        depth scales how many tokens one device window may run. Counts
        every engine sequence, not just frontend-owned ones, because
        ``select`` packs from the same population."""
        dec = pre = 0
        for seq in state.seqs.values():
            if seq.done or seq.pending == 0:
                continue
            if seq.pending == 1:
                dec += 1
            else:
                pre += 1
        return dec, pre

    def note_arrival(self, uid: int) -> None:
        """Frontend stamps admission order (uid values may be arbitrary)."""
        if uid not in self._arrival:
            self._arrival[uid] = self._next_arrival
            self._next_arrival += 1

    def forget(self, uid: int) -> None:
        self._arrival.pop(uid, None)

    def select(self, state, budget: int,
               prefill_chunk: int) -> List[Tuple[int, int]]:
        decodes: List[int] = []
        prefills: List[int] = []
        for uid, seq in state.seqs.items():
            if seq.done or seq.pending == 0:
                continue
            (decodes if seq.pending == 1 else prefills).append(uid)
        order = sorted(decodes, key=lambda u: self._arrival.get(u, u))
        if self.decode_priority and order:
            off = self._rr % len(order)
            order = order[off:] + order[:off]
        picks: List[Tuple[int, int]] = []
        for uid in order:
            if budget < 1:
                # advance the rotation by how many decodes were actually
                # packed, so the rows cut off this step lead the next one
                self._rr += len(picks)
                return picks
            picks.append((uid, 1))
            budget -= 1
        for uid in sorted(prefills, key=lambda u: self._arrival.get(u, u)):
            if budget < 1:
                break
            take = min(state.seqs[uid].pending, prefill_chunk, budget)
            picks.append((uid, take))
            budget -= take
        return picks
