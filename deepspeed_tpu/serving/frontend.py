"""ServingFrontend — the single-threaded serving pump.

Owns the admission queue, the prefix cache, the SplitFuse policy and the
metrics, and drives :meth:`RaggedInferenceEngineTPU.step_with_budget` in a
loop. Single-threaded by design (T3-style: all host scheduling happens
while the device runs the previous step's program; a thread pool would
only add locks to a loop whose wall clock is the device's).

Request path: ``submit`` → bounded queue (reject ``queue_full`` /
``kv_exhausted`` / ``too_long``) → admission matches the prompt against
the radix prefix cache, aliases shared full pages (incref), copy-on-writes
a shared partial page, and adopts the sequence with ``seen_tokens``
already covering the cached span → SplitFuse packs prefill + decode under
the token budget → per-token stream callbacks → flush + cache insert.
"""

import time
from typing import Any, Dict, Iterator, List, Optional

from deepspeed_tpu import telemetry
from deepspeed_tpu.resilience.faults import fault_injector, record_recovery
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.queue import AdmissionError, AdmissionQueue
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.serving.scheduler import TokenBudgetPolicy


def adopt_cached(engine, cache, uid: int, prompt: List[int]) -> int:
    """Admit ``prompt`` as sequence ``uid``, reusing cached prefix pages.

    Matches the prompt against the radix cache, aliases shared FULL pages
    (incref — the ref transfers to the sequence), duplicates a shared
    partial page copy-on-write, and adopts the sequence with
    ``seen_tokens`` covering the reused span; the match is capped at
    ``len(prompt) - 1`` so at least one token prefills and produces this
    request's own logits. Evicts cache LRU pages if the arena can't fit
    the uncached tail (never the pages being handed out). Returns the
    number of prompt tokens served from the cache; raises RuntimeError
    when the arena cannot fit even after eviction (nothing is leaked).
    """
    alloc = engine.state.allocator
    bs = alloc.block_size
    aliased: List[int] = []
    cow_src = None
    matched = 0
    if cache is not None:
        m = cache.match(prompt)
        matched = min(m.matched(bs), len(prompt) - 1)
        full_keep = matched // bs
        aliased = m.full_blocks[:full_keep]
        if matched > full_keep * bs:
            # tail of the match lives mid-page → hand that page out
            # copy-on-write (a capped FULL page counts too: its new owner
            # re-prefills into it)
            cow_src = (m.full_blocks[full_keep]
                       if full_keep < len(m.full_blocks)
                       else m.partial_block)
        else:
            matched = full_keep * bs
    need = -(-len(prompt) // bs) - len(aliased)
    if need > alloc.free_blocks and cache is not None:
        cache.evict(need - alloc.free_blocks,
                    exclude_blocks=aliased + [cow_src])
    if need > alloc.free_blocks:
        raise RuntimeError(
            f"KV arena exhausted: want {need} blocks, "
            f"{alloc.free_blocks} free")
    adopted = list(aliased)
    if aliased:
        alloc.incref(aliased)
    if cow_src is not None:
        try:
            adopted.append(engine.cow_block(cow_src))
        except RuntimeError:
            if aliased:
                alloc.free(aliased)
            raise
    engine.state.adopt(uid, prompt, adopted, matched)
    return matched


class ServingFrontend:

    def __init__(self, engine, max_queue: int = 128,
                 enable_prefix_cache: bool = True,
                 cache_pages: Optional[int] = None,
                 monitor=None, mode=("argmax",),
                 token_budget: Optional[int] = None,
                 emit_every: int = 0, clock=time.monotonic,
                 watchdog=None, http_port: Optional[int] = None,
                 slo_admission: bool = False,
                 megastep_tokens: Optional[int] = None,
                 megastep_adaptive: Optional[bool] = None,
                 retry_budget: Optional[int] = None,
                 kvtier=None,
                 config=None):
        self.engine = engine
        #: optional telemetry.Watchdog armed around each engine step — a
        #: hung decode (deadlocked collective, runaway compile) dumps
        #: stacks + the flight recorder instead of silently stalling SLOs
        self.watchdog = watchdog
        self.policy = TokenBudgetPolicy()
        engine.scheduler.policy = self.policy
        self.queue = AdmissionQueue(max_queue)
        self.cache = (PrefixCache(engine.state.allocator, cache_pages)
                      if enable_prefix_cache else None)
        self.metrics = ServingMetrics()
        self.monitor = monitor
        self.mode = mode
        # vertical page tier under the radix cache (serving/kvtier.py):
        # an explicit KVTier wins; else a config kvtier.* block with
        # enabled=true builds one. Evictions then capture host-side and
        # returning conversations warm-resume instead of re-prefilling.
        self.kvtier = kvtier
        if self.kvtier is None and config is not None and \
                self.cache is not None:
            kcfg = (config.get("kvtier") if isinstance(config, dict)
                    else getattr(config, "kvtier", None))
            kget = ((kcfg or {}).get if isinstance(kcfg, dict)
                    else lambda k, d=None: getattr(kcfg, k, d))
            if kcfg is not None and bool(kget("enabled", False)):
                from deepspeed_tpu.serving.kvtier import KVTier
                self.kvtier = KVTier(
                    engine,
                    dram_bytes=int(kget("dram_bytes", 256 << 20)),
                    nvme_dir=kget("nvme_dir", None),
                    nvme_max_bytes=kget("nvme_max_bytes", None),
                    high_watermark=float(kget("high_watermark", 0.9)),
                    low_watermark=float(kget("low_watermark", 0.7)),
                    compress=str(kget("compress", "none") or "none"))
        if self.cache is not None and self.kvtier is not None:
            self.cache.tier = self.kvtier
        self.token_budget = token_budget     # None → engine max_batch_tokens
        # decode-megastep knobs: explicit kwargs win over a passed
        # DeepSpeedTPUConfig/dict (its serving.* block), which wins over
        # the defaults (megasteps off, adaptive K selection on)
        cfg_ms, cfg_ad = 0, True
        if config is not None:
            srv = (config.get("serving") if isinstance(config, dict)
                   else getattr(config, "serving", None))
            if isinstance(srv, dict):
                cfg_ms = int(srv.get("megastep_tokens", cfg_ms))
                cfg_ad = bool(srv.get("megastep_adaptive", cfg_ad))
            elif srv is not None:
                cfg_ms = int(srv.megastep_tokens)
                cfg_ad = bool(srv.megastep_adaptive)
        self.megastep_tokens = (cfg_ms if megastep_tokens is None
                                else int(megastep_tokens))
        self.megastep_adaptive = (cfg_ad if megastep_adaptive is None
                                  else bool(megastep_adaptive))
        # engine-fault retry budget (resilience.serving_retry_budget):
        # times ONE request may be requeued after an engine step died
        # under it before it finishes with reason "error"
        cfg_rb = 2
        if config is not None:
            rcfg = (config.get("resilience") if isinstance(config, dict)
                    else getattr(config, "resilience", None))
            if isinstance(rcfg, dict):
                cfg_rb = int(rcfg.get("serving_retry_budget", cfg_rb))
            elif rcfg is not None:
                cfg_rb = int(rcfg.serving_retry_budget)
        self.retry_budget = (cfg_rb if retry_budget is None
                             else int(retry_budget))
        #: pump iterations — the ``serving_step`` chaos trigger counts these
        self._pump_steps = 0
        if self.megastep_tokens < 0:
            raise ValueError("megastep_tokens must be >= 0 "
                             f"(got {self.megastep_tokens})")
        self.emit_every = emit_every
        self.clock = clock                   # injectable for deadline tests
        self._running: Dict[int, Request] = {}
        #: compile-time prefill/decode cost records (telemetry/explain) —
        #: SLO admission reads predicted step times from here; tests
        #: inject synthetic records directly
        self.cost_records: Optional[Dict[str, Any]] = None
        if slo_admission:
            try:
                self.cost_records = engine.cost_records(mode=mode)
            except Exception as e:               # noqa: BLE001
                from deepspeed_tpu.utils.logging import logger
                logger.warning(f"SLO admission disabled — cost records "
                               f"unavailable: {e}")
        self._http = None
        if http_port is not None:
            from deepspeed_tpu.telemetry.endpoint import MetricsServer
            self._http = MetricsServer(http_port)
        # metric history + SLO burn-rate engine, same seam as the
        # training engine's (_init_telemetry): a telemetry.history_file
        # key or any slo.objectives turns continuous evaluation on;
        # breaches flip this frontend's /healthz (source="slo") next to
        # the fault-domain draining flag (source="serving")
        self._history = None
        self._slo = None
        self._history_every = 10
        if config is not None:
            tcfg = (config.get("telemetry") if isinstance(config, dict)
                    else getattr(config, "telemetry", None))
            scfg = (config.get("slo") if isinstance(config, dict)
                    else getattr(config, "slo", None))
            tget = ((tcfg or {}).get if isinstance(tcfg, dict)
                    else lambda k, d=None: getattr(tcfg, k, d))
            hist_file = tget("history_file") if tcfg is not None else None
            objectives = []
            if scfg is not None:
                objectives = (scfg.get("objectives") if isinstance(
                    scfg, dict) else getattr(scfg, "objectives", None)) or []
            if hist_file or objectives:
                from deepspeed_tpu.telemetry.slo import engine_from_config
                from deepspeed_tpu.telemetry.timeseries import MetricHistory
                try:
                    self._history = MetricHistory(
                        path=hist_file,
                        max_bytes=tget("history_max_bytes", 8_388_608),
                        downsample=tget("history_downsample", 2))
                    self._history_every = max(
                        1, int(tget("history_every", 0) or 10))
                    self._slo = engine_from_config(scfg, healthz=self._http)
                    if self._slo is not None:
                        self._history.subscribe(self._slo.observe)
                except Exception as e:               # noqa: BLE001
                    from deepspeed_tpu.utils.logging import logger
                    logger.warning(
                        f"serving metric history/SLO init failed: {e}")
                    self._history = self._slo = None
            # goodput ledger: its own enabled gate; arming it also arms
            # the span tracer (the ledger attributes serving/engine_step
            # spans off the tracer ring)
            gcfg = (tget("goodput") if tcfg is not None else None)
            gget = ((gcfg or {}).get if isinstance(gcfg, dict)
                    else lambda k, d=None: getattr(gcfg, k, d))
            if gcfg is not None and gget("enabled", False):
                from deepspeed_tpu import telemetry as _telemetry
                _telemetry.tracer.configure(enabled=True)
                _telemetry.goodput_ledger.configure(
                    enabled=True,
                    window_s=gget("window_s"),
                    capture_threshold=gget("capture_threshold"),
                    capture_cooldown_s=gget("capture_cooldown_s"),
                    capture_duration_ms=gget("capture_duration_ms"),
                    capture_dir=gget("capture_dir"))

    def close(self) -> None:
        """Release frontend-owned resources (the /metrics server, the
        KV tier's I/O engine and spill files); idempotent, safe to call
        on a frontend that never opened either."""
        if self._http is not None:
            self._http.close()
            self._http = None
        if self.kvtier is not None:
            if self.cache is not None:
                self.cache.tier = None    # no capture churn at teardown
            self.kvtier.close()
            self.kvtier = None

    def terminate_inflight(self, reason: str = "drained") -> int:
        """Finish every running AND queued request with ``reason``
        (terminal state, KV released) — the scale-down path. A client
        blocked in :meth:`stream` sees its request reach ``done`` and
        the iterator end, instead of spinning into the stall-timeout
        ``RuntimeError`` because the replica under it was drained.
        Returns requests terminated."""
        now = self.clock()
        n = 0
        for req in list(self._running.values()):
            self._finish(req, reason, RequestState.FINISHED, now)
            n += 1
        for req in list(self.queue._q):
            req.state = RequestState.FINISHED
            req.finish_reason = reason
            req.finish_ts = now
            self._trace_lifecycle(req, reason, now)
            n += 1
        self.queue._q.clear()
        if n:
            self.metrics.bump("terminated_inflight", n)
        return n

    def _slo_check(self, req: Request, now: float) -> None:
        """Reject at the door when the roofline says the deadline is
        unattainable even on an idle engine: best-case latency =
        ceil(prompt/prefill_chunk) prefill steps + max_new_tokens decode
        steps at their predicted step times. Zero predictions (CPU, no
        peak table) disable the check — admission behavior is unchanged
        where there is no model."""
        recs = self.cost_records
        if recs is None or req.deadline is None:
            return
        t_pre = float(recs.get("prefill", {}).get("predicted_s", 0.0))
        t_dec = float(recs.get("decode", {}).get("predicted_s", 0.0))
        if t_pre <= 0.0 or t_dec <= 0.0:
            return
        chunk = max(1, int(self.engine.config.prefill_chunk))
        best = -(-len(req.prompt) // chunk) * t_pre + \
            req.max_new_tokens * t_dec
        if now + best > req.deadline:
            req.state = RequestState.REJECTED
            req.finish_reason = "slo_unattainable"
            self.metrics.bump("rejected_slo")
            raise AdmissionError(
                "slo_unattainable",
                f"best-case {best * 1e3:.1f} ms exceeds deadline "
                f"{(req.deadline - now) * 1e3:.1f} ms away "
                f"(roofline: prefill {t_pre * 1e3:.2f} ms/step, "
                f"decode {t_dec * 1e3:.2f} ms/step)")

    # -- admission ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 16, priority: int = 0,
               timeout: Optional[float] = None,
               deadline: Optional[float] = None,
               stream_cb=None,
               eos_token_id: Optional[int] = None,
               ctx=None) -> Request:
        """Admit a request or raise :class:`AdmissionError` with a reason
        (``queue_full`` | ``kv_exhausted`` | ``too_long`` |
        ``slo_unattainable``) — overload is surfaced at the door, not
        buffered into unbounded latency. ``slo_unattainable`` fires only
        with SLO admission on and a deadline the roofline model says
        cannot be met even best-case. ``eos_token_id`` finishes the
        request early (reason ``"eos"``) when that token is sampled.

        ``ctx`` is an upstream :class:`~deepspeed_tpu.telemetry.reqtrace.
        TraceContext` (the router passes its leg context so this
        frontend's spans join the fleet-wide trace); with request tracing
        enabled and no upstream context, the frontend is the entry point
        and mints the trace itself."""
        now = self.clock()
        prompt = [int(t) for t in prompt]
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      priority=priority, stream_cb=stream_cb,
                      deadline=(now + timeout if timeout is not None
                                else deadline),
                      eos_token_id=eos_token_id)
        total = len(prompt) + req.max_new_tokens
        if not prompt or total > self.engine.config.max_seq_len:
            req.state = RequestState.REJECTED
            req.finish_reason = "too_long"
            self.metrics.bump("rejected_too_long")
            raise AdmissionError(
                "too_long", f"{total} tokens vs max_seq_len="
                f"{self.engine.config.max_seq_len}")
        bs = self.engine.state.allocator.block_size
        need = -(-total // bs)
        avail = self.engine.state.allocator.free_blocks + \
            (self.cache.evictable_pages() if self.cache else 0)
        if need > avail:
            req.state = RequestState.REJECTED
            req.finish_reason = "kv_exhausted"
            self.metrics.bump("rejected_kv_exhausted")
            raise AdmissionError(
                "kv_exhausted", f"need {need} pages, {avail} reclaimable")
        self._slo_check(req, now)
        try:
            victim = self.queue.submit(req, now)
        except AdmissionError:
            self.metrics.bump("rejected_queue_full")
            raise
        if victim is not None:
            # the queue shed a past-deadline entry to make room; give it
            # the same terminal treatment shed_expired victims get — a
            # "deadline" finish the client can observe and a shed count
            victim.finish_ts = now
            self.metrics.bump("shed")
            self._trace_lifecycle(victim, "deadline", now)
        self.metrics.bump("admitted")
        from deepspeed_tpu.telemetry.reqtrace import reqtrace
        req.trace = ctx if ctx is not None else \
            reqtrace.mint(entry="frontend", uid=req.uid)
        if self.kvtier is not None:
            # returning conversation: start the NVMe preads NOW (the PR 6
            # issue/complete split) so the bytes climb to DRAM while the
            # request waits in admission; the complete half runs at admit
            self.kvtier.issue_prefetch(prompt, ctx=req.trace)
        return req

    def cancel(self, req: Request) -> None:
        req.cancel()

    def _try_admit_one(self, now: float) -> bool:
        eng = self.engine
        req = self.queue.pop_next(now)
        if req is None:
            return False
        if len(eng.state.seqs) >= eng.config.max_sequences:
            self.queue._q.insert(0, req)
            return False
        if self.kvtier is not None and self.cache is not None:
            # complete half of the tier prefetch: restore the prompt's
            # spilled chain into arena + radix cache BEFORE the normal
            # cached-prefix adoption aliases it — a warm resume then
            # prefills only the uncovered suffix. The tier degrades to a
            # plain re-prefill on any failure; admission never does.
            try:
                self.kvtier.adopt(req.prompt, self.cache, ctx=req.trace)
            except Exception as e:                   # noqa: BLE001
                from deepspeed_tpu.utils.logging import logger
                logger.warning(f"kvtier adopt failed (re-prefilling): {e}")
        try:
            matched = adopt_cached(eng, self.cache, req.uid, req.prompt)
        except RuntimeError:
            # arena can't fit yet (nothing leaked) — retry when running
            # sequences finish and release pages
            self.queue._q.insert(0, req)
            return False
        self.policy.note_arrival(req.uid)
        req.state = RequestState.RUNNING
        req.schedule_ts = now
        req.cached_tokens = matched
        if matched:
            self.metrics.bump("prefix_tokens_reused", matched)
        self._running[req.uid] = req
        return True

    # -- the pump -----------------------------------------------------------

    def _pick_megastep(self, now: float) -> int:
        """Tokens the next engine step may run device-resident (K).

        Megastep boundaries are the ONLY points where the pump sheds,
        cancels, admits and re-mixes prefill — so K is the knob trading
        dispatch overhead (stepwise pays 2+ host round-trips per token)
        against responsiveness:

        - any running prefill, or K ≤ 1 configured → 1 (stepwise);
        - K never exceeds the deepest remaining budget (no dead window);
        - a non-empty admission queue caps K at the SHALLOWEST remaining
          budget: the next retirement frees the slot/pages the queued
          request is waiting on, and that boundary is an admission point;
        - adaptive mode scales K with the decode backlog (shallow batch →
          short windows keep latency checks frequent) and shrinks K so no
          running/queued deadline expires mid-window (decode step time
          from the roofline ``cost_records`` when available).
        """
        k = self.megastep_tokens
        if k <= 1 or self.mode is None or not self._running:
            return 1
        dec, pre = self.policy.decode_backlog(self.engine.state)
        if pre or not dec:
            return 1                   # prefill in flight → stepwise mix
        rem = [req.max_new_tokens - len(req.tokens_out)
               for req in self._running.values()]
        k = min(k, max(rem))
        if len(self.queue):
            k = min(k, max(1, min(rem)))
        if self.megastep_adaptive:
            # deep decode-only backlogs amortize dispatch best; a shallow
            # batch keeps windows short so new arrivals wait less
            k = min(k, max(1, dec * 8))
            recs = self.cost_records
            t_dec = (float(recs.get("decode", {}).get("predicted_s", 0.0))
                     if recs else 0.0)
            if t_dec > 0.0:
                slacks = [req.deadline - now
                          for req in self._running.values()
                          if req.deadline is not None]
                slacks += [req.deadline - now
                           for req in list(self.queue._q)
                           if req.deadline is not None]
                if slacks:
                    k = min(k, max(1, int(min(slacks) / t_dec)))
        return max(1, k)

    def step(self) -> bool:
        """One pump iteration: shed → cancel → admit → engine step →
        fan tokens out. Returns True while there is (or was) work."""
        now = self.clock()
        progressed = False
        for r in self.queue.shed_expired(now):
            self.metrics.bump("shed")
            progressed = True
        for uid, req in list(self._running.items()):
            if req.cancelled:
                self._finish(req, "cancelled", RequestState.CANCELLED, now)
                progressed = True
            elif req.expired(now):
                self._finish(req, "deadline", RequestState.SHED, now)
                self.metrics.bump("shed")
                progressed = True
        while self._try_admit_one(now):
            progressed = True
        # queue-depth exemplar: the head-of-line request's trace — the
        # one that has been waiting at this depth the longest
        head = self.queue._q[0] if len(self.queue) else None
        self.metrics.queue_depth.record(
            float(len(self.queue)),
            exemplar=head.trace.trace_id
            if head is not None and head.trace else None)
        k = self._pick_megastep(now)
        row_limits = eos_map = None
        if k > 1:
            row_limits = {uid: req.max_new_tokens - len(req.tokens_out)
                          for uid, req in self._running.items()}
            eos_map = {uid: req.eos_token_id
                       for uid, req in self._running.items()
                       if req.eos_token_id is not None}
        if self.watchdog is not None:
            self.watchdog.arm("serving_step")
        t0 = time.monotonic()
        self._pump_steps += 1
        try:
            with telemetry.tracer.span("serving/engine_step",
                                       batch=len(self._running),
                                       max_steps=k):
                # chaos hook: an engine_error entry raises HERE so the
                # injected fault exercises the same except-path a real
                # engine failure takes
                # advisory=False: this hook acts on no advisory kinds, so
                # fleet-scoped entries (replica_kill/replica_slow) stay
                # pending for the router's hook instead of being consumed
                # and dropped by a replica's own pump
                fault_injector.fire("serving_step",
                                    serving_step=self._pump_steps,
                                    advisory=False)
                out = self.engine.step_with_budget(budget=self.token_budget,
                                                   mode=self.mode,
                                                   max_steps=k,
                                                   row_limits=row_limits,
                                                   eos_ids=eos_map)
        except Exception as e:                       # noqa: BLE001
            # serving failure domain: one engine fault must cost at most
            # one retry per in-flight request, never a wedged replica
            self._on_engine_fault(e, self.clock())
            self._update_degraded()
            return True
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()
        self._update_degraded()
        # goodput ledger sweep (rate-limited internally; no-op unless
        # telemetry.goodput is on) — BEFORE the out-is-None early return
        # so idle pumps keep attributing idle seconds
        telemetry.goodput_ledger.maybe_update()
        if out is None:
            return progressed or bool(self._running or len(self.queue))
        self.metrics.bump("engine_steps")
        telemetry.flight_recorder.record_step(
            int(telemetry.registry.counter("serving/engine_steps").value),
            kind="serving", dur_s=time.monotonic() - t0,
            batch=len(self._running), tokens=len(out))
        now = self.clock()
        for uid, toks in out.items():
            req = self._running.get(uid)
            if req is None:
                continue
            if not isinstance(toks, list):
                toks = [toks]
            if req.first_token_ts is None:
                req.first_token_ts = now
                self.metrics.ttft.record(
                    now - (req.enqueue_ts or now),
                    exemplar=req.trace.trace_id if req.trace else None)
                if self.cache is not None:
                    # prefill done → every prompt page holds valid KV;
                    # publish them (cache increfs what it keeps)
                    self.cache.insert(
                        req.prompt, self.engine.state.seqs[uid].blocks)
            if len(toks) > 1:
                self.metrics.bump("megasteps")
                self.metrics.megastep_k.record(float(len(toks)))
                # one marker per fused pump on the request's trace track:
                # a megastep-starved stream shows sparse pumps, not a
                # mystery gap between prefill and finish
                telemetry.reqtrace.instant(
                    "serving/request/megastep", req.trace, ts=now,
                    tid=req.uid, k=len(toks))
            finished = False
            for tok in toks:
                tok = int(tok)
                req.tokens_out.append(tok)
                self.metrics.bump("tokens_out")
                if req.stream_cb is not None:
                    req.stream_cb(tok)
                # eos outranks length: a megastep row that samples eos on
                # its last budgeted token finished because of the eos
                if req.eos_token_id is not None and \
                        tok == req.eos_token_id:
                    self._finish(req, "eos", RequestState.FINISHED, now)
                    finished = True
                    break
                if len(req.tokens_out) >= req.max_new_tokens:
                    self._finish(req, "length", RequestState.FINISHED, now)
                    finished = True
                    break
            if not finished:
                # feed the block's LAST token back — every earlier one
                # already has KV in the arena (megastep wrote it device-
                # side; the engine advanced the descriptor to match)
                try:
                    self.engine.state.extend(uid, [toks[-1]])
                except RuntimeError:
                    if self.cache is not None and self.cache.evict(1):
                        self.engine.state.extend(uid, [toks[-1]])
                    else:
                        self._finish(req, "kv_exhausted",
                                     RequestState.FINISHED, now)
        if self.emit_every and self.metrics.counters["engine_steps"] % \
                self.emit_every == 0:
            self.emit_metrics()
        # metric history + SLO evaluation on its own cadence: one
        # registry snapshot feeds the history file, the slo/* burn
        # gauges, /healthz, and the flight recorder together
        if self._history is not None and \
                self.metrics.counters["engine_steps"] % \
                self._history_every == 0:
            telemetry.registry.flush_to_monitor(
                None, self.metrics.counters["engine_steps"],
                history=self._history)
        # re-evaluate AFTER fan-out: the step that finishes the last
        # retried request must flip /healthz back to healthy — no later
        # pump is guaranteed once the replica drains idle
        self._update_degraded()
        return True

    def _finish(self, req: Request, reason: str, state: RequestState,
                now: float) -> None:
        self.engine.flush(req.uid)
        self.policy.forget(req.uid)
        self._running.pop(req.uid, None)
        req.state = state
        req.finish_reason = reason
        req.finish_ts = now
        self._trace_lifecycle(req, reason, now)
        if req.tpot is not None:
            self.metrics.tpot.record(
                req.tpot,
                exemplar=req.trace.trace_id if req.trace else None)
        if state is RequestState.FINISHED:
            self.metrics.bump("completed")
        elif state is RequestState.CANCELLED:
            self.metrics.bump("cancelled")

    def _on_engine_fault(self, err: BaseException, now: float) -> None:
        """Engine-step failure domain. The engine's device state after a
        mid-step exception is unknowable from here, so every in-flight
        request is flushed (KV pages released — pages never leak on a
        fault), its prefix-cache subtree invalidated (the pages'
        contents are suspect), and the request either requeued at the
        head of the admission queue (tokens already streamed fold into
        the prompt, so re-prefill reproduces the decode state and
        nothing is re-emitted) or — budget exhausted — finished with
        reason ``"error"`` so ``stream()`` terminates instead of
        stalling."""
        from deepspeed_tpu.utils.logging import logger
        telemetry.registry.counter(
            "resilience/serving_engine_faults",
            help="engine-step failures absorbed by the serving "
                 "failure domain").inc()
        telemetry.flight_recorder.record_event(
            "serving_engine_fault", error=f"{type(err).__name__}: {err}",
            batch=len(self._running), pump_step=self._pump_steps)
        requeued = errored = 0
        for uid, req in list(self._running.items()):
            try:
                self.engine.flush(uid)
            except Exception:                        # noqa: BLE001
                pass  # sequence may be half-torn; pages the engine still
                      # tracks are reclaimed with it
            self.policy.forget(uid)
            self._running.pop(uid, None)
            if self.cache is not None:
                self.cache.invalidate(req.prompt)
            if req.retries < self.retry_budget:
                req.retries += 1
                # KV for already-streamed tokens died with the flush;
                # folding them into the prompt re-prefills exactly that
                # state — the client's stream continues where it was
                req.prompt = req.prompt + req.tokens_out
                req.state = RequestState.QUEUED
                req.first_token_ts = None
                telemetry.reqtrace.flag(req.trace, "replay")
                telemetry.reqtrace.instant(
                    "serving/request/replay", req.trace, ts=now,
                    tid=req.uid, replay=req.retries,
                    error=type(err).__name__)
                self.queue._q.insert(0, req)
                self.metrics.bump("requeued_engine_fault")
                telemetry.registry.counter(
                    "resilience/serving_requeued",
                    help="in-flight requests requeued after an engine "
                         "fault").inc()
                requeued += 1
            else:
                self._finish(req, "error", RequestState.FINISHED, now)
                errored += 1
        logger.warning(
            "serving engine fault (%s): requeued %d, errored %d of the "
            "in-flight batch", type(err).__name__, requeued, errored)
        record_recovery("serving_requeue", requeued=requeued,
                        errored=errored,
                        error=f"{type(err).__name__}: {err}")

    def _update_degraded(self) -> None:
        """/healthz shows degraded (503) while fault-requeued requests
        are still draining — the replica is alive and recovering, and a
        balancer should route new traffic elsewhere until it is clean."""
        draining = any(r.retries for r in self._running.values()) or \
            any(r.retries for r in self.queue._q)
        telemetry.registry.gauge(
            "resilience/serving_degraded",
            help="1 while engine-fault retries drain").set(
                1.0 if draining else 0.0)
        if self._http is not None:
            self._http.set_degraded(
                draining, reason="engine-fault retries draining")

    def _trace_lifecycle(self, req: Request, reason: str,
                         now: float) -> None:
        """Emit the request's phase spans retroactively at terminal state
        (queued → prefill → decode, plus the whole-request envelope), one
        trace track per request (tid = uid). The frontend's clock and the
        tracer's are both CLOCK_MONOTONIC-derived, so the retroactive
        timestamps land on the tracer's timeline (see Tracer.complete).

        With a trace context on the request, the spans go through the
        tail-sampling :class:`~deepspeed_tpu.telemetry.reqtrace.ReqTrace`
        buffer instead (trace_id-tagged; retained or dropped whole at the
        root owner's ``finish``); without one, the legacy path records
        untagged spans straight into the tracer ring."""
        rt = telemetry.reqtrace
        ctx = req.trace
        if ctx is not None and rt.enabled:
            if req.enqueue_ts is None:
                return
            tid = req.uid
            rt.complete("serving/request", ctx, req.enqueue_ts, now,
                        tid=tid, envelope=True, reason=reason,
                        tokens_out=len(req.tokens_out),
                        cached_tokens=req.cached_tokens,
                        replay=req.retries)
            if req.schedule_ts is not None:
                rt.complete("serving/request/queued", ctx, req.enqueue_ts,
                            req.schedule_ts, tid=tid)
                if req.first_token_ts is not None:
                    rt.complete("serving/request/prefill", ctx,
                                req.schedule_ts, req.first_token_ts,
                                tid=tid)
                    rt.complete("serving/request/decode", ctx,
                                req.first_token_ts, now, tid=tid)
            if ctx.root:
                # this frontend minted the trace — the stream ends here,
                # so the tail-sampling decision is ours
                rt.finish(ctx, reason=reason, ttft_s=req.ttft,
                          tpot_s=req.tpot)
            return
        tr = telemetry.tracer
        if not tr.enabled or req.enqueue_ts is None:
            return
        tid = req.uid
        tr.complete("serving/request", req.enqueue_ts, now, tid=tid,
                    reason=reason, tokens_out=len(req.tokens_out),
                    cached_tokens=req.cached_tokens)
        if req.schedule_ts is not None:
            tr.complete("serving/request/queued", req.enqueue_ts,
                        req.schedule_ts, tid=tid)
            if req.first_token_ts is not None:
                tr.complete("serving/request/prefill", req.schedule_ts,
                            req.first_token_ts, tid=tid)
                tr.complete("serving/request/decode", req.first_token_ts,
                            now, tid=tid)

    def run_until_idle(self, max_steps: int = 100000) -> None:
        """Pump until every admitted request reached a terminal state."""
        for _ in range(max_steps):
            if not (self._running or len(self.queue)):
                return
            self.step()
        raise RuntimeError(f"serving loop did not drain in {max_steps} steps")

    def stream(self, req: Request, poll_interval: float = 0.0005,
               stall_timeout: float = 30.0) -> Iterator[int]:
        """Yield ``req``'s tokens as they are produced, driving the pump
        between yields (single-threaded streaming iterator). Megastep
        blocks drain in order, K tokens per pump.

        Empty pumps back off (``poll_interval`` doubling to 50 ms) instead
        of busy-spinning the host, and ``stall_timeout`` seconds of zero
        progress raise with the queue/engine state an operator needs —
        not a bare spin counter."""
        emitted = 0
        idle_since: Optional[float] = None
        delay = poll_interval
        while True:
            while emitted < len(req.tokens_out):
                yield req.tokens_out[emitted]
                emitted += 1
            if req.done:
                return
            if self.step():
                idle_since = None
                delay = poll_interval
                continue
            # no-op pump: nothing running, nothing admitted — wall-clock
            # (not the injectable SLO clock) bounds the wait for work to
            # appear before declaring the stream wedged
            now = time.monotonic()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > stall_timeout:
                eng = self.engine
                raise RuntimeError(
                    f"stream stalled {stall_timeout:.2f}s with no engine "
                    f"progress: request uid={req.uid} "
                    f"state={req.state.value} "
                    f"tokens_out={len(req.tokens_out)}/"
                    f"{req.max_new_tokens}; queue_depth={len(self.queue)} "
                    f"running={len(self._running)} free_blocks="
                    f"{eng.state.allocator.free_blocks} free_sequences="
                    f"{eng.config.max_sequences - len(eng.state.seqs)} — "
                    f"was the request submitted to THIS frontend?")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)

    def emit_metrics(self, step: Optional[int] = None) -> None:
        self.metrics.emit(self.monitor, self.cache,
                          step if step is not None
                          else self.metrics.counters["engine_steps"])

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-wide registry (the
        ``serving/*`` series plus anything else recorded in-process) —
        wire this to a ``/metrics`` HTTP handler."""
        return telemetry.metrics_text()

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(self.metrics.counters)
        out["ttft"] = self.metrics.ttft.summary()
        out["tpot"] = self.metrics.tpot.summary()
        out["queue_depth"] = len(self.queue)
        out["running"] = len(self._running)
        if self.cache is not None:
            out["prefix_hit_rate"] = self.cache.hit_rate
            out["prefix_pages_cached"] = self.cache.pages_cached
        if self.kvtier is not None:
            out["kvtier"] = self.kvtier.stats()
        if self._slo is not None:
            out["slo"] = self._slo.summary()
        return out
