"""ServingFrontend — the single-threaded serving pump.

Owns the admission queue, the prefix cache, the SplitFuse policy and the
metrics, and drives :meth:`RaggedInferenceEngineTPU.step_with_budget` in a
loop. Single-threaded by design (T3-style: all host scheduling happens
while the device runs the previous step's program; a thread pool would
only add locks to a loop whose wall clock is the device's).

Request path: ``submit`` → bounded queue (reject ``queue_full`` /
``kv_exhausted`` / ``too_long``) → admission matches the prompt against
the radix prefix cache, aliases shared full pages (incref), copy-on-writes
a shared partial page, and adopts the sequence with ``seen_tokens``
already covering the cached span → SplitFuse packs prefill + decode under
the token budget → per-token stream callbacks → flush + cache insert.
"""

import time
from typing import Any, Dict, Iterator, List, Optional

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.queue import AdmissionError, AdmissionQueue
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.serving.scheduler import TokenBudgetPolicy


def adopt_cached(engine, cache, uid: int, prompt: List[int]) -> int:
    """Admit ``prompt`` as sequence ``uid``, reusing cached prefix pages.

    Matches the prompt against the radix cache, aliases shared FULL pages
    (incref — the ref transfers to the sequence), duplicates a shared
    partial page copy-on-write, and adopts the sequence with
    ``seen_tokens`` covering the reused span; the match is capped at
    ``len(prompt) - 1`` so at least one token prefills and produces this
    request's own logits. Evicts cache LRU pages if the arena can't fit
    the uncached tail (never the pages being handed out). Returns the
    number of prompt tokens served from the cache; raises RuntimeError
    when the arena cannot fit even after eviction (nothing is leaked).
    """
    alloc = engine.state.allocator
    bs = alloc.block_size
    aliased: List[int] = []
    cow_src = None
    matched = 0
    if cache is not None:
        m = cache.match(prompt)
        matched = min(m.matched(bs), len(prompt) - 1)
        full_keep = matched // bs
        aliased = m.full_blocks[:full_keep]
        if matched > full_keep * bs:
            # tail of the match lives mid-page → hand that page out
            # copy-on-write (a capped FULL page counts too: its new owner
            # re-prefills into it)
            cow_src = (m.full_blocks[full_keep]
                       if full_keep < len(m.full_blocks)
                       else m.partial_block)
        else:
            matched = full_keep * bs
    need = -(-len(prompt) // bs) - len(aliased)
    if need > alloc.free_blocks and cache is not None:
        cache.evict(need - alloc.free_blocks,
                    exclude_blocks=aliased + [cow_src])
    if need > alloc.free_blocks:
        raise RuntimeError(
            f"KV arena exhausted: want {need} blocks, "
            f"{alloc.free_blocks} free")
    adopted = list(aliased)
    if aliased:
        alloc.incref(aliased)
    if cow_src is not None:
        try:
            adopted.append(engine.cow_block(cow_src))
        except RuntimeError:
            if aliased:
                alloc.free(aliased)
            raise
    engine.state.adopt(uid, prompt, adopted, matched)
    return matched


class ServingFrontend:

    def __init__(self, engine, max_queue: int = 128,
                 enable_prefix_cache: bool = True,
                 cache_pages: Optional[int] = None,
                 monitor=None, mode=("argmax",),
                 token_budget: Optional[int] = None,
                 emit_every: int = 0, clock=time.monotonic,
                 watchdog=None, http_port: Optional[int] = None,
                 slo_admission: bool = False):
        self.engine = engine
        #: optional telemetry.Watchdog armed around each engine step — a
        #: hung decode (deadlocked collective, runaway compile) dumps
        #: stacks + the flight recorder instead of silently stalling SLOs
        self.watchdog = watchdog
        self.policy = TokenBudgetPolicy()
        engine.scheduler.policy = self.policy
        self.queue = AdmissionQueue(max_queue)
        self.cache = (PrefixCache(engine.state.allocator, cache_pages)
                      if enable_prefix_cache else None)
        self.metrics = ServingMetrics()
        self.monitor = monitor
        self.mode = mode
        self.token_budget = token_budget     # None → engine max_batch_tokens
        self.emit_every = emit_every
        self.clock = clock                   # injectable for deadline tests
        self._running: Dict[int, Request] = {}
        #: compile-time prefill/decode cost records (telemetry/explain) —
        #: SLO admission reads predicted step times from here; tests
        #: inject synthetic records directly
        self.cost_records: Optional[Dict[str, Any]] = None
        if slo_admission:
            try:
                self.cost_records = engine.cost_records(mode=mode)
            except Exception as e:               # noqa: BLE001
                from deepspeed_tpu.utils.logging import logger
                logger.warning(f"SLO admission disabled — cost records "
                               f"unavailable: {e}")
        self._http = None
        if http_port is not None:
            from deepspeed_tpu.telemetry.endpoint import MetricsServer
            self._http = MetricsServer(http_port)

    def close(self) -> None:
        """Release frontend-owned resources (the /metrics server);
        idempotent, safe to call on a frontend that never opened one."""
        if self._http is not None:
            self._http.close()
            self._http = None

    def _slo_check(self, req: Request, now: float) -> None:
        """Reject at the door when the roofline says the deadline is
        unattainable even on an idle engine: best-case latency =
        ceil(prompt/prefill_chunk) prefill steps + max_new_tokens decode
        steps at their predicted step times. Zero predictions (CPU, no
        peak table) disable the check — admission behavior is unchanged
        where there is no model."""
        recs = self.cost_records
        if recs is None or req.deadline is None:
            return
        t_pre = float(recs.get("prefill", {}).get("predicted_s", 0.0))
        t_dec = float(recs.get("decode", {}).get("predicted_s", 0.0))
        if t_pre <= 0.0 or t_dec <= 0.0:
            return
        chunk = max(1, int(self.engine.config.prefill_chunk))
        best = -(-len(req.prompt) // chunk) * t_pre + \
            req.max_new_tokens * t_dec
        if now + best > req.deadline:
            req.state = RequestState.REJECTED
            req.finish_reason = "slo_unattainable"
            self.metrics.bump("rejected_slo")
            raise AdmissionError(
                "slo_unattainable",
                f"best-case {best * 1e3:.1f} ms exceeds deadline "
                f"{(req.deadline - now) * 1e3:.1f} ms away "
                f"(roofline: prefill {t_pre * 1e3:.2f} ms/step, "
                f"decode {t_dec * 1e3:.2f} ms/step)")

    # -- admission ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 16, priority: int = 0,
               timeout: Optional[float] = None,
               deadline: Optional[float] = None,
               stream_cb=None) -> Request:
        """Admit a request or raise :class:`AdmissionError` with a reason
        (``queue_full`` | ``kv_exhausted`` | ``too_long`` |
        ``slo_unattainable``) — overload is surfaced at the door, not
        buffered into unbounded latency. ``slo_unattainable`` fires only
        with SLO admission on and a deadline the roofline model says
        cannot be met even best-case."""
        now = self.clock()
        prompt = [int(t) for t in prompt]
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      priority=priority, stream_cb=stream_cb,
                      deadline=(now + timeout if timeout is not None
                                else deadline))
        total = len(prompt) + req.max_new_tokens
        if not prompt or total > self.engine.config.max_seq_len:
            req.state = RequestState.REJECTED
            req.finish_reason = "too_long"
            self.metrics.bump("rejected_too_long")
            raise AdmissionError(
                "too_long", f"{total} tokens vs max_seq_len="
                f"{self.engine.config.max_seq_len}")
        bs = self.engine.state.allocator.block_size
        need = -(-total // bs)
        avail = self.engine.state.allocator.free_blocks + \
            (self.cache.evictable_pages() if self.cache else 0)
        if need > avail:
            req.state = RequestState.REJECTED
            req.finish_reason = "kv_exhausted"
            self.metrics.bump("rejected_kv_exhausted")
            raise AdmissionError(
                "kv_exhausted", f"need {need} pages, {avail} reclaimable")
        self._slo_check(req, now)
        try:
            self.queue.submit(req, now)
        except AdmissionError:
            self.metrics.bump("rejected_queue_full")
            raise
        self.metrics.bump("admitted")
        return req

    def cancel(self, req: Request) -> None:
        req.cancel()

    def _try_admit_one(self, now: float) -> bool:
        eng = self.engine
        req = self.queue.pop_next(now)
        if req is None:
            return False
        if len(eng.state.seqs) >= eng.config.max_sequences:
            self.queue._q.insert(0, req)
            return False
        try:
            matched = adopt_cached(eng, self.cache, req.uid, req.prompt)
        except RuntimeError:
            # arena can't fit yet (nothing leaked) — retry when running
            # sequences finish and release pages
            self.queue._q.insert(0, req)
            return False
        self.policy.note_arrival(req.uid)
        req.state = RequestState.RUNNING
        req.schedule_ts = now
        req.cached_tokens = matched
        if matched:
            self.metrics.bump("prefix_tokens_reused", matched)
        self._running[req.uid] = req
        return True

    # -- the pump -----------------------------------------------------------

    def step(self) -> bool:
        """One pump iteration: shed → cancel → admit → engine step →
        fan tokens out. Returns True while there is (or was) work."""
        now = self.clock()
        progressed = False
        for r in self.queue.shed_expired(now):
            self.metrics.bump("shed")
            progressed = True
        for uid, req in list(self._running.items()):
            if req.cancelled:
                self._finish(req, "cancelled", RequestState.CANCELLED, now)
                progressed = True
            elif req.expired(now):
                self._finish(req, "deadline", RequestState.SHED, now)
                self.metrics.bump("shed")
                progressed = True
        while self._try_admit_one(now):
            progressed = True
        self.metrics.queue_depth.record(float(len(self.queue)))
        if self.watchdog is not None:
            self.watchdog.arm("serving_step")
        t0 = time.monotonic()
        try:
            with telemetry.tracer.span("serving/engine_step",
                                       batch=len(self._running)):
                out = self.engine.step_with_budget(budget=self.token_budget,
                                                   mode=self.mode)
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()
        if out is None:
            return progressed or bool(self._running or len(self.queue))
        self.metrics.bump("engine_steps")
        telemetry.flight_recorder.record_step(
            int(telemetry.registry.counter("serving/engine_steps").value),
            kind="serving", dur_s=time.monotonic() - t0,
            batch=len(self._running), tokens=len(out))
        now = self.clock()
        for uid, tok in out.items():
            req = self._running.get(uid)
            if req is None:
                continue
            if req.first_token_ts is None:
                req.first_token_ts = now
                self.metrics.ttft.record(now - (req.enqueue_ts or now))
                if self.cache is not None:
                    # prefill done → every prompt page holds valid KV;
                    # publish them (cache increfs what it keeps)
                    self.cache.insert(
                        req.prompt, self.engine.state.seqs[uid].blocks)
            tok = int(tok)
            req.tokens_out.append(tok)
            self.metrics.bump("tokens_out")
            if req.stream_cb is not None:
                req.stream_cb(tok)
            if len(req.tokens_out) >= req.max_new_tokens:
                self._finish(req, "length", RequestState.FINISHED, now)
            else:
                try:
                    self.engine.state.extend(uid, [tok])
                except RuntimeError:
                    if self.cache is not None and self.cache.evict(1):
                        self.engine.state.extend(uid, [tok])
                    else:
                        self._finish(req, "kv_exhausted",
                                     RequestState.FINISHED, now)
        if self.emit_every and self.metrics.counters["engine_steps"] % \
                self.emit_every == 0:
            self.emit_metrics()
        return True

    def _finish(self, req: Request, reason: str, state: RequestState,
                now: float) -> None:
        self.engine.flush(req.uid)
        self.policy.forget(req.uid)
        self._running.pop(req.uid, None)
        req.state = state
        req.finish_reason = reason
        req.finish_ts = now
        self._trace_lifecycle(req, reason, now)
        if req.tpot is not None:
            self.metrics.tpot.record(req.tpot)
        if state is RequestState.FINISHED:
            self.metrics.bump("completed")
        elif state is RequestState.CANCELLED:
            self.metrics.bump("cancelled")

    def _trace_lifecycle(self, req: Request, reason: str,
                         now: float) -> None:
        """Emit the request's phase spans retroactively at terminal state
        (queued → prefill → decode, plus the whole-request envelope), one
        trace track per request (tid = uid). The frontend's clock and the
        tracer's are both CLOCK_MONOTONIC-derived, so the retroactive
        timestamps land on the tracer's timeline (see Tracer.complete)."""
        tr = telemetry.tracer
        if not tr.enabled or req.enqueue_ts is None:
            return
        tid = req.uid
        tr.complete("serving/request", req.enqueue_ts, now, tid=tid,
                    reason=reason, tokens_out=len(req.tokens_out),
                    cached_tokens=req.cached_tokens)
        if req.schedule_ts is not None:
            tr.complete("serving/request/queued", req.enqueue_ts,
                        req.schedule_ts, tid=tid)
            if req.first_token_ts is not None:
                tr.complete("serving/request/prefill", req.schedule_ts,
                            req.first_token_ts, tid=tid)
                tr.complete("serving/request/decode", req.first_token_ts,
                            now, tid=tid)

    def run_until_idle(self, max_steps: int = 100000) -> None:
        """Pump until every admitted request reached a terminal state."""
        for _ in range(max_steps):
            if not (self._running or len(self.queue)):
                return
            self.step()
        raise RuntimeError(f"serving loop did not drain in {max_steps} steps")

    def stream(self, req: Request) -> Iterator[int]:
        """Yield ``req``'s tokens as they are produced, driving the pump
        between yields (single-threaded streaming iterator)."""
        emitted = 0
        stall = 0
        while True:
            while emitted < len(req.tokens_out):
                yield req.tokens_out[emitted]
                emitted += 1
            if req.done:
                return
            stall = stall + 1 if not self.step() else 0
            if stall > 10000:
                raise RuntimeError(
                    f"stream stalled: request {req.uid} in {req.state}")

    def emit_metrics(self, step: Optional[int] = None) -> None:
        self.metrics.emit(self.monitor, self.cache,
                          step if step is not None
                          else self.metrics.counters["engine_steps"])

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-wide registry (the
        ``serving/*`` series plus anything else recorded in-process) —
        wire this to a ``/metrics`` HTTP handler."""
        return telemetry.metrics_text()

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(self.metrics.counters)
        out["ttft"] = self.metrics.ttft.summary()
        out["tpot"] = self.metrics.tpot.summary()
        out["queue_depth"] = len(self.queue)
        out["running"] = len(self._running)
        if self.cache is not None:
            out["prefix_hit_rate"] = self.cache.hit_rate
            out["prefix_pages_cached"] = self.cache.pages_cached
        return out
