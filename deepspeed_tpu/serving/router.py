"""Fault-tolerant multi-replica serving router (``dstpu-router``).

Scales the single-replica :class:`~deepspeed_tpu.serving.frontend.
ServingFrontend` to a fleet: the router spreads streams over N replicas
with prefix-affinity routing (shared-prefix traffic lands where the
radix cache is warm, via rendezvous hashing over the prompt's leading
tokens, spilling to the least-loaded replica under imbalance), tracks
per-replica health with a circuit breaker (closed → open on consecutive
in-band failures or sustained ``/healthz`` 503, half-open probes with
capped exponential backoff before readmission), and defends the client
stream against every replica failure mode:

- **failover**: on replica death or breaker-open mid-stream, the
  request moves to a healthy replica with its already-streamed tokens
  folded into the prompt (the PR 8 requeue fold, one tier up) — the new
  replica re-prefills exactly the decode state the client saw, so the
  delivered token sequence is gapless and duplicate-free;
- **hedged dispatch**: a request queued too long (no first token after
  a p95-derived delay) races a second replica; the first token decides
  the winner and the loser is cancelled;
- **graceful draining**: ``drain(name)`` stops new admissions, lets
  in-flight decodes finish on the replica (optionally bounded by a
  deadline that fails the stragglers over), then removes it without
  dropping a stream — streams it had to cut finish with the honest
  reason ``"drained"``, never a stall error;
- **disaggregated prefill/decode pools**: replicas tagged
  ``pool="prefill"`` / ``pool="decode"`` split the fleet by roofline
  regime (compute-bound ragged prefill vs bandwidth-bound decode). A
  request prefills on the prefill pool for exactly one token, then the
  router ships the prefill replica's radix-cached KV pages to a decode
  replica (:mod:`deepspeed_tpu.serving.handoff`) and the decode leg
  aliases them; a torn or stalled bundle (``handoff_torn`` /
  ``handoff_stall`` faults at the ``handoff`` site) falls back to
  decode-side re-prefill with zero token loss.

T3's principle — host scheduling off the device critical path — holds
at fleet scope: each replica pumps its own frontend on its own thread
(its device never waits on the router), while placement, health, retry
and hedging decisions all happen in :meth:`Router.poll` on the host.

The whole tier is chaos-drillable: ``dstpu-chaos`` plans with
``replica_kill`` / ``replica_slow`` entries at the ``router`` site
kill or degrade a replica mid-drill, and the router publishes
``router/*`` metrics (per-replica state, failovers, hedges won/lost,
breaker transitions) that ``dstpu-top`` and ``dstpu-doctor`` render,
closing the faults==recoveries ledger at fleet scope. See
docs/serving.md "Router, failover & draining".
"""

import enum
import hashlib
import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from deepspeed_tpu import telemetry
from deepspeed_tpu.resilience.faults import fault_injector, record_recovery
from deepspeed_tpu.serving.queue import AdmissionError
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.telemetry.registry import Histogram
from deepspeed_tpu.telemetry.registry import registry as _registry
from deepspeed_tpu.utils.logging import logger

#: numeric replica-state encoding for the ``router/replica/{name}/state``
#: gauges (dstpu-top maps them back to names)
STATE_CODES = {"healthy": 0.0, "half-open": 1.0, "open": 2.0,
               "draining": 3.0, "dead": 4.0}


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica health automaton fed by in-band observations
    (dispatch errors, stream stalls) and out-of-band ``/healthz`` polls.

    CLOSED → OPEN after ``failure_threshold`` consecutive failures;
    OPEN → HALF_OPEN after a backoff that doubles per consecutive open
    period (capped at ``backoff_max_s``) — HALF_OPEN admits exactly one
    probe; a probe success closes the breaker (backoff resets), a probe
    failure re-opens it. The clock is injectable so tests (and the
    router, which shares one monotonic clock across breakers) never
    depend on the wall clock.
    """

    def __init__(self, failure_threshold: int = 3, backoff_s: float = 1.0,
                 backoff_max_s: float = 30.0, clock=time.monotonic,
                 on_transition=None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self._on_transition = on_transition
        self.state = BreakerState.CLOSED
        self.failures = 0            # consecutive, reset on success
        self.last_reason = ""
        self._opened_at: Optional[float] = None
        self._backoff = self.backoff_s

    def _to(self, new: BreakerState, reason: str = "") -> None:
        if new is self.state:
            return
        old, self.state = self.state, new
        self.last_reason = reason
        if self._on_transition is not None:
            self._on_transition(old, new, reason)

    def record_failure(self, reason: str = "") -> bool:
        """One observed failure; returns True when this observation
        opened (or re-opened) the breaker."""
        self.failures += 1
        if self.state is BreakerState.HALF_OPEN:
            # failed probe: back off harder before the next one
            self._backoff = min(self._backoff * 2.0, self.backoff_max_s)
            self._opened_at = self._clock()
            self._to(BreakerState.OPEN, reason or "probe failed")
            return True
        if self.state is BreakerState.CLOSED and \
                self.failures >= self.failure_threshold:
            self._opened_at = self._clock()
            self._backoff = self.backoff_s
            self._to(BreakerState.OPEN, reason)
            return True
        return False

    def force_open(self, reason: str = "") -> None:
        """Immediate open (replica died — no vote needed)."""
        self.failures = max(self.failures, self.failure_threshold)
        if self.state is not BreakerState.OPEN:
            self._opened_at = self._clock()
            if self.state is BreakerState.HALF_OPEN:
                self._backoff = min(self._backoff * 2.0, self.backoff_max_s)
            else:
                self._backoff = self.backoff_s
            self._to(BreakerState.OPEN, reason)

    def record_success(self) -> None:
        self.failures = 0
        if self.state is BreakerState.HALF_OPEN:
            self._backoff = self.backoff_s
            self._to(BreakerState.CLOSED, "probe succeeded")

    def allow_probe(self) -> bool:
        """OPEN → HALF_OPEN once the backoff elapsed; returns True
        exactly once per backoff period (the single probe admission)."""
        if self.state is not BreakerState.OPEN:
            return False
        if self._opened_at is None or \
                self._clock() - self._opened_at < self._backoff:
            return False
        self._to(BreakerState.HALF_OPEN,
                 f"probing after {self._backoff:.2f}s backoff")
        return True


class LocalReplica:
    """One in-process replica: a :class:`ServingFrontend` pumped on its
    own daemon thread (the per-replica analogue of a replica process —
    its device loop never blocks on the router, and a dead replica is a
    dead thread). All frontend access goes through ``lock``: the pump
    thread holds it across ``step()``, the router across ``submit``.

    ``kill()`` has dead-process semantics: the pump stops and the
    frontend is NOT flushed or drained — whatever tokens it produced but
    had not delivered are lost, exactly like a SIGKILLed replica. The
    router's failover replay is what makes the client stream gapless
    anyway.

    ``pool`` assigns the replica to the disaggregated tier: ``"prefill"``
    replicas run prompt prefills (one token out, pages handed off),
    ``"decode"`` replicas run the decode legs, ``"any"`` (the default)
    serves both — a pool of all-``"any"`` replicas is the classic
    homogeneous fleet and nothing about routing changes.
    """

    def __init__(self, name: str, frontend, idle_sleep_s: float = 0.002,
                 pool: str = "any"):
        if pool not in ("any", "prefill", "decode"):
            raise ValueError(f"bad replica pool {pool!r} "
                             f"(want any/prefill/decode)")
        self.name = name
        self.pool = pool
        self.frontend = frontend
        self.lock = threading.RLock()
        self.idle_sleep_s = idle_sleep_s
        #: injected degradation (``replica_slow``): every pump pays this
        self.slow_s = 0.0
        self.killed = False
        self.error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump_loop, daemon=True,
            name=f"dstpu-replica-{name}")
        self._started = False

    def start(self) -> "LocalReplica":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            if self.slow_s > 0.0:
                time.sleep(self.slow_s)
            try:
                with self.lock:
                    progressed = self.frontend.step()
            except BaseException as e:               # noqa: BLE001
                # the frontend's own failure domain absorbs engine
                # faults; anything that escapes is replica-fatal
                self.error = e
                return
            if not progressed:
                time.sleep(self.idle_sleep_s)

    @property
    def alive(self) -> bool:
        return (self._started and not self.killed and self.error is None
                and self._thread.is_alive())

    def submit(self, prompt: List[int], **kw) -> Request:
        if not self.alive:
            raise AdmissionError("replica_dead",
                                 f"replica {self.name} is not alive")
        with self.lock:
            return self.frontend.submit(prompt, **kw)

    def cancel(self, req: Request) -> None:
        req.cancel()                     # flag only — pump honors it

    def load(self) -> int:
        fe = self.frontend
        return len(fe._running) + len(fe.queue)

    def http_target(self) -> Optional[str]:
        http = getattr(self.frontend, "_http", None)
        return None if http is None else f"127.0.0.1:{http.port}"

    def kill(self) -> None:
        self.killed = True
        self._stop.set()

    def close(self) -> None:
        """Graceful teardown (drain-remove or router shutdown): stop the
        pump, terminate any still-attached streams with reason
        ``"drained"`` (their KV released — a client blocked in
        ``frontend.stream()`` sees the request finish instead of a
        stall-timeout RuntimeError), release the cached prefix pages,
        close the endpoint."""
        self._stop.set()
        if self._started and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        fe = self.frontend
        try:
            if hasattr(fe, "terminate_inflight"):
                fe.terminate_inflight("drained")
            else:
                for uid in list(fe._running):
                    try:
                        fe.engine.flush(uid)
                    except Exception:                # noqa: BLE001
                        pass
                fe._running.clear()
            if fe.cache is not None and fe.cache.pages_cached:
                fe.cache.evict(fe.cache.pages_cached)
            fe.close()
        except Exception:                            # noqa: BLE001
            pass


_rr_uid = itertools.count()


@dataclass
class _Assignment:
    replica: LocalReplica
    inner: Request
    dispatch_ts: float
    drained: int = 0                 # inner tokens already delivered
    #: which disaggregated leg this is: "mono" (homogeneous fleet),
    #: "prefill" (one-token leg whose pages hand off) or "decode"
    role: str = "mono"
    #: this leg's TraceContext (child of the request's root) — the
    #: identity the replica's frontend stamps into its spans; None when
    #: request tracing is disabled
    ctx: Optional[object] = None


@dataclass
class RouterRequest:
    """Client-visible request: ``tokens_out`` is exactly what the client
    has been streamed, across any number of failovers/hedges underneath.
    """
    prompt: List[int]
    max_new_tokens: int = 16
    priority: int = 0
    deadline: Optional[float] = None
    eos_token_id: Optional[int] = None

    uid: int = field(default_factory=lambda: next(_rr_uid))
    tokens_out: List[int] = field(default_factory=list)
    state: RequestState = RequestState.QUEUED
    finish_reason: Optional[str] = None
    #: times this request was re-dispatched after a replica failure
    failovers: int = 0
    hedged: bool = False
    #: disaggregated lifecycle: "mono" on a homogeneous fleet, else
    #: "prefill" until the prefill leg finished and its pages handed
    #: off, then "decode"
    phase: str = "mono"
    #: prompt tokens the decode replica served from handed-off pages
    handoff_tokens: int = 0
    #: distributed-trace root context (:class:`~deepspeed_tpu.telemetry.
    #: reqtrace.TraceContext`), minted at :meth:`Router.submit`; every
    #: dispatch leg forks a child from it. The router owns the tail
    #: decision (``reqtrace.finish``) for router-entered requests.
    trace: Optional[object] = field(default=None, repr=False)

    submit_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    last_progress_ts: Optional[float] = None

    primary: Optional[_Assignment] = field(default=None, repr=False)
    hedge: Optional[_Assignment] = field(default=None, repr=False)
    #: set once the first token decides the primary-vs-hedge race
    winner: Optional[_Assignment] = field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.SHED, RequestState.REJECTED)


class Router:
    """Health-driven request router over N serving replicas.

    Single coordinator thread by design (the caller drives
    :meth:`poll`, usually via :meth:`stream` / :meth:`run_until_idle`);
    replicas pump themselves. Construction accepts ``LocalReplica``
    objects or ``(name, frontend)`` pairs; kwargs override the
    ``router.*`` config block, which overrides the defaults.
    """

    def __init__(self, replicas: Sequence, *,
                 affinity_tokens: Optional[int] = None,
                 hedge: Optional[bool] = None,
                 hedge_delay_s: Optional[float] = None,
                 retry_budget: Optional[int] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_backoff_s: Optional[float] = None,
                 breaker_backoff_max_s: Optional[float] = None,
                 stall_timeout_s: Optional[float] = None,
                 spill_factor: Optional[float] = None,
                 chaos_slow_s: Optional[float] = None,
                 health_every: Optional[int] = None,
                 http_port: Optional[int] = None,
                 clock=time.monotonic, config=None):
        rcfg = None
        if config is not None:
            rcfg = (config.get("router") if isinstance(config, dict)
                    else getattr(config, "router", None))
        rget = ((rcfg or {}).get if isinstance(rcfg, dict)
                else lambda k, d=None: getattr(rcfg, k, d))

        def knob(val, key, default):
            if val is not None:
                return val
            if rcfg is not None:
                got = rget(key, None)
                if got is not None:
                    return got
            return default

        self.affinity_tokens = int(knob(affinity_tokens,
                                        "affinity_tokens", 64))
        self.hedge = bool(knob(hedge, "hedge", True))
        self.hedge_delay_s = knob(hedge_delay_s, "hedge_delay_s", None)
        self.retry_budget = int(knob(retry_budget, "retry_budget", 2))
        self.stall_timeout_s = float(knob(stall_timeout_s,
                                          "stall_timeout_s", 30.0))
        self.spill_factor = float(knob(spill_factor, "spill_factor", 2.0))
        self.chaos_slow_s = float(knob(chaos_slow_s, "chaos_slow_s", 0.25))
        self.health_every = int(knob(health_every, "health_every", 50))
        self.clock = clock
        self.replicas: List[LocalReplica] = []
        for i, r in enumerate(replicas):
            if not isinstance(r, LocalReplica):
                name, fe = (r if isinstance(r, tuple) else (f"r{i}", r))
                r = LocalReplica(name, fe)
            self.replicas.append(r.start())
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        self.breakers: Dict[str, CircuitBreaker] = {}
        bf = int(knob(breaker_failures, "breaker_failures", 3))
        bb = float(knob(breaker_backoff_s, "breaker_backoff_s", 1.0))
        bm = float(knob(breaker_backoff_max_s, "breaker_backoff_max_s",
                        30.0))
        #: breaker knobs, kept so autoscaler-spawned replicas
        #: (:meth:`add_replica`) get identical health automata
        self._breaker_kw = dict(failure_threshold=bf, backoff_s=bb,
                                backoff_max_s=bm)
        for r in self.replicas:
            self.breakers[r.name] = CircuitBreaker(
                clock=self.clock, **self._breaker_kw,
                on_transition=self._breaker_transition(r.name))
        self._reqs: Dict[int, RouterRequest] = {}
        self._draining: set = set()
        #: forced-drain deadlines: replica → clock time after which its
        #: remaining streams are failed over (terminal reason "drained"
        #: when they cannot be replayed) and the replica is removed
        self._drain_deadline: Dict[str, float] = {}
        self._polls = 0
        #: chaos-kill recovery ledger: replica → {"t0", "uids"} — closed
        #: (record_recovery) when every failed-over stream completed
        self._pending_recovery: Dict[str, Dict[str, Any]] = {}
        #: chaos-slow ledger: replica → recovery not yet recorded
        self._pending_slow: Dict[str, float] = {}
        #: handoff-fault ledger: req uid → fallback re-prefill in flight;
        #: the recovery is recorded when that stream completes
        self._pending_handoff: Dict[int, Dict[str, Any]] = {}
        #: per-replica tokens delivered to clients (bench attribution)
        self.replica_tokens: Dict[str, int] = {
            r.name: 0 for r in self.replicas}
        self.ttft = Histogram()
        _registry.register("router/ttft_seconds", self.ttft,
                           help="router-observed time to first token (s)",
                           replace=True)
        self._http = None
        if http_port is not None:
            from deepspeed_tpu.telemetry.endpoint import MetricsServer
            self._http = MetricsServer(http_port)
        self._publish_states()

    # -- plumbing -----------------------------------------------------------

    def _breaker_transition(self, name: str):
        def cb(old: BreakerState, new: BreakerState, reason: str) -> None:
            _registry.counter(
                "router/breaker_transitions",
                help="circuit-breaker state changes across replicas").inc()
            telemetry.flight_recorder.record_event(
                "router_breaker", replica=name, from_state=old.value,
                to_state=new.value, reason=reason)
            telemetry.tracer.instant("router/breaker", replica=name,
                                     to_state=new.value)
            logger.warning("router: replica %s breaker %s -> %s (%s)",
                           name, old.value, new.value, reason)
        return cb

    def replica_state(self, r: LocalReplica) -> str:
        if not r.alive:
            return "dead"
        if r.name in self._draining:
            return "draining"
        st = self.breakers[r.name].state
        if st is BreakerState.OPEN:
            return "open"
        if st is BreakerState.HALF_OPEN:
            return "half-open"
        return "healthy"

    def _publish_states(self) -> None:
        _registry.gauge("router/replicas",
                        help="replicas currently in the pool").set(
            float(len(self.replicas)))
        for r in self.replicas:
            _registry.gauge(
                f"router/replica/{r.name}/state",
                help="0 healthy, 1 half-open, 2 open, 3 draining, 4 dead"
            ).set(STATE_CODES[self.replica_state(r)])

    def _update_degraded(self) -> None:
        """Router /healthz is degraded (503) while failover replays are
        still draining — the tier is alive and recovering, but an
        upstream balancer should prefer another router cell."""
        draining = bool(self._pending_recovery) or any(
            req.failovers and not req.done for req in self._reqs.values())
        _registry.gauge(
            "router/degraded",
            help="1 while failover replays drain").set(
            1.0 if draining else 0.0)
        if self._http is not None:
            self._http.set_degraded(draining, source="router",
                                    reason="failover replays draining")

    # -- pools --------------------------------------------------------------

    @property
    def disaggregated(self) -> bool:
        """True when the fleet has BOTH a prefill and a decode pool —
        requests then run as a prefill leg + KV-page handoff + decode
        leg. With either pool absent the router behaves exactly as the
        homogeneous PR-10 fleet."""
        pools = {r.pool for r in self.replicas if r.alive}
        return "prefill" in pools and "decode" in pools

    def pool_members(self, pool: str,
                     live_only: bool = True) -> List[LocalReplica]:
        """Replicas serving ``pool`` (``"any"`` replicas serve both)."""
        return [r for r in self.replicas
                if r.pool in ("any", pool)
                and (not live_only or
                     (r.alive and r.name not in self._draining))]

    def add_replica(self, replica) -> LocalReplica:
        """Grow the fleet at runtime (the autoscaler's scale-up
        effector). Accepts a :class:`LocalReplica` or a ``(name,
        frontend)`` pair; the new replica gets a breaker with the same
        knobs as its peers and starts taking traffic on the next
        placement decision."""
        if not isinstance(replica, LocalReplica):
            name, fe = replica
            replica = LocalReplica(name, fe)
        if replica.name in {r.name for r in self.replicas}:
            raise ValueError(f"replica name {replica.name!r} already "
                             f"in the pool")
        self.replicas.append(replica.start())
        self.breakers[replica.name] = CircuitBreaker(
            clock=self.clock, **self._breaker_kw,
            on_transition=self._breaker_transition(replica.name))
        self.replica_tokens.setdefault(replica.name, 0)
        telemetry.flight_recorder.record_event(
            "router_replica_added", replica=replica.name,
            pool=replica.pool)
        self._publish_states()
        return replica

    # -- placement ----------------------------------------------------------

    def _affinity_key(self, prompt: List[int]) -> bytes:
        head = tuple(prompt[:max(1, self.affinity_tokens)])
        return repr(head).encode()

    def _score(self, key: bytes, name: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key + b"|" + name.encode()).digest()[:8], "big")

    def _choose(self, prompt: List[int],
                exclude: Tuple[str, ...] = (),
                pool: Optional[str] = None) -> LocalReplica:
        """Prefix-affinity placement: rendezvous (highest-random-weight)
        hash of the prompt's leading tokens over the healthy replicas —
        shared-prefix traffic keeps landing on the same replica, and a
        replica's death remaps only its own keys. Spills to the
        least-loaded replica when the affinity target is more than
        ``spill_factor``x busier (a warm cache never justifies a hot
        queue). With no CLOSED-breaker replica available, an OPEN
        replica whose backoff elapsed is admitted as the half-open
        probe; otherwise admission fails loudly. ``pool`` restricts
        candidates to one disaggregated pool (``"any"`` replicas always
        qualify)."""
        cands = (self.replicas if pool is None
                 else [r for r in self.replicas
                       if r.pool in ("any", pool)])
        healthy = [r for r in cands
                   if r.alive and r.name not in self._draining
                   and r.name not in exclude
                   and self.breakers[r.name].state is BreakerState.CLOSED]
        if not healthy:
            for r in cands:
                if (r.alive and r.name not in self._draining
                        and r.name not in exclude
                        and self.breakers[r.name].allow_probe()):
                    return r
            raise AdmissionError(
                "no_healthy_replica",
                (f"pool {pool!r}: " if pool is not None else "") +
                f"{len(cands)} replicas, none admitting "
                f"(states: " + ", ".join(
                    f"{r.name}={self.replica_state(r)}"
                    for r in cands) + ")")
        key = self._affinity_key(prompt)
        chosen = max(healthy, key=lambda r: self._score(key, r.name))
        loads = {r.name: r.load() for r in healthy}
        least = min(healthy, key=lambda r: loads[r.name])
        if loads[chosen.name] > self.spill_factor * (loads[least.name] + 1):
            _registry.counter(
                "router/affinity_spills",
                help="affinity choices overridden by load imbalance").inc()
            return least
        return chosen

    # -- admission ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 16, priority: int = 0,
               deadline: Optional[float] = None,
               timeout: Optional[float] = None,
               eos_token_id: Optional[int] = None) -> RouterRequest:
        """Admit one stream; raises :class:`AdmissionError` (reason
        ``no_healthy_replica`` or the chosen replica's own reason) when
        the fleet cannot take it."""
        now = self.clock()
        req = RouterRequest(
            prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens), priority=priority,
            deadline=(now + timeout if timeout is not None else deadline),
            eos_token_id=eos_token_id)
        req.submit_ts = now
        req.phase = "prefill" if self.disaggregated else "mono"
        req.trace = telemetry.reqtrace.mint(entry="router", uid=req.uid)
        try:
            self._dispatch(req, exclude=())
        except AdmissionError as e:
            # rejected before any leg ran — the trace still records WHY
            # (breaker states are in the per-attempt router/rejected
            # instants) and finishes honestly instead of leaking
            rt = telemetry.reqtrace
            rt.flag(req.trace, "rejected")
            rt.instant("router/rejected", req.trace, tid=req.uid,
                       reason=e.reason, terminal=1)
            rt.finish(req.trace, reason=e.reason)
            raise
        self._reqs[req.uid] = req
        _registry.counter("router/requests",
                          help="streams admitted by the router").inc()
        return req

    def _dispatch(self, req: RouterRequest,
                  exclude: Tuple[str, ...] = (),
                  hedge: bool = False,
                  prefer: Optional[LocalReplica] = None) -> _Assignment:
        """(Re-)dispatch ``req`` to a replica. The already-streamed
        tokens fold into the prompt so the replica re-prefills exactly
        the client-visible decode state — gapless, duplicate-free.

        On a disaggregated fleet the request's ``phase`` picks the pool
        and the leg: a prefill leg runs for exactly ONE token (the first
        token is the proof the prompt's KV is complete), then
        :meth:`_promote_to_decode` hands the pages off; a decode leg
        runs the remaining budget. ``prefer`` pins the first attempt to
        one replica (the handoff path adopts pages into a replica
        BEFORE dispatching to it, so placement must not move)."""
        remaining = req.max_new_tokens - len(req.tokens_out)
        folded = req.prompt + req.tokens_out
        role, pool, inner_max = "mono", None, remaining
        if req.phase == "prefill":
            role, pool, inner_max = "prefill", "prefill", 1
        elif req.phase == "decode":
            role, pool = "decode", "decode"
        last_err: Optional[Exception] = None
        tried: Tuple[str, ...] = exclude
        for _ in range(len(self.replicas) + 1):
            if prefer is not None:
                replica, prefer = prefer, None
            else:
                replica = self._choose(folded, exclude=tried, pool=pool)
            kw: Dict[str, Any] = dict(
                max_new_tokens=inner_max, priority=req.priority,
                deadline=req.deadline, eos_token_id=req.eos_token_id)
            if req.trace is not None:
                # fork this leg's trace context: the replica's frontend
                # stamps its spans with it, so the fleet-wide trace has
                # one child span-tree per dispatch attempt. Omitted
                # entirely when tracing is off (plain frontends and test
                # stubs need not know the kwarg exists).
                leg: Dict[str, Any] = {"replica": replica.name,
                                       "role": role}
                if hedge:
                    leg["hedge"] = 1
                if req.failovers:
                    leg["replay"] = req.failovers
                kw["ctx"] = req.trace.child(**leg)
            try:
                inner = replica.submit(folded, **kw)
            except AdmissionError as e:
                last_err = e
                tried = tried + (replica.name,)
                telemetry.reqtrace.instant(
                    "router/rejected", req.trace, tid=req.uid,
                    replica=replica.name, reason=e.reason)
                self.breakers[replica.name].record_failure(
                    f"submit rejected: {e.reason}")
                continue
            assign = _Assignment(replica=replica, inner=inner,
                                 dispatch_ts=self.clock(), role=role,
                                 ctx=kw.get("ctx"))
            if hedge:
                req.hedge = assign
            else:
                req.primary = assign
            req.state = RequestState.RUNNING
            return assign
        req.state = RequestState.REJECTED
        req.finish_reason = "no_healthy_replica"
        raise last_err if last_err is not None else AdmissionError(
            "no_healthy_replica", "no replica accepted the request")

    # -- chaos --------------------------------------------------------------

    def _chaos_victim(self) -> Optional[LocalReplica]:
        named = os.environ.get("DSTPU_CHAOS_REPLICA")
        if named:
            # a NAMED victim is killable even mid-drain — the
            # scale-down chaos drill targets exactly that window
            for r in self.replicas:
                if r.name == named and r.alive:
                    return r
        cands = [r for r in self.replicas
                 if r.alive and r.name not in self._draining]
        if not cands:
            return None
        # deterministic: the busiest replica (ties → pool order) — the
        # worst case for stream integrity is the drill the ledger wants
        return max(cands, key=lambda r: (r.load(), ))

    def _apply_chaos(self, kind: str) -> None:
        victim = self._chaos_victim()
        if victim is None:
            logger.warning("router CHAOS: %s with no live replica to "
                           "target — ignored", kind)
            return
        telemetry.flight_recorder.record_event(
            f"router_{kind}", replica=victim.name, poll=self._polls)
        telemetry.tracer.instant(f"router/{kind}", replica=victim.name)
        if kind == "replica_kill":
            logger.warning("router CHAOS: killing replica %s "
                           "(%d streams in flight)", victim.name,
                           self._assigned_count(victim))
            victim.kill()
            self._pending_recovery.setdefault(
                victim.name, {"t0": self.clock(), "uids": set()})
        elif kind == "replica_slow":
            logger.warning("router CHAOS: degrading replica %s "
                           "(+%.0f ms per pump)", victim.name,
                           self.chaos_slow_s * 1e3)
            victim.slow_s = self.chaos_slow_s
            self._pending_slow[victim.name] = self.clock()

    def _assigned_count(self, replica: LocalReplica) -> int:
        n = 0
        for req in self._reqs.values():
            for a in (req.primary, req.hedge):
                if a is not None and a.replica is replica and not req.done:
                    n += 1
        return n

    # -- failure handling ---------------------------------------------------

    def _fail_assignment(self, req: RouterRequest, assign: _Assignment,
                         reason: str) -> None:
        """The replica under ``assign`` failed this request. Hedge legs
        are simply dropped (the primary still runs); a failed primary
        promotes a live hedge, else re-dispatches under the retry
        budget."""
        from_name = assign.replica.name
        if req.hedge is assign:
            req.hedge = None
            if req.winner is assign:
                req.winner = None
            return
        req.primary = None
        if req.winner is assign:
            req.winner = None
        if from_name in self._pending_recovery and not req.done:
            self._pending_recovery[from_name]["uids"].add(req.uid)
        if req.hedge is not None and req.hedge.replica.alive and \
                self.breakers[req.hedge.replica.name].state \
                is BreakerState.CLOSED:
            # the race already has a healthy leg — promote it
            req.primary, req.hedge = req.hedge, None
            _registry.counter(
                "router/hedges_won",
                help="hedge legs that delivered the stream").inc()
            if req.primary.ctx is not None:
                req.primary.ctx.baggage["winner"] = 1
                telemetry.reqtrace.instant(
                    "router/hedge_won", req.primary.ctx, tid=req.uid,
                    replica=req.primary.replica.name, winner=1)
            telemetry.reqtrace.flag(req.trace, "failover")
            return
        req.failovers += 1
        # a stream cut because its replica was intentionally drained is
        # an operator action, not an error: past the retry budget it
        # finishes with the honest reason "drained", never a stall/error
        drained = from_name in self._draining or "drain" in reason
        if req.failovers > self.retry_budget:
            if drained:
                self._finish(req, "drained")
                _registry.counter(
                    "router/drained_streams",
                    help="streams finished because their replica was "
                         "drained past the retry budget").inc()
            else:
                self._finish(req, "error")
                _registry.counter(
                    "router/errors",
                    help="streams failed after the retry budget").inc()
            return
        try:
            self._dispatch(req, exclude=(from_name,))
        except AdmissionError:
            if drained:
                self._finish(req, "drained")
                _registry.counter("router/drained_streams").inc()
            else:
                self._finish(req, "error")
                _registry.counter("router/errors").inc()
            return
        _registry.counter(
            "router/failovers",
            help="mid-stream re-dispatches after replica failure").inc()
        telemetry.reqtrace.flag(req.trace, "failover")
        telemetry.reqtrace.instant(
            "router/failover", req.trace, tid=req.uid,
            replica=from_name, to=req.primary.replica.name,
            reason=reason, replay=req.failovers,
            replayed_tokens=len(req.tokens_out))
        telemetry.flight_recorder.record_event(
            "router_failover", replica=from_name,
            to=req.primary.replica.name, uid=req.uid, reason=reason,
            replayed_tokens=len(req.tokens_out))

    def _on_replica_down(self, replica: LocalReplica, reason: str) -> None:
        self.breakers[replica.name].force_open(reason)
        for req in list(self._reqs.values()):
            if req.done:
                continue
            for a in (req.primary, req.hedge):
                if a is not None and a.replica is replica:
                    self._fail_assignment(req, a, reason)

    # -- health -------------------------------------------------------------

    def check_health(self) -> None:
        """Out-of-band sweep: ``/healthz`` of every replica exposing an
        endpoint feeds its breaker (sustained 503 opens it; an ok
        answer is the half-open probe success that readmits it).
        Replicas without endpoints are probed in-band only: a half-open
        breaker on a live replica closes here (its probe is the next
        request routed to it)."""
        from deepspeed_tpu.telemetry.fleet import HostSample, poll_host
        for r in self.replicas:
            if not r.alive:
                continue
            br = self.breakers[r.name]
            target = r.http_target()
            if target is None:
                if br.state is BreakerState.HALF_OPEN:
                    br.record_success()
                continue
            sample = poll_host(HostSample(target), timeout=1.0,
                               clock=self.clock)
            if sample.ok and sample.status == "ok":
                br.record_success()
            else:
                if br.record_failure(f"healthz {sample.status}"):
                    self._on_replica_down(r, f"healthz {sample.status}")

    # -- the coordinator loop -----------------------------------------------

    def poll(self) -> bool:
        """One coordinator iteration: chaos hook → health sweep → token
        fan-in (winner decision, failover, hedging) → drain/recovery
        bookkeeping → state gauges. Returns True while streams are in
        flight."""
        now = self.clock()
        self._polls += 1
        for kind in fault_injector.fire("router", serving_step=self._polls):
            if kind in ("replica_kill", "replica_slow"):
                self._apply_chaos(kind)
        if self.health_every and self._polls % self.health_every == 0:
            self.check_health()
        for r in self.replicas:
            if not r.alive and (self._assigned_count(r) or
                                self.breakers[r.name].state
                                is not BreakerState.OPEN):
                why = ("killed" if r.killed else
                       f"pump died: {type(r.error).__name__}: {r.error}"
                       if r.error else "pump thread exited")
                self._on_replica_down(r, why)
        for req in list(self._reqs.values()):
            if not req.done:
                self._service(req, now)
            if req.done:
                self._reqs.pop(req.uid, None)
        self._sweep_draining()
        self._sweep_recoveries(now)
        self._publish_states()
        self._update_degraded()
        return bool(self._reqs)

    def _service(self, req: RouterRequest, now: float) -> None:
        # 1. decide the race (first token wins; primary on a tie)
        if req.winner is None:
            for a in (req.primary, req.hedge):
                if a is not None and a.replica.alive and a.inner.tokens_out:
                    req.winner = a
                    break
            if req.winner is not None and req.hedge is not None \
                    and req.primary is not None:
                loser = (req.hedge if req.winner is req.primary
                         else req.primary)
                won = req.winner is req.hedge
                _registry.counter(
                    "router/hedges_won" if won else "router/hedges_lost",
                    help="hedge race outcomes").inc()
                # tag both racing legs: winner/loser markers, plus
                # ``winner`` baggage so spans the legs emit from here on
                # carry it (critical_path drops winner==0 spans — the
                # loser ran off the critical path)
                if req.winner.ctx is not None:
                    req.winner.ctx.baggage["winner"] = 1
                    telemetry.reqtrace.instant(
                        "router/hedge_won", req.winner.ctx, tid=req.uid,
                        replica=req.winner.replica.name, winner=1)
                if loser.ctx is not None:
                    loser.ctx.baggage["winner"] = 0
                    telemetry.reqtrace.instant(
                        "router/hedge_lost", loser.ctx, tid=req.uid,
                        replica=loser.replica.name, winner=0)
                loser.replica.cancel(loser.inner)
                if won:
                    req.primary, req.hedge = req.hedge, None
                else:
                    req.hedge = None
                req.winner = req.primary
        active = req.winner or req.primary
        # 2. drain winner tokens to the client view
        if active is not None and active.replica.alive:
            self._drain_tokens(req, active, now)
        # 3. replica health of the active leg
        if active is not None:
            br = self.breakers[active.replica.name]
            if not active.replica.alive or \
                    br.state is BreakerState.OPEN:
                self._fail_assignment(
                    req, active,
                    "replica dead" if not active.replica.alive
                    else f"breaker open: {br.last_reason}")
                return
        # 4. inner terminal states propagate (or trigger failover)
        if active is not None and active.inner.done:
            inner = active.inner
            if inner.finish_reason == "error":
                # the replica burned ITS retry budget under this stream
                if self.breakers[active.replica.name].record_failure(
                        "stream errored"):
                    self._on_replica_down(active.replica, "stream errored")
                else:
                    self._fail_assignment(req, active, "stream errored")
                return
            if inner.finish_reason == "drained":
                # the replica cut this leg because it is scaling down —
                # failover elsewhere, or finish honestly as "drained"
                self._fail_assignment(req, active, "replica drained")
                return
            if inner.state is RequestState.SHED:
                self._finish(req, inner.finish_reason or "deadline")
                _registry.counter(
                    "router/shed",
                    help="streams shed past their deadline").inc()
                return
            if active.role == "prefill":
                # the prefill leg ran exactly one token — catch any
                # late-arriving token first, then either finish (eos /
                # budget done) or hand the KV pages to the decode pool
                self._drain_tokens(req, active, now)
                if inner.finish_reason != "eos" and \
                        len(req.tokens_out) < req.max_new_tokens:
                    self._promote_to_decode(req, active, now)
                    return
            self._finish(req, inner.finish_reason or "length")
            _registry.counter(
                "router/completed",
                help="streams finished successfully").inc()
            if self.breakers[active.replica.name].state \
                    is BreakerState.HALF_OPEN:
                self.breakers[active.replica.name].record_success()
            return
        # 5. stall detection: an assigned stream making no progress is
        # an in-band failure observation
        if active is not None:
            last = req.last_progress_ts or active.dispatch_ts
            if now - last > self.stall_timeout_s:
                req.last_progress_ts = now   # one observation per window
                if self.breakers[active.replica.name].record_failure(
                        f"no progress for {self.stall_timeout_s:.1f}s"):
                    self._on_replica_down(active.replica, "stalled")
                else:
                    self._fail_assignment(req, active, "stalled")
                return
        # 6. hedged dispatch for queued-too-long requests
        if (self.hedge and req.winner is None and req.hedge is None
                and req.primary is not None
                and not req.tokens_out
                and now - req.primary.dispatch_ts > self._hedge_delay()):
            try:
                self._dispatch(req, exclude=(req.primary.replica.name,),
                               hedge=True)
            except AdmissionError:
                return                       # nobody to race — keep waiting
            req.hedged = True
            _registry.counter(
                "router/hedges",
                help="hedge legs dispatched for slow first tokens").inc()
            telemetry.tracer.instant(
                "router/hedge", uid=req.uid,
                primary=req.primary.replica.name,
                hedge=req.hedge.replica.name)
            telemetry.reqtrace.flag(req.trace, "hedge")
            telemetry.reqtrace.instant(
                "router/hedge", req.trace, tid=req.uid,
                primary=req.primary.replica.name,
                hedge=req.hedge.replica.name)
            # the first hedge raced against a chaos-slowed replica IS
            # that fault's recovery: the mitigation engaged and the
            # tail request no longer waits on the degraded replica
            pname = req.primary.replica.name
            if pname in self._pending_slow:
                t0 = self._pending_slow.pop(pname)
                record_recovery("router_hedge", replica=pname,
                                uid=req.uid,
                                engaged_s=round(now - t0, 3))

    def _hedge_delay(self) -> float:
        if self.hedge_delay_s is not None:
            return float(self.hedge_delay_s)
        if self.ttft.count >= 20:
            return max(0.02, float(self.ttft.percentile(95)))
        return 0.25

    def _drain_tokens(self, req: RouterRequest, assign: _Assignment,
                      now: float) -> None:
        """Fold new tokens from ``assign`` into the client view (TTFT on
        the first, progress stamp, per-replica accounting)."""
        inner_toks = assign.inner.tokens_out
        if len(inner_toks) <= assign.drained:
            return
        new = inner_toks[assign.drained:]
        assign.drained = len(inner_toks)
        if req.first_token_ts is None:
            req.first_token_ts = now
            self.ttft.record(
                max(0.0, now - (req.submit_ts or now)),
                exemplar=(req.trace.trace_id
                          if req.trace is not None else None))
        req.tokens_out.extend(int(t) for t in new)
        req.last_progress_ts = now
        self.replica_tokens[assign.replica.name] = \
            self.replica_tokens.get(assign.replica.name, 0) + len(new)
        _registry.counter(
            "router/tokens_out",
            help="tokens delivered to clients").inc(len(new))

    # -- prefill → decode handoff -------------------------------------------

    def _promote_to_decode(self, req: RouterRequest, active: _Assignment,
                           now: float) -> None:
        """The prefill leg delivered its first token — move the request
        to the decode pool. The happy path ships the prefill replica's
        radix-cached KV pages (export → checksummed bundle → adopt into
        the decode arena BEFORE the decode leg dispatches, so its
        ``adopt_cached`` admission aliases them). The failure domain is
        handled here too: a torn (``handoff_torn``) or timed-out
        (``handoff_stall``) bundle adopts nothing and the decode replica
        re-prefills the folded prompt — recompute, never token loss —
        and the fallback is ledgered so faults == recoveries closes."""
        from deepspeed_tpu.serving.handoff import (adopt_bundle,
                                                   export_bundle,
                                                   verify_bundle)
        src = active.replica
        req.handoff_tokens = len(req.tokens_out)
        h0 = time.monotonic()      # handoff span clock — tracer-aligned
        # fault hook: handoff_torn corrupts the bundle in transit,
        # handoff_stall loses it outright — both land in the fallback
        torn = stalled = False
        for kind in fault_injector.fire("handoff",
                                        serving_step=self._polls):
            if kind == "handoff_torn":
                torn = True
            elif kind == "handoff_stall":
                stalled = True
        bundle = None
        if stalled:
            _registry.counter(
                "handoff/stalls",
                help="page bundles lost in transit (timeout)").inc()
        else:
            try:
                with src.lock:
                    bundle = export_bundle(src.frontend, req.prompt)
            except Exception as e:   # noqa: BLE001 — source may be dying
                logger.warning("handoff: export from %s failed: %s",
                               src.name, e)
                bundle = None
            if torn and bundle is not None:
                bundle.checksum ^= 0x1
                _registry.counter(
                    "handoff/torn",
                    help="page bundles failing checksum on arrival").inc()
        # the shipped subtree leaves the source either way: pages that
        # arrived belong to the decode pool now, pages that didn't are
        # suspect — over-invalidation costs recompute, never correctness
        try:
            with src.lock:
                cache = getattr(src.frontend, "cache", None)
                if cache is not None:
                    cache.invalidate(req.prompt)
        except Exception:   # noqa: BLE001 — dying source already failed over
            pass
        req.phase = "decode"
        req.primary = None
        req.winner = None
        if req.hedge is not None:
            if req.hedge.replica.alive:
                req.hedge.replica.cancel(req.hedge.inner)
            req.hedge = None
        folded = req.prompt + req.tokens_out
        fault_kind = ("handoff_torn" if torn
                      else "handoff_stall" if stalled else None)
        dec: Optional[LocalReplica] = None
        adopted = 0
        if bundle is not None and verify_bundle(bundle):
            # pick the decode replica FIRST, adopt under its lock, THEN
            # dispatch pinned to it — dispatch-before-adopt would let the
            # pump admit the leg before the pages are cached (silent full
            # re-prefill)
            try:
                dec = self._choose(folded, pool="decode")
                with dec.lock:
                    adopted = adopt_bundle(dec.frontend, bundle)
            except AdmissionError:
                dec = None
            except Exception as e:   # noqa: BLE001
                logger.warning("handoff: adopt into %s failed: %s",
                               dec.name if dec is not None else "?", e)
                adopted = 0
        if adopted:
            _registry.counter(
                "handoff/completed",
                help="prefill→decode page handoffs that shipped").inc()
            _registry.counter(
                "handoff/pages_shipped",
                help="KV pages adopted by decode replicas").inc(adopted)
            _registry.counter(
                "handoff/bytes_shipped",
                help="KV bytes adopted by decode replicas").inc(
                    bundle.nbytes)
            telemetry.flight_recorder.record_event(
                "router_handoff", replica=src.name, to=dec.name,
                pages=adopted, uid=req.uid)
        elif fault_kind is not None:
            _registry.counter(
                "handoff/fallback_reprefills",
                help="failed handoffs recovered by decode-side "
                     "re-prefill").inc()
            self._pending_handoff[req.uid] = {
                "req": req, "t0": now, "kind": fault_kind,
                "from": src.name}
            telemetry.flight_recorder.record_event(
                "router_handoff_fallback", replica=src.name,
                fault=fault_kind, uid=req.uid)
        else:
            _registry.counter(
                "handoff/skipped",
                help="promotions with no cached pages to ship").inc()
        if fault_kind is not None:
            telemetry.reqtrace.flag(req.trace, "reprefill")
        telemetry.reqtrace.complete(
            "router/handoff", req.trace, h0, time.monotonic(),
            tid=req.uid, src=src.name,
            dst=(dec.name if dec is not None else None),
            pages=adopted,
            bytes=(bundle.nbytes if adopted and bundle is not None
                   else 0),
            fault=fault_kind)
        try:
            self._dispatch(req, prefer=dec)
        except AdmissionError:
            self._finish(req, "error")
            _registry.counter("router/errors").inc()

    def _finish(self, req: RouterRequest, reason: str) -> None:
        for a in (req.primary, req.hedge):
            if a is not None and a.replica.alive and not a.inner.done:
                a.replica.cancel(a.inner)
        req.state = (RequestState.SHED if reason == "deadline"
                     else RequestState.FINISHED)
        req.finish_reason = reason
        req.finish_ts = self.clock()
        if req.trace is None:
            return
        # the router owns the root context: emit the client-visible
        # envelope span, then hand the trace to the tail sampler —
        # retained (flushed into the ring) or dropped whole
        rt = telemetry.reqtrace
        ttft = (req.first_token_ts - req.submit_ts
                if req.first_token_ts is not None
                and req.submit_ts is not None else None)
        tpot = ((req.finish_ts - req.first_token_ts) /
                (len(req.tokens_out) - 1)
                if req.first_token_ts is not None
                and len(req.tokens_out) >= 2 else None)
        if req.submit_ts is not None:
            rt.complete("router/request", req.trace, req.submit_ts,
                        req.finish_ts, tid=req.uid, envelope=True,
                        reason=reason, tokens_out=len(req.tokens_out),
                        failovers=req.failovers, hedged=int(req.hedged),
                        handoff_tokens=req.handoff_tokens)
        rt.finish(req.trace, reason=reason, ttft_s=ttft, tpot_s=tpot)

    # -- draining & recovery ledger -----------------------------------------

    def drain(self, name: str,
              deadline_s: Optional[float] = None) -> None:
        """Stop new admissions to ``name``; in-flight decodes finish on
        it, then :meth:`poll` removes it without dropping a stream.
        With ``deadline_s`` set, streams still assigned past the
        deadline fail over (token-fold replay) instead of pinning the
        replica open — the scale-down path uses this so a wedged stream
        can't block the fleet from shrinking."""
        if name not in {r.name for r in self.replicas}:
            raise KeyError(f"no replica named {name!r}")
        self._draining.add(name)
        if deadline_s is not None:
            self._drain_deadline[name] = self.clock() + float(deadline_s)
        _registry.counter("router/drains",
                          help="replicas put into draining").inc()
        telemetry.flight_recorder.record_event("router_drain_start",
                                               replica=name)
        self._publish_states()

    def _sweep_draining(self) -> None:
        now = self.clock()
        for r in list(self.replicas):
            if r.name not in self._draining:
                continue
            if self._assigned_count(r) and \
                    now >= self._drain_deadline.get(r.name, float("inf")):
                for req in list(self._reqs.values()):
                    if req.done:
                        continue
                    for a in (req.primary, req.hedge):
                        if a is not None and a.replica is r:
                            self._fail_assignment(req, a, "drain deadline")
            if self._assigned_count(r) == 0:
                self._draining.discard(r.name)
                self._drain_deadline.pop(r.name, None)
                self.replicas.remove(r)
                _registry.gauge(f"router/replica/{r.name}/state").set(
                    STATE_CODES["dead"])
                telemetry.flight_recorder.record_event(
                    "router_drained", replica=r.name, pool=r.pool)
                logger.warning("router: replica %s drained and removed",
                               r.name)
                r.close()

    def _sweep_recoveries(self, now: float) -> None:
        for uid in list(self._pending_handoff):
            entry = self._pending_handoff[uid]
            req = entry["req"]
            if not req.done:
                continue
            del self._pending_handoff[uid]
            if req.finish_reason == "error":
                continue     # the fallback itself failed — stays open
            record_recovery("handoff_reprefill", fault=entry["kind"],
                            replica=entry["from"], uid=uid,
                            recovery_s=round(now - entry["t0"], 3))
            logger.warning("router: %s handoff for uid=%d recovered by "
                           "decode-side re-prefill in %.3fs",
                           entry["kind"], uid, now - entry["t0"])
        for name in list(self._pending_recovery):
            entry = self._pending_recovery[name]
            if any(uid in self._reqs and not self._reqs[uid].done
                   for uid in entry["uids"]):
                continue
            recovery_s = now - entry["t0"]
            del self._pending_recovery[name]
            _registry.gauge(
                "router/last_recovery_s",
                help="wall seconds from replica loss to the last "
                     "failed-over stream completing").set(recovery_s)
            record_recovery("router_failover", replica=name,
                            requests=len(entry["uids"]),
                            recovery_s=round(recovery_s, 3))
            logger.warning("router: replica %s loss recovered — %d "
                           "streams replayed in %.3fs", name,
                           len(entry["uids"]), recovery_s)

    # -- client surface -----------------------------------------------------

    def stream(self, req: RouterRequest, poll_interval: float = 0.001,
               stall_timeout: float = 60.0) -> Iterator[int]:
        """Yield ``req``'s tokens as they arrive, driving :meth:`poll`
        between yields."""
        emitted = 0
        t_last = time.monotonic()
        while True:
            while emitted < len(req.tokens_out):
                yield req.tokens_out[emitted]
                emitted += 1
                t_last = time.monotonic()
            if req.done:
                return
            self.poll()
            if time.monotonic() - t_last > stall_timeout:
                raise RuntimeError(
                    f"router stream stalled {stall_timeout:.1f}s: uid="
                    f"{req.uid} state={req.state.value} tokens="
                    f"{len(req.tokens_out)}/{req.max_new_tokens} "
                    f"replicas=" + ",".join(
                        f"{r.name}:{self.replica_state(r)}"
                        for r in self.replicas))
            time.sleep(poll_interval)

    def run_until_idle(self, wall_timeout_s: float = 120.0,
                       poll_interval: float = 0.001) -> None:
        """Drive :meth:`poll` until every admitted stream is terminal."""
        t0 = time.monotonic()
        while self.poll():
            if time.monotonic() - t0 > wall_timeout_s:
                raise RuntimeError(
                    f"router did not drain in {wall_timeout_s:.0f}s: "
                    f"{len(self._reqs)} streams in flight, replicas=" +
                    ",".join(f"{r.name}:{self.replica_state(r)}"
                             for r in self.replicas))
            time.sleep(poll_interval)

    def stats(self) -> Dict[str, Any]:
        c = _registry.counter
        return {
            "replicas": {r.name: self.replica_state(r)
                         for r in self.replicas},
            "pools": {r.name: r.pool for r in self.replicas},
            "disaggregated": self.disaggregated,
            "requests": int(c("router/requests").value),
            "completed": int(c("router/completed").value),
            "errors": int(c("router/errors").value),
            "failovers": int(c("router/failovers").value),
            "hedges": int(c("router/hedges").value),
            "hedges_won": int(c("router/hedges_won").value),
            "hedges_lost": int(c("router/hedges_lost").value),
            "breaker_transitions":
                int(c("router/breaker_transitions").value),
            "tokens_out": int(c("router/tokens_out").value),
            "drained_streams": int(c("router/drained_streams").value),
            "handoffs": int(c("handoff/completed").value),
            "handoff_pages": int(c("handoff/pages_shipped").value),
            "handoff_fallbacks":
                int(c("handoff/fallback_reprefills").value),
            "handoff_skipped": int(c("handoff/skipped").value),
            "replica_tokens": dict(self.replica_tokens),
            "ttft_p95_s": (round(self.ttft.percentile(95), 4)
                           if self.ttft.count else None),
            "last_recovery_s":
                _registry.gauge("router/last_recovery_s").value,
        }

    def close(self) -> None:
        if self._http is not None:
            self._http.close()
            self._http = None
        for r in self.replicas:
            r.close()


# ---------------------------------------------------------------------------
# dstpu-router CLI: a local replica pool + drill in one command
# ---------------------------------------------------------------------------

def _build_local_pool(n: int, size: str, http_ports: bool,
                      seed: int = 0, pools: Optional[List[str]] = None,
                      ) -> List[LocalReplica]:
    """N in-process replicas over tiny CPU engines sharing one param
    tree (each replica owns its engine + KV arena, exactly the state a
    real replica process would lose on a kill). ``pools`` assigns each
    replica's pool (``prefill``/``decode``/``any``) for a disaggregated
    fleet; default is a monolithic ``any`` pool."""
    import jax
    from deepspeed_tpu.inference.engine_v2 import RaggedInferenceEngineTPU
    from deepspeed_tpu.models.llama import llama3_config
    from deepspeed_tpu.models.transformer import init_params
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.serving.frontend import ServingFrontend
    build_mesh(data=1, devices=jax.devices()[:1])
    cfg = llama3_config(size, max_seq_len=256, vocab_size=256)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    eng_cfg = {"dtype": "float32", "num_blocks": 64, "block_size": 8,
               "max_seq_len": 256, "prefill_chunk": 16,
               "max_batch_tokens": 128, "max_sequences": 16}
    out = []
    for i in range(n):
        eng = RaggedInferenceEngineTPU(cfg, dict(eng_cfg), params=params)
        fe = ServingFrontend(eng, max_queue=256,
                             http_port=(0 if http_ports else None))
        pool = pools[i] if pools else "any"
        out.append(LocalReplica(f"r{i}", fe, pool=pool))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """``dstpu-router``: spin up a local pool of N serving replicas,
    route a demo request stream over them (optionally under a chaos
    plan), and print a JSON drill summary::

        dstpu-router --replicas 3 --requests 24 \\
            --chaos "serving_step:8:replica_kill:router"

    For a multi-process pool, spawn the replicas with the launcher's
    pool agent (``python -m deepspeed_tpu.launcher.agent --pool N --
    ...``) and point a Router at their endpoints.
    """
    import argparse
    import json as _json
    ap = argparse.ArgumentParser(
        prog="dstpu-router",
        description="Fault-tolerant multi-replica serving router: local "
                    "pool demo + chaos drill harness.")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--prefill", type=int, default=0,
                    help="run a DISAGGREGATED fleet: this many prefill "
                         "replicas (use with --decode; overrides "
                         "--replicas)")
    ap.add_argument("--decode", type=int, default=0,
                    help="decode-pool replicas for --prefill")
    ap.add_argument("--size", default="tiny")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--chaos", default=None,
                    help="fault plan armed for the drill (e.g. "
                         "'serving_step:8:replica_kill:router')")
    ap.add_argument("--http-port", type=int, default=None,
                    help="router /metrics + /healthz port (0=ephemeral)")
    ap.add_argument("--replica-http", action="store_true",
                    help="give each replica its own ephemeral endpoint "
                         "(breaker then also polls /healthz)")
    ap.add_argument("--no-hedge", action="store_true")
    ap.add_argument("--hedge-delay", type=float, default=None)
    args = ap.parse_args(argv)

    import numpy as np
    rng = np.random.default_rng(0)
    if args.prefill or args.decode:
        if not (args.prefill and args.decode):
            ap.error("--prefill and --decode must both be > 0")
        pools = (["prefill"] * args.prefill + ["decode"] * args.decode)
        replicas = _build_local_pool(len(pools), args.size,
                                     args.replica_http, pools=pools)
    else:
        replicas = _build_local_pool(args.replicas, args.size,
                                     args.replica_http)
    router = Router(replicas, hedge=not args.no_hedge,
                    hedge_delay_s=args.hedge_delay,
                    http_port=args.http_port)
    if args.chaos:
        fault_injector.arm(args.chaos, _env=False)
    shared = rng.integers(1, 250, size=8).tolist()
    t0 = time.perf_counter()
    reqs = [router.submit(shared + rng.integers(1, 250, size=4).tolist(),
                          max_new_tokens=args.max_new)
            for _ in range(args.requests)]
    try:
        router.run_until_idle(wall_timeout_s=300.0)
    finally:
        wall = time.perf_counter() - t0
        summary = {"drill": {"replicas": len(replicas),
                             "requests": args.requests,
                             "chaos": args.chaos,
                             "wall_s": round(wall, 3)},
                   "ok": all(r.finish_reason in ("length", "eos")
                             for r in reqs),
                   "router": router.stats()}
        print(_json.dumps(summary))
        router.close()
        fault_injector.disarm()
    return 0 if all(r.done for r in reqs) else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
