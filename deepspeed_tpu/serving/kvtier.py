"""Tiered KV cache: HBM → host DRAM → NVMe paging for returning sessions.

Millions of users means millions of *idle* conversations. Their cached
prefixes are pure gold on return (warm resume skips the prefill) but pure
waste while idle — HBM pages pinned by the radix cache are pages decode
batches can't use. This module is the vertical tier underneath
:class:`~deepspeed_tpu.serving.prefix_cache.PrefixCache` that resolves
the tension, the ZeRO-Infinity HBM→DRAM→NVMe offload hierarchy retargeted
from parameters at serving KV:

- **Capture.** When the radix cache evicts a cold leaf (ref count zero in
  the arena, least-recently-used by the cache clock), the page is
  exported host-side FIRST (``engine.export_pages``) and stored in a
  bounded DRAM arena as a checksummed :class:`PageBundle` keyed by the
  exact token prefix it covers — PR 11's export/verify/adopt handoff
  machinery generalized from horizontal (replica→replica) to vertical
  (HBM→host) movement. Optionally EQuARX-style low-precision encoded
  (fp16 / int8 + scale): cold pages tolerate lossy storage because a
  mismatch only costs a slightly different resume, never correctness of
  accounting.
- **Spill.** Past the DRAM high watermark, the least-recently-used
  bundles serialize to an NVMe directory (atomic tmp+rename writes via
  :func:`~deepspeed_tpu.io.async_io.atomic_write`; deliberately not
  fsync'd — see :meth:`KVTier._spill_one`) until usage falls under the
  low watermark. The NVMe level is itself bounded
  (``nvme_max_bytes``); beyond it the coldest entries are dropped — the
  tier degrades to re-prefill, never to an error.
- **Prefetch + adopt.** On the first token of a returning conversation
  (``ServingFrontend.submit``), :meth:`KVTier.issue_prefetch` starts
  async preads of any NVMe-resident chain pages (the PR 6 ``param_stream``
  issue/complete split, retargeted at KV) so the bytes move while the
  request waits in admission; at admission :meth:`KVTier.adopt` drains,
  CRC-verifies, decodes, imports into freshly allocated arena pages and
  re-inserts into the radix cache — the request's normal ``adopt_cached``
  aliasing then skips prefill for everything the tier restored.

Failure domain: a torn spill (CRC mismatch on load — ``kvtier_torn_spill``)
or a stale entry at adoption (``kvtier_stale_adopt``) adopts nothing from
that point in the chain; the request re-prefills the uncovered suffix.
Like handoff, the tier never carries tokens — a lost page costs
recompute, never correctness — and every fault closes the
faults==recoveries ledger with a ``kvtier_reprefill`` recovery.
"""

import json
import os
import struct
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import ml_dtypes
import numpy as np

from deepspeed_tpu.io.async_io import AsyncIOEngine, atomic_write, \
    pread_retry
from deepspeed_tpu.resilience.faults import fault_injector, record_recovery
from deepspeed_tpu.serving.handoff import PageBundle, _checksum, \
    verify_bundle

#: spill file header magic — a file that doesn't start with it is torn
_MAGIC = b"DSKV"
_COMPRESS_MODES = ("none", "fp16", "int8")


class TornSpill(RuntimeError):
    """A tier entry failed CRC verification on load (torn spill file or
    corrupted DRAM bundle). The tier drops the entry and the returning
    conversation re-prefills — never adopts garbage KV."""


def _np_dtype(name: str):
    return {"bfloat16": ml_dtypes.bfloat16,
            "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
            "float8_e5m2": ml_dtypes.float8_e5m2}.get(name) or np.dtype(name)


def _encode(pages: Dict[str, np.ndarray], compress: str
            ) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Encode an ``export_pages`` payload for cold storage. ``none`` is
    byte-exact; ``fp16``/``int8`` are the EQuARX-style low-precision
    knobs (per-array symmetric scale for int8) — lossy, which is fine
    for COLD pages whose alternative is not existing at all."""
    if compress not in _COMPRESS_MODES:
        raise ValueError(f"kvtier compress mode {compress!r} "
                         f"(want one of {_COMPRESS_MODES})")
    src_dtype = str(np.asarray(next(iter(pages.values()))).dtype)
    meta: Dict = {"compress": compress, "dtype": src_dtype, "scales": None}
    if compress == "none":
        payload = {k: np.ascontiguousarray(v) for k, v in pages.items()}
    elif compress == "fp16":
        payload = {k: np.asarray(v, np.float32).astype(np.float16)
                   for k, v in pages.items()}
    else:                                   # int8 + per-array scale
        payload, scales = {}, {}
        for k, v in pages.items():
            a = np.asarray(v, np.float32)
            s = float(np.max(np.abs(a)) / 127.0) if a.size else 0.0
            s = s or 1.0
            payload[k] = np.clip(np.round(a / s), -127, 127).astype(np.int8)
            scales[k] = s
        meta["scales"] = scales
    return payload, meta


def _decode(payload: Dict[str, np.ndarray], meta: Dict
            ) -> Dict[str, np.ndarray]:
    dtype = _np_dtype(meta["dtype"])
    compress = meta["compress"]
    if compress == "none":
        return {k: np.asarray(v, dtype) for k, v in payload.items()}
    if compress == "fp16":
        return {k: np.asarray(v, np.float32).astype(dtype)
                for k, v in payload.items()}
    return {k: (np.asarray(v, np.float32) * meta["scales"][k]).astype(dtype)
            for k, v in payload.items()}


@dataclass
class _TierEntry:
    """One page-sized token prefix resident in the tier. ``bundle`` set →
    DRAM-resident; ``path`` set → NVMe-resident (exactly one of the two).
    ``checksum`` is the expected CRC32 of the ENCODED payload bytes, the
    torn detector at every level."""
    key: Tuple[int, ...]
    meta: Dict
    checksum: int
    nbytes: int                      # encoded payload bytes (DRAM cost)
    bundle: Optional[PageBundle] = field(default=None, repr=False)
    path: Optional[str] = None
    file_bytes: int = 0
    arrays: Optional[List[Dict]] = None   # encoded shapes/dtypes for load


def _serialize_entry(entry: _TierEntry) -> bytes:
    """Entry → spill file bytes: magic, u32 header length, JSON header,
    encoded payload arrays in sorted-key order. Self-describing — the
    loader needs nothing but the file (and verifies CRC before trusting
    a byte of payload)."""
    payload = entry.bundle.pages
    arrays = [{"key": k,
               "shape": list(payload[k].shape),
               "dtype": str(payload[k].dtype),
               "nbytes": int(payload[k].nbytes)}
              for k in sorted(payload)]
    header = json.dumps({
        "tokens": list(entry.key), "meta": entry.meta,
        "crc": entry.checksum, "arrays": arrays,
    }).encode()
    parts = [_MAGIC, struct.pack("<I", len(header)), header]
    parts += [np.ascontiguousarray(payload[k]).tobytes()
              for k in sorted(payload)]
    return b"".join(parts)


def _parse_spill(raw: bytes) -> Tuple[Dict, Dict[str, np.ndarray]]:
    """Spill file bytes → (header, payload arrays). Raises
    :class:`TornSpill` on any structural damage or CRC mismatch."""
    if len(raw) < 8 or raw[:4] != _MAGIC:
        raise TornSpill("spill file is not a KV bundle (bad magic)")
    hlen = struct.unpack("<I", raw[4:8])[0]
    if len(raw) < 8 + hlen:
        raise TornSpill("spill file truncated inside header")
    try:
        header = json.loads(raw[8:8 + hlen])
    except ValueError as e:
        raise TornSpill(f"spill header is not valid JSON: {e}") from e
    body = raw[8 + hlen:]
    if zlib.crc32(body) != int(header["crc"]):
        raise TornSpill("spill payload failed CRC32 verification")
    payload: Dict[str, np.ndarray] = {}
    off = 0
    for a in header["arrays"]:
        n = int(a["nbytes"])
        if off + n > len(body):
            raise TornSpill("spill payload truncated")
        payload[a["key"]] = np.frombuffer(
            body[off:off + n], dtype=_np_dtype(a["dtype"])
        ).reshape(a["shape"])
        off += n
    return header, payload


def _count(name: str, by: int = 1, help: str = "") -> None:
    try:
        from deepspeed_tpu import telemetry
        telemetry.registry.counter(name, help=help).inc(by)
    except Exception:                                # noqa: BLE001
        pass


def _event(kind: str, **fields) -> None:
    try:
        from deepspeed_tpu import telemetry
        telemetry.flight_recorder.record_event(kind, **fields)
    except Exception:                                # noqa: BLE001
        pass


class KVTier:
    """The host-side page tier under one frontend's radix cache.

    Entries are keyed by the exact token prefix a page covers (full pages:
    a multiple of ``block_size`` tokens from the root; at most one partial
    extension per chain). LRU order is the :class:`OrderedDict` order —
    every capture/match moves the touched chain to the MRU end, so
    watermark spills and capacity drops always take the coldest
    conversation first, deterministically.
    """

    def __init__(self, engine, dram_bytes: int = 256 << 20,
                 nvme_dir: Optional[str] = None,
                 nvme_max_bytes: Optional[int] = None,
                 high_watermark: float = 0.9, low_watermark: float = 0.7,
                 compress: str = "none",
                 aio: Optional[AsyncIOEngine] = None):
        if not 0.0 < low_watermark <= high_watermark <= 1.0:
            raise ValueError(
                f"kvtier watermarks must satisfy 0 < low <= high <= 1 "
                f"(got low={low_watermark}, high={high_watermark})")
        if compress not in _COMPRESS_MODES:
            raise ValueError(f"kvtier compress mode {compress!r} "
                             f"(want one of {_COMPRESS_MODES})")
        self.engine = engine
        self.block_size = engine.state.allocator.block_size
        self.dram_bytes = int(dram_bytes)
        self.nvme_dir = nvme_dir
        self.nvme_max_bytes = nvme_max_bytes
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.compress = compress
        self.aio = aio or AsyncIOEngine()
        if nvme_dir:
            os.makedirs(nvme_dir, exist_ok=True)
        #: LRU: oldest first; values are :class:`_TierEntry`
        self._entries: "OrderedDict[Tuple[int, ...], _TierEntry]" = \
            OrderedDict()
        #: full-page prefix → partial keys extending it (chain tails)
        self._partial_index: Dict[Tuple[int, ...], List[Tuple[int, ...]]] \
            = {}
        #: NVMe prefetches in flight: key → destination byte buffer
        self._inflight: Dict[Tuple[int, ...], np.ndarray] = {}
        self._dram_used = 0
        self._nvme_used = 0
        self._spill_seq = 0
        #: adopt-attempt clock — the ``serving_step`` the chaos schedule
        #: triggers ``kvtier_*`` kinds against
        self._ops = 0
        self.counters = {k: 0 for k in (
            "captures", "spills", "adopts", "hits", "misses",
            "torn_spills", "stale_adopts", "fallback_reprefills",
            "dropped", "invalidated", "prefetch_issued",
            "bytes_spilled", "bytes_adopted")}

    # -- capture (PrefixCache eviction sink) --------------------------------

    def capture(self, tokens: List[int], block: int) -> bool:
        """Export one page the radix cache is about to evict into the
        DRAM arena. Called by ``PrefixCache.evict`` BEFORE the allocator
        ref drops — the page's KV is still valid in the arena at export
        time even if another owner keeps the physical page alive after.
        Returns True when the page entered the tier."""
        key = tuple(int(t) for t in tokens)
        if not key:
            return False
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        pages = self.engine.export_pages([block])
        payload, meta = _encode(pages, self.compress)
        crc = _checksum(payload)
        bundle = PageBundle(tokens=list(key), block_size=self.block_size,
                            pages=payload, checksum=crc)
        entry = _TierEntry(key=key, meta=meta, checksum=crc,
                           nbytes=bundle.nbytes, bundle=bundle)
        self._entries[key] = entry
        if len(key) % self.block_size != 0:
            base = key[:len(key) - len(key) % self.block_size]
            self._partial_index.setdefault(base, []).append(key)
        self._dram_used += entry.nbytes
        self.counters["captures"] += 1
        _count("kvtier/evictions",
               help="radix-cache pages captured into the host tier")
        self._maybe_spill()
        self._publish()
        return True

    # -- spill (DRAM watermark → NVMe) --------------------------------------

    def _spill_one(self, entry: _TierEntry) -> bool:
        """DRAM → NVMe for one entry (atomic, deliberately NOT fsync'd:
        tier contents are recomputable cache state — a torn file after a
        crash is caught by the CRC at load and costs one re-prefill, so
        paying a durability barrier per spill in the serving path buys
        nothing). Returns False when there is no NVMe level to spill
        to."""
        if not self.nvme_dir:
            return False
        data = _serialize_entry(entry)
        self._spill_seq += 1
        path = os.path.join(
            self.nvme_dir,
            f"kv-{self._spill_seq:08d}-{entry.checksum & 0xFFFFFFFF:08x}"
            f".bundle")
        atomic_write(path, data, durable=False)
        self._dram_used -= entry.nbytes
        entry.arrays = [{"key": k,
                         "shape": list(entry.bundle.pages[k].shape),
                         "dtype": str(entry.bundle.pages[k].dtype),
                         "nbytes": int(entry.bundle.pages[k].nbytes)}
                        for k in sorted(entry.bundle.pages)]
        entry.bundle = None
        entry.path = path
        entry.file_bytes = len(data)
        self._nvme_used += len(data)
        self.counters["spills"] += 1
        self.counters["bytes_spilled"] += len(data)
        _count("kvtier/spills", help="tier pages spilled DRAM → NVMe")
        _count("kvtier/bytes_spilled", len(data),
               help="bytes written to the NVMe tier level")
        _event("kvtier_spill", pages=1, bytes=len(data))
        return True

    def _maybe_spill(self) -> None:
        """Enforce the DRAM watermark pair: above ``high``, move the
        least-recently-used DRAM-resident entries down (or out) until
        usage is back under ``low`` — hysteresis so a hot eviction burst
        doesn't thrash one page across the boundary."""
        if self._dram_used <= self.high_watermark * self.dram_bytes:
            self._enforce_nvme_bound()
            return
        target = self.low_watermark * self.dram_bytes
        for key in list(self._entries):
            if self._dram_used <= target:
                break
            entry = self._entries[key]
            if entry.bundle is None:
                continue                     # already on NVMe
            if not self._spill_one(entry):
                self._drop(entry, reason="dram_full")
        self._enforce_nvme_bound()

    def _enforce_nvme_bound(self) -> None:
        if self.nvme_max_bytes is None:
            return
        if self._nvme_used <= self.nvme_max_bytes:
            return
        for key in list(self._entries):
            if self._nvme_used <= self.nvme_max_bytes:
                break
            entry = self._entries[key]
            if entry.path is not None:
                self._drop(entry, reason="nvme_full")

    def _drop(self, entry: _TierEntry, reason: str = "") -> None:
        """Remove an entry from every level and index (idempotent)."""
        if self._entries.pop(entry.key, None) is None:
            return
        if entry.bundle is not None:
            self._dram_used -= entry.nbytes
            entry.bundle = None
        if entry.path is not None:
            self._nvme_used -= entry.file_bytes
            try:
                os.unlink(entry.path)
            except OSError:
                pass
            entry.path = None
        if len(entry.key) % self.block_size != 0:
            base = entry.key[:len(entry.key)
                             - len(entry.key) % self.block_size]
            keys = self._partial_index.get(base)
            if keys and entry.key in keys:
                keys.remove(entry.key)
                if not keys:
                    del self._partial_index[base]
        self._inflight.pop(entry.key, None)
        if reason:
            self.counters["dropped"] += 1
            _count("kvtier/dropped",
                   help="tier entries dropped (capacity/stale/torn)")

    def _drop_subtree(self, prefix: Tuple[int, ...]) -> int:
        """Drop every entry whose key extends ``prefix`` (inclusive) —
        a lost or invalidated page orphans every deeper page of its
        chain."""
        doomed = [e for k, e in self._entries.items()
                  if len(k) >= len(prefix) and k[:len(prefix)] == prefix]
        for e in doomed:
            self._drop(e, reason="subtree")
        return len(doomed)

    # -- lookup -------------------------------------------------------------

    def _match_chain(self, prompt: List[int]) -> List[_TierEntry]:
        """Longest contiguous chain of tier entries covering a prefix of
        ``prompt``: full pages from the root, then at most one partial
        extension. Touch refreshes LRU recency."""
        bs = self.block_size
        prompt = [int(t) for t in prompt]
        chain: List[_TierEntry] = []
        i = bs
        while i <= len(prompt):
            entry = self._entries.get(tuple(prompt[:i]))
            if entry is None:
                break
            chain.append(entry)
            i += bs
        covered = i - bs
        best: Optional[Tuple[int, ...]] = None
        for pk in self._partial_index.get(tuple(prompt[:covered]), []):
            if len(pk) <= len(prompt) and tuple(prompt[:len(pk)]) == pk:
                if best is None or len(pk) > len(best):
                    best = pk
        if best is not None:
            chain.append(self._entries[best])
        for entry in chain:
            self._entries.move_to_end(entry.key)
        return chain

    def match_pages(self, prompt: List[int]) -> int:
        """Pages the tier could restore for ``prompt`` (no I/O, no LRU
        touch beyond recency) — the admission planner's tier-pressure
        signal."""
        return len(self._match_chain(prompt))

    # -- prefetch (issue half) ----------------------------------------------

    def issue_prefetch(self, prompt: List[int], ctx=None) -> int:
        """Start async preads for every NVMe-resident page of the
        prompt's chain — fire-and-forget at ``submit`` time so the bytes
        climb to DRAM while the request waits in admission. Returns
        preads issued (0 for an all-DRAM chain: nothing to do). ``ctx``
        (the request's TraceContext) stamps the issue into the request's
        distributed trace."""
        issued = 0
        for entry in self._match_chain(prompt):
            if entry.path is None or entry.key in self._inflight:
                continue
            buf = np.empty(entry.file_bytes, np.uint8)
            self.aio.pread(entry.path, buf, 0)
            self._inflight[entry.key] = buf
            issued += 1
        if issued:
            self.counters["prefetch_issued"] += issued
            _count("kvtier/prefetch_issued", issued,
                   help="NVMe tier preads issued ahead of admission")
            if ctx is not None:
                try:
                    from deepspeed_tpu.telemetry.reqtrace import reqtrace
                    reqtrace.instant("kvtier/prefetch", ctx,
                                     issued=issued)
                except Exception:                    # noqa: BLE001
                    pass
        return issued

    # -- adopt (complete half) ----------------------------------------------

    def _load(self, entry: _TierEntry) -> Dict[str, np.ndarray]:
        """Entry → decoded ``export_pages`` payload, CRC-verified at
        whichever level it lives. Raises :class:`TornSpill`."""
        if entry.bundle is not None:
            if entry.bundle.checksum != entry.checksum or \
                    not verify_bundle(entry.bundle):
                raise TornSpill(f"DRAM bundle for {len(entry.key)}-token "
                                f"prefix failed verification")
            return _decode(entry.bundle.pages, entry.meta)
        buf = self._inflight.pop(entry.key, None)
        if buf is not None:
            raw = buf.tobytes()
        else:
            raw = pread_retry(entry.path, size=entry.file_bytes)
        header, payload = _parse_spill(raw)
        if int(header["crc"]) != entry.checksum:
            raise TornSpill("spill file does not match the tier index "
                            "(stale or swapped file)")
        return _decode(payload, entry.meta)

    def _fallback(self, kind: str, prompt_len: int, ctx=None) -> None:
        """One torn/stale fault handled: the returning conversation will
        re-prefill the uncovered suffix instead. Counts the fallback and
        closes the chaos ledger (one recovery per injected fault). With
        ``ctx``, additionally flags the request's trace interesting —
        kvtier fallbacks are tail-retention causes."""
        self.counters["fallback_reprefills"] += 1
        _count("kvtier/fallback_reprefills",
               help="tier adoptions abandoned for a re-prefill")
        _event("kvtier_fallback", cause=kind, prompt_len=prompt_len)
        record_recovery("kvtier_reprefill", cause=kind,
                        prompt_len=prompt_len)
        if ctx is not None:
            try:
                from deepspeed_tpu.telemetry.reqtrace import reqtrace
                reqtrace.flag(ctx, "kvtier_fallback")
                reqtrace.instant("kvtier/fallback", ctx, cause=kind)
            except Exception:                        # noqa: BLE001
                pass

    def adopt(self, prompt: List[int], cache, ctx=None) -> int:
        """Restore the prompt's tier chain into the arena + radix cache.
        Returns pages the cache now additionally holds (0 → nothing
        restored; the caller's normal prefill covers the rest). Pages
        leave the tier only once the cache owns them — a declined insert
        (page cap) keeps the entry for the next return. ``ctx`` stamps a
        ``kvtier/adopt`` span into the request's distributed trace."""
        t0 = time.monotonic()
        added = self._adopt(prompt, cache, ctx=ctx)
        if ctx is not None and added:
            try:
                from deepspeed_tpu.telemetry.reqtrace import reqtrace
                reqtrace.complete("kvtier/adopt", ctx, t0,
                                  time.monotonic(), pages=added)
            except Exception:                        # noqa: BLE001
                pass
        return added

    def _adopt(self, prompt: List[int], cache, ctx=None) -> int:
        chain = self._match_chain(prompt)
        if not chain:
            if self._entries:
                self.counters["misses"] += 1
                _count("kvtier/misses",
                       help="returning prompts with no tier coverage")
            # advisory=False: a due kvtier fault stays pending for an
            # adopt that actually has a chain to act on
            fault_injector.fire("kvtier", serving_step=self._ops,
                                advisory=False)
            return 0
        self._ops += 1
        advisories = fault_injector.fire("kvtier", serving_step=self._ops,
                                         advisory=True)
        if "kvtier_torn_spill" in advisories:
            # tear the chain root: CRC verification below must catch it
            chain[0].checksum ^= 0x1
            if chain[0].bundle is not None:
                chain[0].bundle.checksum = chain[0].checksum
        if "kvtier_stale_adopt" in advisories:
            # the whole chain is stale by the time we adopt: drop it and
            # force the re-prefill path
            n = len(chain)
            self._drop_subtree(chain[0].key)
            self.counters["stale_adopts"] += n
            _count("kvtier/stale_adopts", n,
                   help="tier entries dropped as stale at adoption")
            self._fallback("kvtier_stale_adopt", len(prompt), ctx=ctx)
            self._publish()
            return 0
        if self._inflight:
            self.aio.drain()
        payloads: List[Dict[str, np.ndarray]] = []
        adopted: List[_TierEntry] = []
        for entry in chain:
            try:
                payloads.append(self._load(entry))
                adopted.append(entry)
            except (TornSpill, OSError) as e:
                # the chain breaks here: deeper pages are orphans
                self.counters["torn_spills"] += 1
                _count("kvtier/torn_spills",
                       help="tier entries lost to torn spills (CRC)")
                self._drop_subtree(entry.key)
                self._fallback("kvtier_torn_spill", len(prompt), ctx=ctx)
                if not isinstance(e, TornSpill):
                    self._drop(entry, reason="io_error")
                break
        if not adopted:
            self.counters["misses"] += 1
            self._publish()
            return 0
        alloc = self.engine.state.allocator
        if len(adopted) > alloc.free_blocks:
            cache.evict(len(adopted) - alloc.free_blocks)
        while adopted and len(adopted) > alloc.free_blocks:
            adopted.pop()                   # trim chain tail under pressure
            payloads.pop()
        if not adopted:
            self.counters["misses"] += 1
            return 0
        pages = {k: np.concatenate([p[k] for p in payloads], axis=2)
                 for k in payloads[0]}
        tokens = list(adopted[-1].key)
        blocks = alloc.allocate(len(adopted))
        try:
            self.engine.import_pages(pages, blocks)
            added = cache.insert(tokens, blocks)
        finally:
            alloc.free(blocks)
        nbytes = sum(int(p[k].nbytes) for p in payloads for k in p)
        if added > 0:
            # the cache kept (at least the leading) pages: their tier
            # copies are now redundant — and would go stale the moment
            # the owner decodes into the partial page
            for entry in adopted[:added] if added < len(adopted) \
                    else adopted:
                self._drop(entry)
            self.counters["adopts"] += added
            self.counters["hits"] += 1
            self.counters["bytes_adopted"] += nbytes
            _count("kvtier/adopts", added,
                   help="tier pages restored into the radix cache")
            _count("kvtier/hits", help="returning prompts warm-resumed "
                                       "from the tier")
            _count("kvtier/bytes_adopted", nbytes,
                   help="bytes restored from the host tier")
            _event("kvtier_adopt", pages=added, bytes=nbytes,
                   prompt_len=len(prompt))
        else:
            self.counters["misses"] += 1
            _count("kvtier/misses",
                   help="returning prompts with no tier coverage")
        self._publish()
        return added

    # -- invalidation (stale protection) ------------------------------------

    def invalidate(self, tokens: List[int]) -> int:
        """Drop every tier entry reachable through ``tokens``' first
        chunk — mirrors ``PrefixCache.invalidate``: after an engine
        fault the tier's copies of the suspect prefix are exactly as
        poisonous as the cache's, and a later warm resume from them
        would be the ``kvtier_stale_adopt`` failure for real."""
        tokens = [int(t) for t in tokens]
        bs = self.block_size
        n = 0
        if len(tokens) >= bs:
            n += self._drop_subtree(tuple(tokens[:bs]))
        for key in [k for k in list(self._entries)
                    if len(k) < bs and tuple(tokens[:len(k)]) == k]:
            self._drop(self._entries[key])
            n += 1
        if n:
            self.counters["invalidated"] += n
            _count("kvtier/invalidated", n,
                   help="tier entries dropped by fault invalidation")
            self._publish()
        return n

    # -- accounting ---------------------------------------------------------

    @property
    def dram_pages(self) -> int:
        return sum(1 for e in self._entries.values()
                   if e.bundle is not None)

    @property
    def nvme_pages(self) -> int:
        return sum(1 for e in self._entries.values() if e.path is not None)

    @property
    def total_pages(self) -> int:
        return len(self._entries)

    def _publish(self) -> None:
        try:
            from deepspeed_tpu import telemetry
            g = telemetry.registry.gauge
            g("kvtier/dram_pages",
              help="tier pages resident in host DRAM").set(self.dram_pages)
            g("kvtier/dram_bytes",
              help="host-DRAM arena bytes in use").set(self._dram_used)
            g("kvtier/nvme_pages",
              help="tier pages resident on NVMe").set(self.nvme_pages)
            g("kvtier/nvme_bytes",
              help="NVMe tier bytes in use").set(self._nvme_used)
        except Exception:                            # noqa: BLE001
            pass

    def stats(self) -> Dict[str, int]:
        out = dict(self.counters)
        out.update(dram_pages=self.dram_pages, nvme_pages=self.nvme_pages,
                   dram_bytes=self._dram_used, nvme_bytes=self._nvme_used,
                   total_pages=self.total_pages)
        return out

    def close(self) -> None:
        """Drain in-flight preads and release buffers. Spill files stay
        on disk only while indexed; a closed tier clears its index (a
        fresh process can't trust another's arena geometry anyway)."""
        if self._inflight:
            self.aio.drain()
            self._inflight.clear()
        for entry in list(self._entries.values()):
            self._drop(entry)
        self._publish()
