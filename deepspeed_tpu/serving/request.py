"""Request lifecycle objects for the serving frontend.

Reference: mii/batching/data_classes.py (Request/RequestBatch) — there a
request carries prompt tensors plus generation bookkeeping through the
ragged batch loop; here it additionally carries SLO fields (priority,
deadline) and a cancellation flag that the frontend honors between engine
steps, plus an optional per-token stream callback.
"""

import enum
import itertools
from dataclasses import dataclass, field
from typing import Callable, List, Optional


class RequestState(enum.Enum):
    QUEUED = "queued"        # admitted to the queue, not yet scheduled
    RUNNING = "running"      # owns a uid + KV pages in the engine
    FINISHED = "finished"    # produced max_new_tokens (or hit a stop)
    CANCELLED = "cancelled"  # user cancel honored
    SHED = "shed"            # dropped past-deadline to protect the batch
    REJECTED = "rejected"    # never admitted (queue/KV backpressure)


_uid_counter = itertools.count()


@dataclass
class Request:
    """One generation request.

    ``priority``: higher value is served first (ties FIFO). ``deadline``:
    absolute timestamp on the frontend's clock (``time.monotonic``); a
    queued request past its deadline is shed, never silently run late.
    ``stream_cb`` is invoked with each generated token id as soon as the
    frontend observes it (same thread as the engine loop — keep it cheap).
    ``eos_token_id`` retires the request early when sampled — honored
    ON DEVICE inside decode megasteps (the row stops writing KV
    mid-window) and host-side on the stepwise path.
    """
    prompt: List[int]
    max_new_tokens: int = 16
    priority: int = 0
    deadline: Optional[float] = None
    stream_cb: Optional[Callable[[int], None]] = None
    eos_token_id: Optional[int] = None

    uid: int = field(default_factory=lambda: next(_uid_counter))
    state: RequestState = RequestState.QUEUED
    finish_reason: Optional[str] = None
    tokens_out: List[int] = field(default_factory=list)

    # SLO accounting, stamped by the frontend (monotonic-clock seconds)
    enqueue_ts: Optional[float] = None
    schedule_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None

    # prefix-cache accounting
    cached_tokens: int = 0   # prompt tokens served from the prefix cache

    #: engine-fault recovery accounting: times this request was requeued
    #: after an engine step failed under it. The frontend's retry budget
    #: caps it; an exhausted budget finishes the request with reason
    #: ``"error"`` (streamed to the client, never a hang).
    retries: int = 0

    #: distributed-trace identity (:class:`~deepspeed_tpu.telemetry.
    #: reqtrace.TraceContext`): minted by the frontend when it is the
    #: entry point, or passed in by the router so this leg's spans join
    #: the fleet-wide trace. None when request tracing is disabled.
    trace: Optional[object] = field(default=None, repr=False)

    _cancel: bool = field(default=False, repr=False)

    def cancel(self) -> None:
        """Request cancellation; honored at the next frontend step."""
        self._cancel = True

    @property
    def cancelled(self) -> bool:
        return self._cancel

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.SHED, RequestState.REJECTED)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    @property
    def ttft(self) -> Optional[float]:
        if self.enqueue_ts is None or self.first_token_ts is None:
            return None
        return self.first_token_ts - self.enqueue_ts

    @property
    def tpot(self) -> Optional[float]:
        """Mean time per output token after the first."""
        if (self.first_token_ts is None or self.finish_ts is None
                or len(self.tokens_out) < 2):
            return None
        return (self.finish_ts - self.first_token_ts) / \
            (len(self.tokens_out) - 1)
