"""Radix prefix cache: token prefixes → ref-counted KV pages.

Real serving traffic is dominated by shared prompts (system prompts,
few-shot prefixes); this trie maps page-sized token chunks to physical KV
pages so a request whose prompt shares a cached prefix skips prefill for
the shared pages entirely (the single biggest serving-throughput lever —
SGLang's RadixAttention, vLLM automatic prefix caching).

Granularity is one KV page (``block_size`` tokens): a trie edge is the
exact token chunk that filled a page. FULL pages are immutable once their
owner's prefill wrote them, so a hit aliases them in the new sequence's
page table (``BlockedAllocator.incref``). The last PARTIAL page of a
cached prompt is also stored (with its token span); its bytes beyond the
labeled span may later be overwritten by the inserter's decode, so a hit
on it is handed out copy-on-write (``engine.cow_block``) — the copy's
labeled span is valid prompt KV and everything past it is junk the
attention masks (``kpos < start``) can never read.

The cache is an OWNER of every page it holds (one ref each); eviction
drops that ref, and the page returns to the pool only when no live
sequence still shares it.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class _Node:
    __slots__ = ("chunk", "block", "children", "partials", "parent",
                 "last_used")

    def __init__(self, chunk: Tuple[int, ...], block: Optional[int],
                 parent: "Optional[_Node]"):
        self.chunk = chunk
        self.block = block            # physical page id (None for root)
        self.children: Dict[Tuple[int, ...], _Node] = {}
        # partial last pages: token-span → (block, last_used clock)
        self.partials: Dict[Tuple[int, ...], List[int]] = {}
        self.parent = parent
        self.last_used = 0


@dataclass
class PrefixMatch:
    """Result of a lookup. ``full_blocks`` alias as-is; ``partial_block``
    (if any) must be handed out copy-on-write. ``matched`` counts tokens
    covered (``len(full_blocks) * block_size + partial_len``)."""
    full_blocks: List[int] = field(default_factory=list)
    partial_block: Optional[int] = None
    partial_len: int = 0

    def matched(self, block_size: int) -> int:
        return len(self.full_blocks) * block_size + self.partial_len


class PrefixCache:

    def __init__(self, allocator, max_pages: Optional[int] = None,
                 tier=None):
        self.allocator = allocator
        self.block_size = allocator.block_size
        #: soft page cap; None → up to half the arena
        self.max_pages = (max_pages if max_pages is not None
                          else max(1, allocator.num_blocks // 2))
        #: optional vertical page tier (serving/kvtier.KVTier): eviction
        #: captures the page host-side BEFORE the allocator ref drops
        self.tier = tier
        self._root = _Node((), None, None)
        self._clock = 0
        self.pages_cached = 0
        self.lookups = 0
        self.hits = 0
        self.tokens_hit = 0
        #: eviction accounting, kept separately so a page that moved to
        #: the tier AND returned to the pool is never counted twice as
        #: "freed": ``pages_released`` counts pages the allocator
        #: actually reclaimed (refcount hit zero — free_blocks grew by
        #: exactly this much); ``pages_tiered`` counts pages whose KV
        #: entered the tier. A shared CoW prefix can be tiered while a
        #: live sequence keeps the physical page (tiered +1, released +0).
        self.pages_released = 0
        self.pages_tiered = 0

    # -- lookup ------------------------------------------------------------

    def match(self, tokens: List[int]) -> PrefixMatch:
        """Longest cached prefix of ``tokens`` at page granularity."""
        self.lookups += 1
        self._clock += 1
        bs = self.block_size
        node = self._root
        out = PrefixMatch()
        i = 0
        while i + bs <= len(tokens):
            key = tuple(tokens[i:i + bs])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._clock
            out.full_blocks.append(child.block)
            node = child
            i += bs
        # longest partial continuation under the deepest full node
        best: Optional[Tuple[Tuple[int, ...], List[int]]] = None
        for span, rec in node.partials.items():
            if len(span) <= len(tokens) - i and \
                    tuple(tokens[i:i + len(span)]) == span:
                if best is None or len(span) > len(best[0]):
                    best = (span, rec)
        if best is not None:
            best[1][1] = self._clock
            out.partial_block = best[1][0]
            out.partial_len = len(best[0])
        if out.matched(bs) > 0:
            self.hits += 1
            self.tokens_hit += out.matched(bs)
        return out

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    # -- insert ------------------------------------------------------------

    def insert(self, tokens: List[int], blocks: List[int]) -> int:
        """Cache the pages covering ``tokens`` (a fully-prefilled prompt
        whose KV lives in ``blocks``). Increfs every NEWLY cached page;
        already-cached chunks are left alone. Returns pages added."""
        bs = self.block_size
        self._clock += 1
        node = self._root
        added = 0
        n_full = len(tokens) // bs
        path = set()
        for i in range(n_full):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                # never evict a page on the path being inserted — the new
                # child would attach to a detached node and leak its ref
                if self.pages_cached >= self.max_pages and \
                        self.evict(1, exclude_blocks=path) == 0:
                    return added
                blk = blocks[i]
                self.allocator.incref([blk])
                child = _Node(key, blk, node)
                node.children[key] = child
                self.pages_cached += 1
                added += 1
            child.last_used = self._clock
            path.add(child.block)
            node = child
        rem = tokens[n_full * bs:]
        if rem and len(blocks) > n_full:
            span = tuple(rem)
            if span not in node.partials:
                if self.pages_cached >= self.max_pages and \
                        self.evict(1, exclude_blocks=path) == 0:
                    return added
                blk = blocks[n_full]
                self.allocator.incref([blk])
                node.partials[span] = [blk, self._clock]
                self.pages_cached += 1
                added += 1
            else:
                node.partials[span][1] = self._clock
        return added

    # -- eviction ----------------------------------------------------------

    def _token_path(self, node: _Node) -> List[int]:
        """Reconstruct the exact token prefix a trie node's page covers
        (root → node chunk concatenation) — the tier key for a captured
        page."""
        chunks: List[Tuple[int, ...]] = []
        while node is not None and node.parent is not None:
            chunks.append(node.chunk)
            node = node.parent
        return [t for chunk in reversed(chunks) for t in chunk]

    def _release(self, block: int, tokens: Optional[List[int]]) -> None:
        """Drop the cache's ref on one page, optionally capturing its KV
        into the tier first (the export must happen while the page is
        still live in the arena). Updates the split eviction accounting."""
        if self.tier is not None and tokens:
            if self.tier.capture(tokens, block):
                self.pages_tiered += 1
        self.pages_released += self.allocator.free([block])

    def _leaves(self, node: _Node, out: List[Tuple[int, object, object]]):
        for span, rec in node.partials.items():
            out.append((rec[1], node, span))
        for child in node.children.values():
            if not child.children and not child.partials:
                out.append((child.last_used, node, child))
            else:
                self._leaves(child, out)

    def evict(self, n_pages: int, exclude_blocks=()) -> int:
        """Drop the ``n_pages`` least-recently-used LEAF pages (inner trie
        pages are prefixes of live leaves and must outlive them);
        ``exclude_blocks`` protects pages an in-flight match/insert is
        about to hand out. Returns pages dropped; the allocator reclaims
        each page only once every sequence sharing it has also let go."""
        exclude = set(b for b in exclude_blocks if b is not None)
        dropped = 0
        while dropped < n_pages:
            leaves: List[Tuple[int, object, object]] = []
            self._leaves(self._root, leaves)
            leaves = [t for t in leaves
                      if (t[2].block if isinstance(t[2], _Node)
                          else t[1].partials[t[2]][0]) not in exclude]
            if not leaves:
                break
            leaves.sort(key=lambda t: t[0])
            _, parent, what = leaves[0]
            if isinstance(what, _Node):
                self._release(what.block, self._token_path(what))
                del parent.children[what.chunk]
            else:                           # partial span key
                self._release(parent.partials[what][0],
                              self._token_path(parent) + list(what))
                del parent.partials[what]
            self.pages_cached -= 1
            dropped += 1
        return dropped

    def _free_subtree(self, node: _Node) -> Tuple[int, int]:
        """Drop the cache's ref on every page below ``node`` (not
        ``node`` itself). Returns ``(dropped, released)``: refs this
        cache let go vs pages the ALLOCATOR actually reclaimed
        (refcount hit zero). The two must be reported separately —
        a page a live sequence still shares is dropped-but-not-released,
        and conflating them double-counts the pool. Fault path: pages
        are NEVER captured to the tier here (their KV is suspect)."""
        n = rel = 0
        for rec in node.partials.values():
            rel += self.allocator.free([rec[0]])
            n += 1
        node.partials.clear()
        for child in node.children.values():
            cn, crel = self._free_subtree(child)
            n += cn
            rel += crel
            rel += self.allocator.free([child.block])
            n += 1
        node.children.clear()
        return n, rel

    def invalidate(self, tokens: List[int]) -> int:
        """Drop every cached page reachable through ``tokens``' first
        chunk — the serving failure domain calls this when an engine
        fault may have left a request's KV suspect. A corrupt prefix
        page poisons every cached extension of it, so the whole subtree
        goes (over-invalidation only costs recompute; serving stale KV
        costs correctness). The tier's copies of the prefix are exactly
        as suspect, so they go too (and are never re-captured from
        here). Returns pages dropped; pages the allocator actually
        reclaimed accrue to ``pages_released``."""
        self._clock += 1
        dropped = 0
        root = self._root
        key = (tuple(tokens[:self.block_size])
               if len(tokens) >= self.block_size else None)
        child = root.children.get(key) if key is not None else None
        if child is not None:
            sub_n, sub_rel = self._free_subtree(child)
            dropped += sub_n
            self.pages_released += sub_rel
            self.pages_released += self.allocator.free([child.block])
            del root.children[key]
            dropped += 1
        for span in [s for s in list(root.partials)
                     if len(s) <= len(tokens)
                     and tuple(tokens[:len(s)]) == s]:
            self.pages_released += self.allocator.free(
                [root.partials[span][0]])
            del root.partials[span]
            dropped += 1
        self.pages_cached -= dropped
        if self.tier is not None:
            self.tier.invalidate(tokens)
        return dropped

    def owned_blocks(self) -> List[int]:
        """Every physical page id this cache holds a ref on (full trie
        pages + partial last pages). The handoff/accounting seam: a
        serialize→adopt→invalidate round trip must leave
        ``len(owned_blocks()) == pages_cached`` on both sides with no
        page double-counted."""
        out: List[int] = []

        def walk(node: _Node) -> None:
            for rec in node.partials.values():
                out.append(rec[0])
            for child in node.children.values():
                out.append(child.block)
                walk(child)

        walk(self._root)
        return out

    def evictable_pages(self) -> int:
        """Pages the cache could give back under arena pressure (all of
        them — eviction recurses leaf-inward)."""
        return self.pages_cached

    def clear(self) -> int:
        return self.evict(self.pages_cached)
