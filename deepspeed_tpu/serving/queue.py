"""Bounded admission queue with priority, deadline shedding, backpressure.

The reference frontends (MII / vLLM-style servers) queue without bound and
let latency blow up under overload; here admission is explicit: a full
queue REJECTS with a machine-readable reason rather than accepting work it
cannot serve inside its deadline, and queued work that has already missed
its deadline is shed before it can stall the running batch.
"""

from typing import List, Optional

from deepspeed_tpu.serving.request import Request, RequestState


class AdmissionError(RuntimeError):
    """Raised when a request cannot be admitted; ``reason`` is one of
    ``queue_full`` | ``kv_exhausted`` | ``too_long``."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"request rejected ({reason}): {detail}"
                         if detail else f"request rejected ({reason})")
        self.reason = reason


class AdmissionQueue:
    """FIFO within priority; bounded depth; deadline shedding.

    Not thread-safe by design — the frontend is a single-threaded pump
    (T3-style: host scheduling stays off the device critical path, and a
    lock-free queue would buy nothing single-threaded).
    """

    def __init__(self, max_depth: int = 128):
        self.max_depth = max_depth
        self._q: List[Request] = []
        self._seq = 0            # FIFO tiebreak within a priority class

    def __len__(self) -> int:
        return len(self._q)

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, req: Request, now: float) -> Optional[Request]:
        """Enqueue ``req``; returns the past-deadline victim shed to make
        room (None when the queue had space). The caller owns the
        victim's terminal accounting — it is already in state SHED with
        reason ``"deadline"``, but only the frontend can emit its finish
        and bump the shed counter."""
        victim = None
        if len(self._q) >= self.max_depth:
            # backpressure, not buffering: shed a past-deadline entry to
            # make room before rejecting live work
            victim = self._shed_one(now)
            if victim is None:
                req.state = RequestState.REJECTED
                req.finish_reason = "queue_full"
                raise AdmissionError(
                    "queue_full", f"depth {len(self._q)} == max_depth")
        req.enqueue_ts = now
        req.state = RequestState.QUEUED
        self._q.append(req)
        self._seq += 1
        return victim

    def _shed_one(self, now: float) -> Optional[Request]:
        """Shed the LOWEST-priority expired entry, if any."""
        expired = [r for r in self._q if r.expired(now)]
        if not expired:
            return None
        victim = min(expired, key=lambda r: r.priority)
        self._q.remove(victim)
        victim.state = RequestState.SHED
        victim.finish_reason = "deadline"
        return victim

    def shed_expired(self, now: float) -> List[Request]:
        """Drop every queued request already past its deadline."""
        shed = [r for r in self._q if r.expired(now)]
        for r in shed:
            self._q.remove(r)
            r.state = RequestState.SHED
            r.finish_reason = "deadline"
        return shed

    def pop_next(self, now: float) -> Optional[Request]:
        """Highest priority first, FIFO within a class; drops cancelled
        entries on the way."""
        while self._q:
            best_i = 0
            for i in range(1, len(self._q)):
                if self._q[i].priority > self._q[best_i].priority:
                    best_i = i
            req = self._q.pop(best_i)
            if req.cancelled:
                req.state = RequestState.CANCELLED
                req.finish_reason = "cancelled"
                continue
            return req
        return None

    def peek_all(self) -> List[Request]:
        return list(self._q)
