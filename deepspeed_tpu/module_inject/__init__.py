"""Module injection / AutoTP (reference: deepspeed/module_inject/)."""

from deepspeed_tpu.module_inject.auto_tp import (AutoTPPlanner, TPRule,
                                                 autotp_specs)

__all__ = ["AutoTPPlanner", "TPRule", "autotp_specs"]
