"""AutoTP — policy-driven tensor-parallel sharding for arbitrary pytrees.

Reference: ``module_inject/auto_tp.py:193`` (``AutoTP``: ``tp_parser``:285
walks an HF module graph classifying each Linear as row- or
column-parallel by name heuristics + architecture policies;
``_replace``:348 slices the weights). The torch version must physically
slice tensors per rank and swap modules for ``LinearAllreduce``; on TPU
the entire mechanism collapses to PRODUCING A PARTITIONSPEC PYTREE — the
'model' axis annotation IS the slicing, and XLA inserts the row-parallel
allreduce the reference hand-codes in ``LinearAllreduce``.

The classifier mirrors the reference's rules:

- **column-parallel** (shard the OUTPUT dim): q/k/v/qkv projections, MLP
  up/gate projections — names matching ``_COL_PATTERNS``;
- **row-parallel** (shard the INPUT dim; XLA adds the psum): attention
  output and MLP down projections — ``_ROW_PATTERNS``;
- **vocab-parallel**: embedding / lm_head tables;
- everything else replicates (norms, biases of row-parallel layers).

Works on any pytree whose leaf paths carry transformer-ish names (an HF
checkpoint loaded by models/hf_loader.py, an in-tree params tree, or a
custom model) — the analogue of the reference supporting any HF
architecture through policy classes.
"""

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.pytree import path_key

Pytree = Any

#: reference auto_tp.py tp_parser name heuristics (lowercased substrings)
_COL_PATTERNS = ("q_proj", "k_proj", "v_proj", "qkv", "wq", "wk", "wv",
                 "gate_proj", "up_proj", "wi", "wg", "w1", "w3",
                 "fc1", "fc_in", "dense_h_to_4h", "query", "key", "value")
_ROW_PATTERNS = ("o_proj", "out_proj", "wo", "down_proj", "w2", "fc2",
                 "fc_out", "dense_4h_to_h", "attention/dense", "proj_out")
_VOCAB_PATTERNS = ("embed", "wte", "lm_head", "word_embeddings")
_SKIP_PATTERNS = ("norm", "ln", "bias", "rotary", "scale")


@dataclass
class TPRule:
    """One classification outcome for a leaf."""
    kind: str          #: 'column' | 'row' | 'vocab' | 'replicate'
    dim: Optional[int] = None   #: which dim gets the 'model' axis


class AutoTPPlanner:
    """tp_parser + _replace as a spec planner (reference AutoTP)."""

    def __init__(self, tp_axis: str = "model",
                 extra_column: Sequence[str] = (),
                 extra_row: Sequence[str] = ()):
        self.tp_axis = tp_axis
        self.col = tuple(p.lower() for p in _COL_PATTERNS) + \
            tuple(p.lower() for p in extra_column)
        self.row = tuple(p.lower() for p in _ROW_PATTERNS) + \
            tuple(p.lower() for p in extra_row)

    # -- classification (reference tp_parser:285) --------------------------

    def classify(self, path: str, leaf) -> TPRule:
        name = path.lower()
        nd = np.ndim(leaf)
        if nd < 2 or not jax.numpy.issubdtype(
                jax.numpy.asarray(leaf).dtype
                if not hasattr(leaf, "dtype") else leaf.dtype,
                jax.numpy.floating):
            return TPRule("replicate")
        if any(p in name for p in _SKIP_PATTERNS) and \
                not any(p in name for p in self.col + self.row):
            return TPRule("replicate")
        if any(p in name for p in self.row):
            # row-parallel: shard the INPUT (second-to-last) dim
            return TPRule("row", dim=nd - 2)
        if any(p in name for p in self.col):
            # column-parallel: shard the OUTPUT (last) dim
            return TPRule("column", dim=nd - 1)
        if any(p in name for p in _VOCAB_PATTERNS):
            # vocab dim = the bigger of the trailing two dims
            shape = np.shape(leaf)
            return TPRule("vocab",
                          dim=nd - 2 if shape[nd - 2] >= shape[nd - 1]
                          else nd - 1)
        return TPRule("replicate")

    # -- spec construction (reference _replace:348) ------------------------

    def build_specs(self, params: Pytree, tp_size: int = 1,
                    fsdp_axes: Optional[Tuple[str, ...]] = None
                    ) -> Pytree:
        """PartitionSpec pytree for ``params``. Leaves whose sharded dim
        doesn't divide by ``tp_size`` fall back to replication WITH a
        warning (VERDICT: silent fallbacks hide mis-sized meshes)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = []
        counts = {"column": 0, "row": 0, "vocab": 0, "replicate": 0}
        for path, leaf in flat:
            key = path_key(path)
            rule = self.classify(key, leaf)
            nd = np.ndim(leaf)
            entries: List[Any] = [None] * nd
            if rule.dim is not None and tp_size > 1:
                if np.shape(leaf)[rule.dim] % tp_size:
                    logger.warning(
                        f"AutoTP: '{key}' dim {rule.dim} size "
                        f"{np.shape(leaf)[rule.dim]} not divisible by "
                        f"tp={tp_size}; replicating")
                    rule = TPRule("replicate")
                else:
                    entries[rule.dim] = self.tp_axis
            if fsdp_axes and nd >= 2:
                # FSDP on a dim the TP axis didn't take
                for d in range(nd):
                    if entries[d] is None:
                        entries[d] = fsdp_axes
                        break
            counts[rule.kind] += 1
            specs.append(P(*entries) if any(e is not None
                                            for e in entries) else P())
        log_dist(f"AutoTP plan: {counts['column']} column, "
                 f"{counts['row']} row, {counts['vocab']} vocab, "
                 f"{counts['replicate']} replicated")
        return jax.tree_util.tree_unflatten(treedef, specs)


def autotp_specs(params: Pytree, tp_size: int,
                 fsdp_axes: Optional[Tuple[str, ...]] = None,
                 **kw) -> Pytree:
    """One-call AutoTP (reference module_inject.replace_module entry)."""
    return AutoTPPlanner(**kw).build_specs(params, tp_size,
                                           fsdp_axes=fsdp_axes)
