"""OptimizedLinear — LoRA adapters over a (optionally quantized) frozen base.

Reference: ``deepspeed/linear/optimized_linear.py:18`` (``OptimizedLinear``
dispatcher, ``LoRAOptimizedLinear``:76) and ``linear/quantization.py:18,129``
(``QuantizedParameter``/``QuantizedLinear``). The reference subclasses
nn.Linear, shards the frozen base across ranks, and dequantizes in forward;
here the layer is a pure function over a params pytree:

- ``base`` is FROZEN (``lax.stop_gradient``) and optionally stored
  block-quantized — symmetric int8 or block-scaled fp8-e4m3
  (``QuantizationConfig.q_dtype``; ops/quantizer.py) — 4× less HBM than
  fp32, 2× less than bf16; dequantize fuses into the matmul epilogue
  under jit. ``mantissa_bits`` is a parity field only: the fp8 path is
  e4m3 (fp6 has no native TPU dtype).
- ``lora_a [r, in]`` / ``lora_b [out, r]`` are the trainable adapters;
  output = x @ baseᵀ + (alpha/r) · x @ lora_aᵀ @ lora_bᵀ.
- sharding: the base weight's PartitionSpec puts the out-dim on the fsdp
  axis when ``base_weight_sharding > 1`` (the reference's sharded frozen
  base); adapters replicate (they're tiny).

``merge_lora`` folds the adapters into the base (the reference hybrid
engine's LoRA fuse, runtime/hybrid_engine.py:132) for serving.
"""

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.ops.quantizer import (dequantize_blocks,
                                          dequantize_fp8_blocks,
                                          quantize_blocks,
                                          quantize_fp8_blocks)

Params = Dict[str, Any]


def init_optimized_linear(rng: jax.Array, in_features: int,
                          out_features: int,
                          lora: Optional[LoRAConfig] = None,
                          quant: Optional[QuantizationConfig] = None,
                          base: Optional[jax.Array] = None,
                          dtype=jnp.float32) -> Params:
    """Build the params pytree. ``base`` (a pretrained [out, in] weight)
    may be passed in; otherwise kaiming-init."""
    ra, rb = jax.random.split(rng)
    if base is None:
        base = jax.random.normal(ra, (out_features, in_features), dtype) \
            * (1.0 / math.sqrt(in_features))
    base = base.astype(dtype)
    p: Params = {}
    if quant is not None:
        if quant.q_bits != 8:
            raise ValueError(
                "OptimizedLinear quantized base supports 8-bit storage "
                "(q_dtype 'int8' or 'fp8'); use ops/quantizer directly "
                "for int4")
        if quant.q_dtype not in ("int8", "fp8"):
            raise ValueError(f"unknown q_dtype '{quant.q_dtype}'")
        total = out_features * in_features
        if total % quant.group_size:
            raise ValueError(
                f"out*in ({total}) must be divisible by group_size "
                f"({quant.group_size})")
        if quant.q_dtype == "fp8":
            q, s = quantize_fp8_blocks(base.reshape(-1),
                                       block=quant.group_size)
        else:
            q, s, _ = quantize_blocks(base.reshape(-1),
                                      block=quant.group_size, bits=8)
        # natural [out, in] so shape metadata lives in the array; group
        # size is recoverable as q.size // scales.size
        p["base_q"] = q.reshape(out_features, in_features)
        p["base_scales"] = s
    else:
        p["base"] = base
    if lora is not None and lora.lora_r > 0:
        r = lora.lora_r
        # reference init: A ~ kaiming, B = 0 (adapter starts as identity)
        p["lora_a"] = jax.random.normal(rb, (r, in_features), dtype) \
            * (1.0 / math.sqrt(in_features))
        p["lora_b"] = jnp.zeros((out_features, r), dtype)
    return p


def _materialize_base(p: Params, quant: Optional[QuantizationConfig],
                      dtype) -> jax.Array:
    if "base" in p:
        return p["base"].astype(dtype)
    q = p["base_q"]
    group = q.size // p["base_scales"].size
    if q.dtype == jnp.float8_e4m3fn:
        flat = dequantize_fp8_blocks(q.reshape(-1), p["base_scales"],
                                     block=group, dtype=dtype)
    else:
        flat = dequantize_blocks(q.reshape(-1), p["base_scales"],
                                 block=group, bits=8, dtype=dtype)
    return flat.reshape(q.shape)


def apply_optimized_linear(p: Params, x: jax.Array,
                           lora: Optional[LoRAConfig] = None,
                           quant: Optional[QuantizationConfig] = None
                           ) -> jax.Array:
    """x: [..., in] → [..., out]. Base path is stop-gradiented — only the
    adapters train (reference: base requires_grad=False)."""
    w = _materialize_base(p, quant, x.dtype)
    out = x @ lax.stop_gradient(w).T
    if "lora_a" in p:
        r = p["lora_a"].shape[0]
        alpha = lora.lora_alpha if lora is not None else float(r)
        scaling = alpha / r
        out = out + scaling * ((x @ p["lora_a"].T) @ p["lora_b"].T)
    return out


def lora_partition_specs(p: Params, lora: Optional[LoRAConfig] = None
                         ) -> Params:
    """PartitionSpec pytree: shard the big frozen base over the fsdp axis
    when configured; adapters replicate."""
    shard = lora is not None and lora.base_weight_sharding > 1
    fsdp = ("data", "data_inner", "expert") if shard else None
    specs: Params = {}
    for k, v in p.items():
        if k in ("base", "base_q"):
            specs[k] = P(fsdp, None)
        elif k == "base_scales":
            specs[k] = P(fsdp)
        else:
            specs[k] = P(*([None] * jnp.ndim(v)))
    return specs


def trainable_mask(p: Params) -> Params:
    """True for leaves the optimizer should update (adapters only when
    LoRA is present — the reference freezes the base)."""
    has_lora = "lora_a" in p
    return {k: (k.startswith("lora_") if has_lora else True) for k in p}


def split_params(p: Params) -> Tuple[Params, Params]:
    """(trainable, frozen) split for ``jax.grad``: int8/frozen leaves can't
    be grad inputs, so differentiate the trainable dict with the frozen
    dict closed over::

        trainable, frozen = split_params(p)
        grads = jax.grad(lambda tr: loss(merge_params(tr, frozen)))(trainable)
    """
    mask = trainable_mask(p)
    return ({k: v for k, v in p.items() if mask[k]},
            {k: v for k, v in p.items() if not mask[k]})


def merge_params(trainable: Params, frozen: Params) -> Params:
    return {**frozen, **trainable}


def merge_lora(p: Params, lora: LoRAConfig,
               quant: Optional[QuantizationConfig] = None) -> jax.Array:
    """Fold adapters into a dense [out, in] weight (hybrid-engine LoRA
    fuse, reference runtime/hybrid_engine.py:132-146)."""
    w = _materialize_base(p, quant, jnp.float32)
    if "lora_a" in p:
        scaling = lora.lora_alpha / lora.lora_r
        w = w + scaling * (p["lora_b"].astype(jnp.float32) @
                           p["lora_a"].astype(jnp.float32))
    return w
