"""OptimizedLinear — LoRA + quantized-base linear (reference:
deepspeed/linear/optimized_linear.py:18)."""

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.optimized_linear import (apply_optimized_linear,
                                                   merge_params,
                                                   split_params,
                                                   init_optimized_linear,
                                                   lora_partition_specs,
                                                   merge_lora,
                                                   trainable_mask)

__all__ = ["LoRAConfig", "QuantizationConfig", "init_optimized_linear",
           "apply_optimized_linear", "lora_partition_specs", "merge_lora",
           "trainable_mask", "split_params", "merge_params"]
