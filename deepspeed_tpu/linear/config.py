"""Configs for OptimizedLinear (reference: deepspeed/linear/config.py:13
``LoRAConfig``, :39 ``QuantizationConfig``)."""

from dataclasses import dataclass


@dataclass
class LoRAConfig:
    """Reference linear/config.py:13.

    ``base_weight_sharding``: how many ways the FROZEN base weight is
    sharded over the 'data' (fsdp) axis — the reference shards the base
    across ranks and gathers on use; on TPU the partition spec does the
    same through XLA.
    """
    lora_r: int = 8
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    #: delay LoRA grad sync until this many tokens (parity knob; XLA
    #: handles sync placement — kept for config compat)
    offload_ratio: float = 0.0


@dataclass
class QuantizationConfig:
    """Reference linear/config.py:39: frozen-base weight quantization."""
    q_bits: int = 8
    group_size: int = 256
    #: quantize only the frozen base (LoRA adapters stay high precision)
    mantissa_bits: int = 3   # parity field (fp6 path in the reference)
    #: 'int8' (symmetric block quant) or 'fp8' (block-scaled e4m3 — the
    #: reference fp_quantizer / FP6-LLM path, native dtype on TPU)
    q_dtype: str = "int8"
