"""Per-host launch agent.

Reference: ``launcher/launch.py:145`` (per-node agent: spawns one process
per local rank, exports RANK/WORLD_SIZE env, ``sigkill_handler`` kills
the tree on failure) + the elastic relaunch path (``--elastic_training``
in runner.py → DSElasticAgent). TPU translation: ONE worker process per
host (jax drives every local chip), so the agent's job is environment
setup, supervision, bounded restarts, and signal forwarding:

- exports the jax distributed rendezvous env
  (DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID — consumed by
  comm.init_distributed);
- runs the training command as a child process group;
- forwards SIGTERM (pod preemption) to the child so the in-process
  DSElasticAgent (elasticity/elastic_agent.py) can checkpoint;
- restarts the child up to ``max_restarts`` on nonzero exit (the
  torchelastic worker-group restart), backing off between attempts;
- exports ``DSTPU_HEARTBEAT_FILE`` so the worker's watchdog
  (telemetry/watchdog.py) stamps per-step heartbeats this host's
  operator — and ``dstpu-doctor`` — can read to name a straggler, and
  stamps agent-level status (started/exited/restarting) into the same
  file while no worker is alive.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


class LaunchAgent:
    """Supervise one per-host worker process (reference launch.py main)."""

    def __init__(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 0, restart_backoff_s: float = 5.0,
                 max_backoff_s: float = 60.0,
                 restart_window_s: float = 300.0,
                 heartbeat_file: Optional[str] = None):
        self.cmd = cmd
        self.env = {**os.environ, **(env or {})}
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.max_backoff_s = max_backoff_s
        #: rolling restart budget: only restarts within the last
        #: ``restart_window_s`` seconds count against ``max_restarts`` —
        #: a worker that dies once a day is healthy; one that dies
        #: max_restarts times in five minutes is crash-looping
        self.restart_window_s = restart_window_s
        self._restart_times: List[float] = []
        self.heartbeat_file = heartbeat_file or \
            self.env.get("DSTPU_HEARTBEAT_FILE")
        if self.heartbeat_file:
            # the worker's watchdog picks this up and takes over stamping
            self.env["DSTPU_HEARTBEAT_FILE"] = self.heartbeat_file
        self._child: Optional[subprocess.Popen] = None
        self._terminating = False

    def _beat(self, phase: str, **extra) -> None:
        """Agent-level heartbeat (atomic write, best effort). The worker's
        watchdog overwrites the same file with per-step beats once it is
        up; agent beats cover the gaps (spawn, restart backoff, exit)."""
        if not self.heartbeat_file:
            return
        try:
            doc = {"hostname": socket.gethostname(), "pid": os.getpid(),
                   "agent": True, "phase": phase, "ts": time.time(),
                   **extra}
            parent = os.path.dirname(os.path.abspath(self.heartbeat_file))
            os.makedirs(parent, exist_ok=True)
            tmp = f"{self.heartbeat_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.heartbeat_file)
        except Exception:
            pass

    def _forward(self, signum, _frame) -> None:
        """SIGTERM/SIGINT → forward to the child's process group so the
        worker can checkpoint (reference sigkill_handler — but graceful
        first: preemption gives a drain window)."""
        self._terminating = True
        if self._child and self._child.poll() is None:
            logger.warning(
                f"launch agent: forwarding {signal.Signals(signum).name} "
                f"to worker pid {self._child.pid}")
            try:
                os.killpg(os.getpgid(self._child.pid), signum)
            except ProcessLookupError:
                pass

    def run(self) -> int:
        prev_term = signal.signal(signal.SIGTERM, self._forward)
        prev_int = signal.signal(signal.SIGINT, self._forward)
        try:
            attempt = 0
            while True:
                # chaos hook: lets a fault plan target the supervisor
                # itself (a launcher-scoped hang or preempt)
                from deepspeed_tpu.resilience.faults import fault_injector
                fault_injector.fire("launcher")
                log_dist(f"launch agent: starting worker "
                         f"(attempt {attempt + 1}): "
                         f"{' '.join(self.cmd)}")
                self._child = subprocess.Popen(
                    self.cmd, env=self.env, start_new_session=True)
                self._beat("worker_started", worker_pid=self._child.pid,
                           attempt=attempt)
                rc = self._child.wait()
                self._beat("worker_exited", rc=rc, attempt=attempt)
                if rc == 0 or self._terminating:
                    return rc
                now = time.monotonic()
                self._restart_times = [
                    t for t in self._restart_times
                    if now - t <= self.restart_window_s]
                if len(self._restart_times) >= self.max_restarts:
                    logger.error(
                        f"launch agent: worker failed (rc={rc}) with "
                        f"{len(self._restart_times)} restarts already in "
                        f"the last {self.restart_window_s:.0f}s "
                        f"(budget {self.max_restarts}); giving up")
                    self._beat("crash_loop", rc=rc,
                               restarts_in_window=len(self._restart_times),
                               attempt=attempt)
                    return rc
                self._restart_times.append(now)
                attempt += 1
                delay = min(
                    self.restart_backoff_s *
                    (2 ** (len(self._restart_times) - 1)),
                    self.max_backoff_s)
                logger.warning(
                    f"launch agent: worker rc={rc}; restart "
                    f"{len(self._restart_times)}/{self.max_restarts} "
                    f"(window {self.restart_window_s:.0f}s) in "
                    f"{delay:.1f}s")
                # doctor reads this phase + count to name a crash-looping
                # host from the heartbeat alone
                self._beat("restart_backoff", rc=rc, backoff_s=delay,
                           restarts_in_window=len(self._restart_times),
                           attempt=attempt)
                time.sleep(delay)
                if self._terminating:
                    # SIGTERM landed during the backoff (preemption):
                    # spawning a fresh worker that never saw the signal
                    # would lose the checkpoint window
                    logger.warning("launch agent: termination requested "
                                   "during backoff; not restarting")
                    return rc
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m deepspeed_tpu.launcher.agent -- cmd args...``
    with rendezvous env passed through (spawned over ssh by
    launcher/runner.py on each host)."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-restarts", type=int,
                    default=int(os.environ.get("DSTPU_MAX_RESTARTS", 0)))
    ap.add_argument("--heartbeat-file", default=None,
                    help="per-host heartbeat JSON for dstpu-doctor "
                         "straggler naming (default: env "
                         "DSTPU_HEARTBEAT_FILE)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("usage: agent.py [--max-restarts N] [--heartbeat-file F] "
              "-- prog args...", file=sys.stderr)
        return 2
    return LaunchAgent(cmd, max_restarts=args.max_restarts,
                       heartbeat_file=args.heartbeat_file).run()


if __name__ == "__main__":
    sys.exit(main())
