"""Per-host launch agent.

Reference: ``launcher/launch.py:145`` (per-node agent: spawns one process
per local rank, exports RANK/WORLD_SIZE env, ``sigkill_handler`` kills
the tree on failure) + the elastic relaunch path (``--elastic_training``
in runner.py → DSElasticAgent). TPU translation: ONE worker process per
host (jax drives every local chip), so the agent's job is environment
setup, supervision, bounded restarts, and signal forwarding:

- exports the jax distributed rendezvous env
  (DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID — consumed by
  comm.init_distributed);
- runs the training command as a child process group;
- forwards SIGTERM (pod preemption) to the child so the in-process
  DSElasticAgent (elasticity/elastic_agent.py) can checkpoint;
- restarts the child up to ``max_restarts`` on nonzero exit (the
  torchelastic worker-group restart), backing off between attempts;
- exports ``DSTPU_HEARTBEAT_FILE`` so the worker's watchdog
  (telemetry/watchdog.py) stamps per-step heartbeats this host's
  operator — and ``dstpu-doctor`` — can read to name a straggler, and
  stamps agent-level status (started/exited/restarting) into the same
  file while no worker is alive.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


class LaunchAgent:
    """Supervise one per-host worker process (reference launch.py main)."""

    def __init__(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 0, restart_backoff_s: float = 5.0,
                 max_backoff_s: float = 60.0,
                 restart_window_s: float = 300.0,
                 heartbeat_file: Optional[str] = None):
        self.cmd = cmd
        self.env = {**os.environ, **(env or {})}
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.max_backoff_s = max_backoff_s
        #: rolling restart budget: only restarts within the last
        #: ``restart_window_s`` seconds count against ``max_restarts`` —
        #: a worker that dies once a day is healthy; one that dies
        #: max_restarts times in five minutes is crash-looping
        self.restart_window_s = restart_window_s
        self._restart_times: List[float] = []
        self.heartbeat_file = heartbeat_file or \
            self.env.get("DSTPU_HEARTBEAT_FILE")
        if self.heartbeat_file:
            # the worker's watchdog picks this up and takes over stamping
            self.env["DSTPU_HEARTBEAT_FILE"] = self.heartbeat_file
        self._child: Optional[subprocess.Popen] = None
        self._terminating = False

    def _beat(self, phase: str, **extra) -> None:
        """Agent-level heartbeat (atomic write, best effort). The worker's
        watchdog overwrites the same file with per-step beats once it is
        up; agent beats cover the gaps (spawn, restart backoff, exit)."""
        if not self.heartbeat_file:
            return
        try:
            doc = {"hostname": socket.gethostname(), "pid": os.getpid(),
                   "agent": True, "phase": phase, "ts": time.time(),
                   **extra}
            parent = os.path.dirname(os.path.abspath(self.heartbeat_file))
            os.makedirs(parent, exist_ok=True)
            tmp = f"{self.heartbeat_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.heartbeat_file)
        except Exception:
            pass

    def _forward(self, signum, _frame) -> None:
        """SIGTERM/SIGINT → forward to the child's process group so the
        worker can checkpoint (reference sigkill_handler — but graceful
        first: preemption gives a drain window)."""
        self._terminating = True
        if self._child and self._child.poll() is None:
            logger.warning(
                f"launch agent: forwarding {signal.Signals(signum).name} "
                f"to worker pid {self._child.pid}")
            try:
                os.killpg(os.getpgid(self._child.pid), signum)
            except ProcessLookupError:
                pass

    def run(self) -> int:
        prev_term = signal.signal(signal.SIGTERM, self._forward)
        prev_int = signal.signal(signal.SIGINT, self._forward)
        try:
            attempt = 0
            while True:
                # chaos hook: lets a fault plan target the supervisor
                # itself (a launcher-scoped hang or preempt)
                from deepspeed_tpu.resilience.faults import fault_injector
                fault_injector.fire("launcher")
                log_dist(f"launch agent: starting worker "
                         f"(attempt {attempt + 1}): "
                         f"{' '.join(self.cmd)}")
                self._child = subprocess.Popen(
                    self.cmd, env=self.env, start_new_session=True)
                self._beat("worker_started", worker_pid=self._child.pid,
                           attempt=attempt)
                rc = self._child.wait()
                self._beat("worker_exited", rc=rc, attempt=attempt)
                if rc == 0 or self._terminating:
                    return rc
                now = time.monotonic()
                self._restart_times = [
                    t for t in self._restart_times
                    if now - t <= self.restart_window_s]
                if len(self._restart_times) >= self.max_restarts:
                    logger.error(
                        f"launch agent: worker failed (rc={rc}) with "
                        f"{len(self._restart_times)} restarts already in "
                        f"the last {self.restart_window_s:.0f}s "
                        f"(budget {self.max_restarts}); giving up")
                    self._beat("crash_loop", rc=rc,
                               restarts_in_window=len(self._restart_times),
                               attempt=attempt)
                    return rc
                self._restart_times.append(now)
                attempt += 1
                delay = min(
                    self.restart_backoff_s *
                    (2 ** (len(self._restart_times) - 1)),
                    self.max_backoff_s)
                logger.warning(
                    f"launch agent: worker rc={rc}; restart "
                    f"{len(self._restart_times)}/{self.max_restarts} "
                    f"(window {self.restart_window_s:.0f}s) in "
                    f"{delay:.1f}s")
                # doctor reads this phase + count to name a crash-looping
                # host from the heartbeat alone
                self._beat("restart_backoff", rc=rc, backoff_s=delay,
                           restarts_in_window=len(self._restart_times),
                           attempt=attempt)
                time.sleep(delay)
                if self._terminating:
                    # SIGTERM landed during the backoff (preemption):
                    # spawning a fresh worker that never saw the signal
                    # would lose the checkpoint window
                    logger.warning("launch agent: termination requested "
                                   "during backoff; not restarting")
                    return rc
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)


class ReplicaPoolAgent:
    """Spawn and supervise a local pool of N serving-replica processes —
    the multi-process backend for the serving router
    (serving/router.py; docs/serving.md "Router, failover & draining").

    Each child runs ``cmd`` with ``DSTPU_REPLICA_NAME=r<i>`` and, when
    ``base_port > 0``, ``DSTPU_HTTP_PORT=base_port+i`` (the replica's
    /metrics + /healthz endpoint the router's breaker polls). Unlike
    :class:`LaunchAgent` this supervisor is poll-driven and installs no
    signal handlers, so it can run off the main thread or embedded in a
    router process; restarts share one rolling per-replica budget so a
    crash-looping replica gives up instead of flapping its breaker
    forever. ``kill(name)`` has chaos semantics: SIGKILL the process
    group and (optionally) leave it down — the router's failover is
    what keeps the streams alive.
    """

    def __init__(self, cmd: List[str], n: int, base_port: int = 0,
                 env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 2, restart_window_s: float = 300.0,
                 heartbeat_dir: Optional[str] = None):
        if n < 1:
            raise ValueError("pool needs at least one replica")
        self.cmd = cmd
        self.names = [f"r{i}" for i in range(n)]
        self.base_port = base_port
        self.env = {**os.environ, **(env or {})}
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        #: one heartbeat JSON per replica under this dir (doctor input)
        self.heartbeat_dir = heartbeat_dir
        self._children: Dict[str, Optional[subprocess.Popen]] = {
            name: None for name in self.names}
        self._restart_times: Dict[str, List[float]] = {
            name: [] for name in self.names}
        #: replicas deliberately downed (kill/stop): never restarted
        self._downed: set = set()
        #: replicas in graceful scale-down: SIGTERM only lands after the
        #: router has drained them; heartbeats read ``draining`` so
        #: dstpu-top/doctor never mistake an intentional shrink for a
        #: crash loop
        self._draining: set = set()
        self.restarts = 0
        self._next_idx = n

    def _beat(self, name: str, phase: str, **extra) -> None:
        """Per-replica agent heartbeat (atomic write, best effort) —
        the LaunchAgent._beat contract, one file per replica under
        ``heartbeat_dir``."""
        if not self.heartbeat_dir:
            return
        try:
            doc = {"hostname": socket.gethostname(), "pid": os.getpid(),
                   "agent": True, "replica": name, "phase": phase,
                   "ts": time.time(), **extra}
            os.makedirs(self.heartbeat_dir, exist_ok=True)
            path = os.path.join(self.heartbeat_dir, f"{name}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)
        except Exception:
            pass

    def _spawn(self, name: str) -> subprocess.Popen:
        i = self.names.index(name)
        env = dict(self.env)
        env["DSTPU_REPLICA_NAME"] = name
        if self.base_port > 0:
            env["DSTPU_HTTP_PORT"] = str(self.base_port + i)
        child = subprocess.Popen(self.cmd, env=env, start_new_session=True)
        self._children[name] = child
        log_dist(f"replica pool: started {name} pid={child.pid}" +
                 (f" port={self.base_port + i}" if self.base_port else ""))
        return child

    def start(self) -> "ReplicaPoolAgent":
        for name in self.names:
            self._spawn(name)
        return self

    def targets(self) -> List[str]:
        """Scrape targets for a Router / dstpu-top over this pool."""
        if self.base_port <= 0:
            return []
        return [f"127.0.0.1:{self.base_port + i}"
                for i in range(len(self.names))]

    def poll(self) -> Dict[str, str]:
        """One supervision sweep: restart dead replicas inside their
        rolling budget; returns per-replica phase (``running`` |
        ``restarting`` | ``down`` | ``crash_loop`` | ``draining``).
        A draining replica is NEVER restarted — it is leaving on
        purpose; if it dies mid-drain (chaos) it is simply down and the
        router's failover owns its streams."""
        phases: Dict[str, str] = {}
        now = time.monotonic()
        for name, child in list(self._children.items()):
            if name in self._draining:
                if child is not None and child.poll() is not None:
                    self._draining.discard(name)
                    self._downed.add(name)
                    phases[name] = "down"
                    self._beat(name, "down", rc=child.returncode)
                else:
                    phases[name] = "draining"
                    self._beat(name, "draining")
                continue
            if name in self._downed:
                phases[name] = "down"
                continue
            if child is not None and child.poll() is None:
                phases[name] = "running"
                continue
            times = self._restart_times[name] = [
                t for t in self._restart_times[name]
                if now - t <= self.restart_window_s]
            if len(times) >= self.max_restarts:
                phases[name] = "crash_loop"
                self._beat(name, "crash_loop",
                           restarts_in_window=len(times))
                continue
            rc = child.returncode if child is not None else None
            logger.warning(f"replica pool: {name} exited rc={rc}; "
                           f"restart {len(times) + 1}/{self.max_restarts}")
            times.append(now)
            self.restarts += 1
            self._spawn(name)
            phases[name] = "restarting"
            self._beat(name, "restarting", rc=rc,
                       restarts_in_window=len(times))
        return phases

    # -- elastic scale-up / scale-down --------------------------------------

    def add_replica(self) -> str:
        """Scale-up: spawn one more replica and return its name (the
        autoscaler's ``spawn_fn`` seam for process pools). Names never
        recycle — ``r<next>`` keeps doctor timelines unambiguous."""
        name = f"r{self._next_idx}"
        self._next_idx += 1
        self.names.append(name)
        self._children[name] = None
        self._restart_times[name] = []
        self._spawn(name)
        self._beat(name, "running")
        return name

    def begin_drain(self, name: str) -> None:
        """Mark ``name`` as gracefully scaling down (the autoscaler's
        ``drain_fn`` seam). The process keeps running — the router is
        still finishing or failing over its streams — but heartbeats
        and :meth:`poll` read ``draining``, and only
        :meth:`finish_drain` / :meth:`stop` send the SIGTERM."""
        if name not in self._children:
            raise KeyError(f"no replica named {name!r}")
        if name in self._downed:
            return
        self._draining.add(name)
        self._beat(name, "draining")

    def finish_drain(self, name: str, grace_s: float = 5.0) -> None:
        """Complete a scale-down: the router drained ``name`` (no
        streams assigned, KV released) — now SIGTERM its process group,
        escalating to SIGKILL past ``grace_s``. The slot stays down."""
        if name not in self._draining:
            raise KeyError(f"{name!r} is not draining")
        self._draining.discard(name)
        self._downed.add(name)
        child = self._children.get(name)
        self._beat(name, "down", drained=True)
        if child is None or child.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(child.pid), signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            child.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(child.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
            child.wait()

    def kill(self, name: str, restart: bool = False) -> None:
        """SIGKILL one replica's process group (chaos ``replica_kill``
        at process scope). ``restart=True`` lets the next :meth:`poll`
        bring it back (counts against the rolling budget)."""
        child = self._children.get(name)
        if child is None:
            raise KeyError(f"no replica named {name!r}")
        if not restart:
            self._downed.add(name)
        if child.poll() is None:
            try:
                os.killpg(os.getpgid(child.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
            child.wait()

    def stop(self, grace_s: float = 5.0,
             drain: Optional[Callable[[str], None]] = None) -> None:
        """Stop the pool with drain-before-SIGTERM ordering: every live
        replica is marked ``draining`` first (heartbeats say so, not
        ``crash_loop``), the ``drain`` callback — typically
        ``router.drain`` — gets each name so in-flight streams finish
        or fail over, and only then does SIGTERM land (SIGKILL for
        stragglers past ``grace_s``)."""
        for name, child in self._children.items():
            if name in self._downed or child is None or \
                    child.poll() is not None:
                continue
            self._draining.add(name)
            self._beat(name, "draining")
            if drain is not None:
                try:
                    drain(name)
                except Exception as e:
                    logger.warning(f"replica pool: drain callback for "
                                   f"{name} failed: {e}")
        self._downed.update(self.names)
        self._draining.clear()
        live = [c for c in self._children.values()
                if c is not None and c.poll() is None]
        for c in live:
            try:
                os.killpg(os.getpgid(c.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = time.monotonic() + grace_s
        for c in live:
            try:
                c.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(c.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
                c.wait()
        for name in self.names:
            self._beat(name, "down", stopped=True)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m deepspeed_tpu.launcher.agent -- cmd args...``
    with rendezvous env passed through (spawned over ssh by
    launcher/runner.py on each host). ``--pool N`` supervises N serving
    replicas of the command instead (each with DSTPU_REPLICA_NAME and,
    with ``--base-port``, its own DSTPU_HTTP_PORT)."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-restarts", type=int,
                    default=int(os.environ.get("DSTPU_MAX_RESTARTS", 0)))
    ap.add_argument("--heartbeat-file", default=None,
                    help="per-host heartbeat JSON for dstpu-doctor "
                         "straggler naming (default: env "
                         "DSTPU_HEARTBEAT_FILE)")
    ap.add_argument("--pool", type=int, default=0, metavar="N",
                    help="supervise N serving-replica copies of the "
                         "command instead of one worker")
    ap.add_argument("--base-port", type=int, default=0,
                    help="with --pool: replica i serves /metrics on "
                         "base_port+i (DSTPU_HTTP_PORT)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("usage: agent.py [--max-restarts N] [--heartbeat-file F] "
              "[--pool N [--base-port P]] -- prog args...",
              file=sys.stderr)
        return 2
    if args.pool:
        pool = ReplicaPoolAgent(
            cmd, args.pool, base_port=args.base_port,
            max_restarts=args.max_restarts or 2).start()
        try:
            while True:
                phases = pool.poll()
                if all(p in ("down", "crash_loop")
                       for p in phases.values()):
                    logger.error(f"replica pool: no replica left "
                                 f"restartable ({phases}); exiting")
                    return 1
                time.sleep(1.0)
        except KeyboardInterrupt:
            return 0
        finally:
            pool.stop()
    return LaunchAgent(cmd, max_restarts=args.max_restarts,
                       heartbeat_file=args.heartbeat_file).run()


if __name__ == "__main__":
    sys.exit(main())
