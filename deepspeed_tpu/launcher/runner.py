"""Launcher — multi-host TPU-pod job runner.

Reference: ``bin/deepspeed`` → ``launcher/runner.py`` (main:436, hostfile
parsing:230–308) → per-node ``launcher/launch.py``:145. TPU translation:
one process per HOST (not per chip — jax drives all local chips), the
rendezvous is ``jax.distributed.initialize`` instead of
torch.distributed, and remote spawn uses ssh (the PDSH/MPI runner family
of multinode_runner.py collapses to one ssh runner because TPU pods are
homogeneous by construction).

Single-host: exec the script in-process env. Multi-host: parse a
hostfile (same ``hostname slots=N`` grammar as the reference), export
DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID and ssh-spawn
``launch.py`` per host.
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path: str) -> Dict[str, int]:
    """Reference runner.py:_parse_hostfile:243 — 'host slots=N' lines."""
    hosts: Dict[str, int] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            if name in hosts:
                raise ValueError(f"duplicate host {name} in hostfile")
            hosts[name] = slots
    if not hosts:
        raise ValueError(f"empty hostfile {path}")
    return hosts


def filter_hosts(hosts: Dict[str, int], include: str = "",
                 exclude: str = "") -> Dict[str, int]:
    """Reference include/exclude filters (runner.py:310–399), host-level
    subset (slot-level filtering is meaningless when one process drives
    all local chips)."""
    out = dict(hosts)
    if include:
        names = set(include.split("@"))
        out = {h: s for h, s in out.items() if h in names}
    if exclude:
        names = set(exclude.split("@"))
        out = {h: s for h, s in out.items() if h not in names}
    if not out:
        raise ValueError("no hosts left after include/exclude filtering")
    return out


def build_launch_env(coordinator: str, num_processes: int, process_id: int,
                     base_env: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env["DSTPU_COORDINATOR"] = coordinator
    env["DSTPU_NUM_PROCESSES"] = str(num_processes)
    env["DSTPU_PROCESS_ID"] = str(process_id)
    return env


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu launcher")
    ap.add_argument("--hostfile", default=None)
    ap.add_argument("--include", default="", help="host[@host...] to keep")
    ap.add_argument("--exclude", default="", help="host[@host...] to drop")
    ap.add_argument("--master_addr", default=None)
    ap.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    ap.add_argument("--ssh_port", type=int, default=22)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    cmd = [sys.executable, args.script, *args.script_args]

    if args.hostfile is None:
        # single host: exec in place (reference launch.py single-node path)
        os.execvpe(cmd[0], cmd, dict(os.environ))

    hosts = filter_hosts(parse_hostfile(args.hostfile), args.include,
                         args.exclude)
    names = list(hosts)
    coord = f"{args.master_addr or names[0]}:{args.master_port}"
    procs: List[subprocess.Popen] = []

    def _kill(*_):
        # reference sigkill_handler (runner.py:633): tear the tree down
        for p in procs:
            p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)

    for idx, host in enumerate(names):
        env_exports = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in [
                ("DSTPU_COORDINATOR", coord),
                ("DSTPU_NUM_PROCESSES", str(len(names))),
                ("DSTPU_PROCESS_ID", str(idx)),
            ])
        remote = f"cd {shlex.quote(os.getcwd())} && {env_exports} " + \
            " ".join(shlex.quote(c) for c in cmd)
        procs.append(subprocess.Popen(
            ["ssh", "-p", str(args.ssh_port), host, remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
