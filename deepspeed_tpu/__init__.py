"""deepspeed_tpu — a TPU-native large-scale training & inference framework.

Provides the capabilities of DeepSpeed (reference: deepspeed/__init__.py —
``initialize``:78, ``init_inference``:302) re-designed for TPU: SPMD over a
``jax.sharding.Mesh``, ZeRO as sharding layouts, XLA collectives over
ICI/DCN, Pallas kernels for hot ops.
"""

from deepspeed_tpu.version import __version__
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.config import AUTO, DeepSpeedTPUConfig  # noqa: F401
from deepspeed_tpu.parallel.mesh import build_mesh, get_mesh, mesh_from_config  # noqa: F401

__all__ = ["__version__", "DeepSpeedTPUConfig", "AUTO", "build_mesh",
           "get_mesh", "mesh_from_config", "comm", "initialize"]


def initialize(*args, **kwargs):
    """Create a training engine (reference deepspeed/__init__.py:78).

    Deferred import so config/comm utilities stay importable without
    triggering engine deps.
    """
    from deepspeed_tpu.runtime.engine import initialize as _initialize
    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Create an inference engine (reference deepspeed/__init__.py:302)."""
    from deepspeed_tpu.inference.engine import init_inference as _init_inference
    return _init_inference(*args, **kwargs)
