"""deepspeed_tpu — a TPU-native large-scale training & inference framework.

Provides the capabilities of DeepSpeed (reference: deepspeed/__init__.py —
``initialize``:78, ``init_inference``:302) re-designed for TPU: SPMD over a
``jax.sharding.Mesh``, ZeRO as sharding layouts, XLA collectives over
ICI/DCN, Pallas kernels for hot ops.
"""

from deepspeed_tpu.utils import jax_compat as _jax_compat

_jax_compat.install()

from deepspeed_tpu.version import __version__
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.config import AUTO, DeepSpeedTPUConfig  # noqa: F401
from deepspeed_tpu.parallel.mesh import build_mesh, get_mesh, mesh_from_config  # noqa: F401

__all__ = ["__version__", "DeepSpeedTPUConfig", "AUTO", "build_mesh",
           "get_mesh", "mesh_from_config", "comm", "initialize",
           "init_inference", "add_config_arguments",
           "default_inference_config", "tp_model_init"]


def add_config_arguments(parser):
    """Add the framework's CLI arguments to an argparse parser
    (reference deepspeed/__init__.py:279 ``add_config_arguments`` /
    ``_add_core_arguments``:240 — same flag names so launch scripts
    port unchanged; the deprecated --deepscale aliases are accepted
    too)."""
    group = parser.add_argument_group("DeepSpeed",
                                      "deepspeed_tpu configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable the engine (helper flag for user "
                            "code, no impact on the backend)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="json configuration file for "
                            "deepspeed_tpu.initialize()")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser


def default_inference_config():
    """Default inference configuration dict (reference
    deepspeed/__init__.py:295)."""
    from deepspeed_tpu.inference.engine import DeepSpeedTPUInferenceConfig
    return DeepSpeedTPUInferenceConfig().model_dump()


def tp_model_init(model, tp_size, dtype, config=None, rng=None):
    """Initialize a model tensor-parallel (reference
    deepspeed/__init__.py:380 ``tp_model_init`` — there it wraps a
    torch module in the TpTrainingManager; here the model IS a config +
    params pytree, so this builds/validates a mesh with a ``tp_size``
    model axis and jit-initializes the params with TP ``out_shardings``
    so they never materialize unsharded).

    ``config`` is accepted for reference-signature parity only (the
    reference reads kernel-injection knobs from it that have no TPU
    analogue) and is ignored with a warning when set. Returns
    ``(params, mesh)``.
    """
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import transformer
    from deepspeed_tpu.parallel.mesh import get_mesh, has_mesh
    from deepspeed_tpu.utils.logging import logger
    from jax.sharding import NamedSharding

    if config is not None:
        logger.warning(
            "tp_model_init: config is a reference-parity argument and "
            "is ignored; pass the dict to deepspeed_tpu.initialize()")
    if has_mesh():
        mesh = get_mesh()
        if mesh.shape.get("model", 1) != tp_size:
            raise ValueError(
                f"tp_model_init(tp_size={tp_size}) conflicts with the "
                f"live mesh (model axis {mesh.shape.get('model', 1)}); "
                "build_mesh(model=tp_size, ...) with your full topology "
                "first — silently replacing the process mesh would "
                "invalidate it for everything else")
    else:
        mesh = build_mesh(model=tp_size)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    specs = transformer.partition_specs(model, zero_stage=0, tp=tp_size > 1)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda s: not isinstance(s, dict))
    jdt = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
           "float32": jnp.float32, "fp32": jnp.float32,
           "float16": jnp.float16, "fp16": jnp.float16}.get(str(dtype),
                                                            dtype)
    init = jax.jit(
        lambda r: jax.tree.map(
            lambda a: a.astype(jdt)
            if jnp.issubdtype(a.dtype, jnp.floating) else a,
            transformer.init_params(model, r)),
        out_shardings=shardings)
    return init(rng), mesh


def initialize(*args, **kwargs):
    """Create a training engine (reference deepspeed/__init__.py:78).

    Deferred import so config/comm utilities stay importable without
    triggering engine deps.
    """
    from deepspeed_tpu.runtime.engine import initialize as _initialize
    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Create an inference engine (reference deepspeed/__init__.py:302)."""
    from deepspeed_tpu.inference.engine import init_inference as _init_inference
    return _init_inference(*args, **kwargs)
